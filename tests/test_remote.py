"""Remote model loading (api/remote.py): http(s) fetch with validated
local cache, gated cloud schemes, and dynamic serving over remote paths
(SURVEY.md §1 C1 / §3 B3; VERDICT r1 #6)."""

import http.server
import os
import pathlib
import threading
import time

import numpy as np
import pytest

from flink_jpmml_tpu.api import remote
from flink_jpmml_tpu.api.reader import ModelReader, clear_model_cache
from flink_jpmml_tpu.utils.exceptions import ModelLoadingException

_CONST_XML = """<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
  <Header/>
  <DataDictionary numberOfFields="2">
    <DataField name="a" optype="continuous" dataType="double"/>
    <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <RegressionModel functionName="regression">
    <MiningSchema>
      <MiningField name="y" usageType="target"/>
      <MiningField name="a"/>
    </MiningSchema>
    <RegressionTable intercept="{c}">
      <NumericPredictor name="a" coefficient="0.5"/>
    </RegressionTable>
  </RegressionModel></PMML>"""


class _CountingHandler(http.server.SimpleHTTPRequestHandler):
    stats = {"GET": 0, "304": 0}

    def log_message(self, *a):
        pass

    def do_GET(self):
        type(self).stats["GET"] += 1
        super().do_GET()

    def send_response(self, code, *a, **kw):
        if code == 304:
            type(self).stats["304"] += 1
        super().send_response(code, *a, **kw)


@pytest.fixture()
def http_root(tmp_path, monkeypatch):
    monkeypatch.setenv("FJT_MODEL_CACHE", str(tmp_path / "cache"))
    clear_model_cache()
    docroot = tmp_path / "www"
    docroot.mkdir()
    handler = type(
        "Handler", (_CountingHandler,), {"stats": {"GET": 0, "304": 0}}
    )
    srv = http.server.ThreadingHTTPServer(
        ("127.0.0.1", 0),
        lambda *a, **kw: handler(*a, directory=str(docroot), **kw),
    )
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield docroot, f"http://127.0.0.1:{srv.server_address[1]}", handler
    finally:
        srv.shutdown()
        srv.server_close()


class TestHttpFetch:
    def test_load_scores_like_local(self, http_root):
        docroot, base, _h = http_root
        (docroot / "m.pmml").write_text(_CONST_XML.format(c=1.5))
        cm = ModelReader(f"{base}/m.pmml").load(batch_size=4)
        [pred] = cm.score_records([{"a": 2.0}])
        assert pred.score.value == pytest.approx(1.5 + 0.5 * 2.0)

    def test_revalidation_not_redownload(self, http_root):
        docroot, base, h = http_root
        (docroot / "m.pmml").write_text(_CONST_XML.format(c=1.0))
        uri = f"{base}/m.pmml"
        m1 = ModelReader(uri).load(batch_size=4)
        gets_after_first = h.stats["GET"]
        m2 = ModelReader(uri).load(batch_size=4)
        # second load revalidated (304) and reused the compiled model
        assert m2 is m1
        assert h.stats["GET"] == gets_after_first + 1
        assert h.stats["304"] >= 1

    def test_changed_remote_model_recompiles(self, http_root):
        docroot, base, _h = http_root
        p = docroot / "m.pmml"
        p.write_text(_CONST_XML.format(c=1.0))
        uri = f"{base}/m.pmml"
        m1 = ModelReader(uri).load(batch_size=4)
        p.write_text(_CONST_XML.format(c=9.0))
        # Last-Modified has 1s resolution: push the mtime forward
        future = time.time() + 5
        os.utime(p, (future, future))
        m2 = ModelReader(uri).load(batch_size=4)
        assert m2 is not m1
        [pred] = m2.score_records([{"a": 0.0}])
        assert pred.score.value == pytest.approx(9.0)

    def test_stale_cache_serves_through_outage(self, http_root):
        docroot, base, _h = http_root
        (docroot / "m.pmml").write_text(_CONST_XML.format(c=3.0))
        uri = f"{base}/m.pmml"
        local, tok1 = remote.fetch(uri)
        assert pathlib.Path(local).exists()
        # an unreachable host with no cached copy is a typed error…
        dead = "http://127.0.0.1:1/m.pmml"
        with pytest.raises(ModelLoadingException):
            remote.fetch(dead)
        # …but with a pre-seeded cache entry the stale disk copy serves
        # through the outage (DFS-blip parity)
        import hashlib, json, shutil

        stem_dead = hashlib.sha256(dead.encode()).hexdigest()[:32]
        cdir = remote.cache_dir()
        shutil.copy(local, os.path.join(cdir, stem_dead + ".pmml"))
        with open(os.path.join(cdir, stem_dead + ".meta"), "w") as f:
            json.dump({"etag": "x", "uri": dead}, f)
        local2, tok2 = remote.fetch(dead)
        assert pathlib.Path(local2).read_text() == pathlib.Path(local).read_text()


class TestGatedSchemes:
    @pytest.mark.slow
    def test_gs_unusable_is_typed_error(self, monkeypatch, tmp_path):
        # google-cloud-storage may or may not be installed; either a
        # missing dep or missing credentials must surface as the typed
        # loading error, never an ImportError/credentials traceback
        monkeypatch.setenv("FJT_MODEL_CACHE", str(tmp_path))
        with pytest.raises(ModelLoadingException):
            remote.fetch("gs://bucket/model.pmml")

    def test_s3_without_dep_is_typed_error(self, monkeypatch, tmp_path):
        monkeypatch.setenv("FJT_MODEL_CACHE", str(tmp_path))
        with pytest.raises(ModelLoadingException, match="boto3"):
            remote.fetch("s3://bucket/model.pmml")

    def test_file_scheme_and_bare_paths_pass_through(self, tmp_path):
        p = tmp_path / "m.pmml"
        p.write_text(_CONST_XML.format(c=1.0))
        local, _ = remote.fetch(f"file://{p}")
        assert local == str(p)
        local2, _ = remote.fetch(str(p))
        assert local2 == str(p)


class TestDynamicServingRemote:
    def test_add_with_remote_path_serves(self, http_root):
        from flink_jpmml_tpu.models.control import AddMessage
        from flink_jpmml_tpu.runtime.sources import ControlSource
        from flink_jpmml_tpu.serving.scorer import DynamicScorer

        docroot, base, _h = http_root
        (docroot / "served.pmml").write_text(_CONST_XML.format(c=7.0))
        ctrl = ControlSource()
        sc = DynamicScorer(control=ctrl, batch_size=4)
        ctrl.push(
            AddMessage("rm", 1, f"{base}/served.pmml", timestamp=1.0)
        )
        out = sc.finish(sc.submit([("rm", {"a": 2.0})]))
        (p, _e) = out[0]
        assert p.score.value == pytest.approx(7.0 + 0.5 * 2.0)


class _WebHdfsHandler(http.server.BaseHTTPRequestHandler):
    """Minimal WebHDFS NameNode stub: GETFILESTATUS + OPEN over one
    in-memory file, counting operations."""

    content = b""
    mtime = 1000
    stats = {"status": 0, "open": 0}

    def log_message(self, *a):
        pass

    def do_GET(self):
        cls = type(self)
        if "op=GETFILESTATUS" in self.path:
            cls.stats["status"] += 1
            body = (
                '{"FileStatus": {"modificationTime": %d, "length": %d, '
                '"type": "FILE"}}' % (cls.mtime, len(cls.content))
            ).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif "op=OPEN" in self.path:
            cls.stats["open"] += 1
            self.send_response(200)
            self.send_header("Content-Length", str(len(cls.content)))
            self.end_headers()
            self.wfile.write(cls.content)
        else:
            self.send_response(400)
            self.end_headers()


@pytest.fixture()
def webhdfs(tmp_path, monkeypatch):
    monkeypatch.setenv("FJT_MODEL_CACHE", str(tmp_path / "cache"))
    _WebHdfsHandler.content = _CONST_XML.format(c=4.0).encode()
    _WebHdfsHandler.mtime = 1000
    _WebHdfsHandler.stats = {"status": 0, "open": 0}
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _WebHdfsHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv.server_address[1]
    srv.shutdown()


class TestHdfsFetch:
    def test_webhdfs_fetch_and_score(self, webhdfs):
        clear_model_cache()
        uri = f"hdfs://127.0.0.1:{webhdfs}/models/const.pmml"
        m = ModelReader(uri).load()
        p = m.score_records([{"a": 2.0}])[0]
        assert p.score.value == pytest.approx(5.0)
        assert _WebHdfsHandler.stats == {"status": 1, "open": 1}

    def test_unchanged_file_revalidates_without_download(self, webhdfs):
        clear_model_cache()
        uri = f"hdfs://127.0.0.1:{webhdfs}/models/const.pmml"
        remote.fetch(uri)
        remote.fetch(uri)
        assert _WebHdfsHandler.stats["status"] == 2
        assert _WebHdfsHandler.stats["open"] == 1  # cache hit, no re-read

    def test_changed_mtime_redownloads(self, webhdfs):
        clear_model_cache()
        uri = f"hdfs://127.0.0.1:{webhdfs}/models/const.pmml"
        _, tok1 = remote.fetch(uri)
        _WebHdfsHandler.content = _CONST_XML.format(c=9.0).encode()
        _WebHdfsHandler.mtime = 2000
        local, tok2 = remote.fetch(uri)
        assert tok1 != tok2
        assert _WebHdfsHandler.stats["open"] == 2
        assert b"9.0" in pathlib.Path(local).read_bytes()

    def test_outage_serves_stale_with_warning(self, webhdfs):
        clear_model_cache()
        uri = f"hdfs://127.0.0.1:{webhdfs}/models/const.pmml"
        local, _ = remote.fetch(uri)
        # unreachable port: stale cache + RuntimeWarning
        dead = f"hdfs://127.0.0.1:1/models/const.pmml"
        with pytest.warns(RuntimeWarning, match="stale"):
            # seed the dead URI's cache entry by copying the good one
            lp, _ = remote._cache_paths(dead)
            pathlib.Path(lp).write_bytes(pathlib.Path(local).read_bytes())
            got, tok = remote.fetch(dead, timeout_s=0.5)
        assert got == lp and tok == "stale"

    def test_unreachable_without_cache_typed_error(self, tmp_path,
                                                   monkeypatch):
        monkeypatch.setenv("FJT_MODEL_CACHE", str(tmp_path / "c2"))
        with pytest.raises(ModelLoadingException, match="cannot fetch"):
            remote.fetch("hdfs://127.0.0.1:1/nope.pmml", timeout_s=0.5)


class TestHdfsPortResolution:
    def test_rpc_port_maps_to_rest_default(self, monkeypatch):
        # hdfs://nn:8020/... must NOT speak HTTP at 8020; with no env
        # override it targets the REST default — unreachable here, and
        # with no cache that is a typed error mentioning the REST port
        monkeypatch.setenv("FJT_MODEL_CACHE", "/tmp/fjt-nonexistent-cache-x")
        with pytest.raises(ModelLoadingException, match="cannot fetch"):
            remote.fetch("hdfs://127.0.0.1:8020/m.pmml", timeout_s=0.3)

    def test_env_override_always_wins(self, webhdfs, monkeypatch):
        clear_model_cache()
        monkeypatch.setenv("FJT_WEBHDFS_PORT", str(webhdfs))
        # URI carries the RPC port; the env override routes to the stub
        local, tok = remote.fetch(
            f"hdfs://127.0.0.1:8020/models/const.pmml"
        )
        assert pathlib.Path(local).exists() and tok

    def test_bad_ports_typed_errors(self, monkeypatch, tmp_path):
        monkeypatch.setenv("FJT_MODEL_CACHE", str(tmp_path))
        with pytest.raises(ModelLoadingException, match="port"):
            remote.fetch("hdfs://nn:80x0/m.pmml", timeout_s=0.3)
        monkeypatch.setenv("FJT_WEBHDFS_PORT", "default")
        with pytest.raises(ModelLoadingException, match="port"):
            remote.fetch("hdfs://nn/m.pmml", timeout_s=0.3)


class _AlluxioHandler(http.server.BaseHTTPRequestHandler):
    """Minimal Alluxio proxy REST stub (v1): get-status / open-file /
    streams read+close over one in-memory file, counting operations."""

    content = b""
    mtime_ms = 1000
    stats = {"status": 0, "open": 0, "read": 0, "close": 0}

    def log_message(self, *a):
        pass

    def do_POST(self):
        cls = type(self)

        def reply(body: bytes, ctype="application/json"):
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        if self.path.endswith("/get-status"):
            cls.stats["status"] += 1
            reply(
                b'{"lastModificationTimeMs": %d, "length": %d, '
                b'"folder": false}' % (cls.mtime_ms, len(cls.content))
            )
        elif self.path.endswith("/open-file"):
            cls.stats["open"] += 1
            reply(b"7")  # stream id
        elif self.path.endswith("/streams/7/read"):
            cls.stats["read"] += 1
            reply(cls.content, ctype="application/octet-stream")
        elif self.path.endswith("/streams/7/close"):
            cls.stats["close"] += 1
            reply(b"")
        else:
            self.send_response(400)
            self.end_headers()


@pytest.fixture()
def alluxio(tmp_path, monkeypatch):
    monkeypatch.setenv("FJT_MODEL_CACHE", str(tmp_path / "cache"))
    _AlluxioHandler.content = _CONST_XML.format(c=4.0).encode()
    _AlluxioHandler.mtime_ms = 1000
    _AlluxioHandler.stats = {"status": 0, "open": 0, "read": 0, "close": 0}
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _AlluxioHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv.server_address[1]
    srv.shutdown()


class TestAlluxioFetch:
    def test_fetch_and_score(self, alluxio):
        clear_model_cache()
        uri = f"alluxio://127.0.0.1:{alluxio}/models/const.pmml"
        m = ModelReader(uri).load()
        p = m.score_records([{"a": 2.0}])[0]
        assert p.score.value == pytest.approx(5.0)
        assert _AlluxioHandler.stats == {
            "status": 1, "open": 1, "read": 1, "close": 1,
        }

    def test_unchanged_file_revalidates_without_download(self, alluxio):
        clear_model_cache()
        uri = f"alluxio://127.0.0.1:{alluxio}/models/const.pmml"
        remote.fetch(uri)
        remote.fetch(uri)
        assert _AlluxioHandler.stats["status"] == 2
        assert _AlluxioHandler.stats["read"] == 1  # cache hit, no re-read

    def test_changed_mtime_redownloads(self, alluxio):
        clear_model_cache()
        uri = f"alluxio://127.0.0.1:{alluxio}/models/const.pmml"
        _, tok1 = remote.fetch(uri)
        _AlluxioHandler.content = _CONST_XML.format(c=9.0).encode()
        _AlluxioHandler.mtime_ms = 2000
        local, tok2 = remote.fetch(uri)
        assert tok1 != tok2
        assert b"9.0" in pathlib.Path(local).read_bytes()

    def test_outage_serves_stale_with_warning(self, alluxio):
        clear_model_cache()
        uri = f"alluxio://127.0.0.1:{alluxio}/models/const.pmml"
        local, _ = remote.fetch(uri)
        dead = "alluxio://127.0.0.1:1/models/const.pmml"
        with pytest.warns(RuntimeWarning, match="stale"):
            lp, _ = remote._cache_paths(dead)
            pathlib.Path(lp).write_bytes(pathlib.Path(local).read_bytes())
            got, tok = remote.fetch(dead, timeout_s=0.5)
        assert got == lp and tok == "stale"

    def test_rpc_port_maps_to_proxy_default(self, monkeypatch, tmp_path):
        # alluxio://master:19998/... must NOT speak HTTP at the RPC port
        monkeypatch.setenv("FJT_MODEL_CACHE", str(tmp_path / "c3"))
        with pytest.raises(ModelLoadingException, match="cannot fetch"):
            remote.fetch("alluxio://127.0.0.1:19998/m.pmml", timeout_s=0.3)

    def test_env_override_always_wins(self, alluxio, monkeypatch):
        clear_model_cache()
        monkeypatch.setenv("FJT_ALLUXIO_PORT", str(alluxio))
        local, tok = remote.fetch(
            "alluxio://127.0.0.1:19998/models/const.pmml"
        )
        assert pathlib.Path(local).exists() and tok
