"""Rollout control plane (flink_jpmml_tpu/rollout/): staged traffic
splits, shadow scoring, guardrail-driven auto-promotion/rollback, and
checkpoint durability.

The pinned end-to-end drills:
- a candidate with injected +latency (and, separately, disagreement) is
  auto-rolled-back under canary — the incumbent keeps serving and the
  flight recorder holds the decision event;
- a healthy candidate auto-promotes shadow → canary → full with a
  per-key-stable split at each stage;
- a checkpoint restore mid-canary resumes the same stage and the
  identical split;
- a registry restore while a background warm is mid-compile neither
  double-compiles nor serves a cold model.
"""

import pathlib
import time

import pytest

from flink_jpmml_tpu.models.control import (
    AddMessage,
    RolloutMessage,
    from_wire,
    to_wire,
)
from flink_jpmml_tpu.models.core import ModelId
from flink_jpmml_tpu.obs import recorder as flight
from flink_jpmml_tpu.rollout import split as rsplit
from flink_jpmml_tpu.rollout.controller import _hist_window
from flink_jpmml_tpu.rollout.state import (
    GuardrailSpec,
    RolloutState,
    apply_rollout,
)
from flink_jpmml_tpu.runtime.sources import ControlSource
from flink_jpmml_tpu.serving.registry import ModelRegistry
from flink_jpmml_tpu.serving.scorer import DynamicScorer
from flink_jpmml_tpu.utils.metrics import Histogram

_CONST_XML = """<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
  <Header/>
  <DataDictionary numberOfFields="2">
    <DataField name="a" optype="continuous" dataType="double"/>
    <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <RegressionModel functionName="regression">
    <MiningSchema>
      <MiningField name="y" usageType="target"/>
      <MiningField name="a"/>
    </MiningSchema>
    <RegressionTable intercept="{c}"/>
  </RegressionModel></PMML>"""


def _write_const(tmp_path, name, c):
    p = pathlib.Path(tmp_path, name)
    p.write_text(_CONST_XML.format(c=c))
    return str(p)


def _wait_warm(reg, mid, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if reg.model_if_warm(mid) is not None:
            return
        err = reg.warm_error(mid)
        assert err is None, f"warm of {mid} failed: {err!r}"
        time.sleep(0.01)
    raise AssertionError(f"{mid} never warmed")


def _values(results):
    return [p.score.value if not p.is_empty else None for p, _ in results]


def _events(name, n, start=0):
    return [
        (name, {"a": 0.0, "_key": f"k{start + i}"}) for i in range(n)
    ]


def _run(sc, events, batch=64):
    out = []
    for off in range(0, len(events), batch):
        out += sc.finish(sc.submit(events[off : off + batch]))
    return out


class _SlowOut:
    """A dispatch result whose readiness wait carries injected latency
    (the dispatcher blocks on leaves' ``block_until_ready``, so the
    delay lands exactly where a slow device would put it)."""

    def __init__(self, out, delay):
        self.out = out
        self._delay = delay

    def block_until_ready(self):
        time.sleep(self._delay)


class _SlowModel:
    """CompiledModel wrapper adding +delay to every dispatch — the
    "miscompiled, slow" candidate of the rollback drill."""

    def __init__(self, inner, delay):
        self._inner = inner
        self._delay = delay

    def quantized_scorer(self):
        return None

    @property
    def field_space(self):
        return self._inner.field_space

    @property
    def batch_size(self):
        return self._inner.batch_size

    def warmup(self):
        return self._inner.warmup()

    def predict(self, X, M):
        return _SlowOut(self._inner.predict(X, M), self._delay)

    def decode(self, out, n):
        return self._inner.decode(out.out, n)


def _inject_slow(reg, substr, delay_s):
    """Models whose path contains ``substr`` gain +delay per dispatch."""
    orig = reg._load

    def load(info):
        cm = orig(info)
        if substr in info.path:
            return _SlowModel(cm, delay_s)
        return cm

    reg._load = load


class TestSplit:
    def test_per_key_stable_and_monotone(self):
        keys = [f"u{i}" for i in range(4000)]
        a10 = [rsplit.assign_candidate("m", 2, 0.1, k) for k in keys]
        assert a10 == [rsplit.assign_candidate("m", 2, 0.1, k) for k in keys]
        share = sum(a10) / len(a10)
        assert abs(share - 0.1) < 0.02
        # growing the canary never reassigns a candidate key back
        a30 = [rsplit.assign_candidate("m", 2, 0.3, k) for k in keys]
        assert all(b or not a for a, b in zip(a10, a30))
        # a new candidate version canaries a different key population
        b10 = [rsplit.assign_candidate("m", 3, 0.1, k) for k in keys]
        assert a10 != b10

    def test_content_addressed_keys(self):
        rec = {"a": 1.5, "b": "x"}
        assert rsplit.record_key(dict(rec)) == rsplit.record_key(
            {"b": "x", "a": 1.5}
        )
        assert rsplit.record_key({"_key": "s1", "a": 1.0}) == "s1"


class TestTransitions:
    def test_stage_change_resets_dwell_knob_turn_keeps_it(self):
        m1 = RolloutMessage("m", 2, "shadow", 10.0)
        states, ch = apply_rollout({}, m1)
        assert ch and states["m"].stage_since == 10.0
        # knob turn: same stage, new fraction — dwell preserved
        m2 = RolloutMessage("m", 2, "shadow", 50.0, fraction=0.5)
        states, ch = apply_rollout(states, m2)
        assert ch and states["m"].stage_since == 10.0
        # stage change: dwell resets
        m3 = RolloutMessage("m", 2, "canary", 99.0)
        states, ch = apply_rollout(states, m3)
        assert ch and states["m"].stage_since == 99.0

    def test_stale_terminal_is_noop(self):
        states, _ = apply_rollout({}, RolloutMessage("m", 3, "canary", 1.0))
        # a replayed decision about version 2 must not cancel v3's rollout
        states2, ch = apply_rollout(
            states, RolloutMessage("m", 2, "rollback", 2.0)
        )
        assert not ch and states2 == states

    def test_wire_roundtrip(self):
        msg = RolloutMessage(
            "m", 2, "canary", 1.5, path="/p.pmml", fraction=0.25,
            guardrails=GuardrailSpec(max_disagree_rate=0.1),
        )
        back = from_wire(to_wire(msg))
        assert back == msg
        with pytest.raises(ValueError):
            from_wire({"kind": "nope"})

    def test_bad_stage_and_fraction_rejected(self):
        with pytest.raises(ValueError):
            RolloutMessage("m", 2, "yolo", 1.0)
        with pytest.raises(ValueError):
            RolloutMessage("m", 2, "canary", 1.0, fraction=1.5)
        with pytest.raises(ValueError):
            RolloutState("m", 2, "full", 1.0)  # terminal is not storable


class TestHistWindow:
    def test_delta_and_reset_fallback(self):
        h = Histogram()
        for v in (0.001,) * 50:
            h.observe(v)
        old = h.state()
        for v in (0.1,) * 50:
            h.observe(v)
        win = _hist_window({"histograms": {"x": h.state()}},
                           {"histograms": {"x": old}}, "x")
        assert win.count() == 50
        assert win.quantile(0.5) >= 0.05  # only the new observations
        # a counter going backwards (worker restart) falls back whole
        win2 = _hist_window({"histograms": {"x": old}},
                            {"histograms": {"x": h.state()}}, "x")
        assert win2.count() == 50  # cumulative fallback, not negative


class TestCanaryServing:
    def test_split_serves_fraction_and_replays_identically(self, tmp_path):
        v1 = _write_const(tmp_path, "v1.pmml", 1.0)
        v2 = _write_const(tmp_path, "v2.pmml", 2.0)
        ctrl = ControlSource()
        sc = DynamicScorer(control=ctrl, batch_size=64, auto_rollout=False)
        ctrl.push(AddMessage("m", 1, v1, timestamp=1.0))
        _run(sc, _events("m", 1))
        ctrl.push(RolloutMessage(
            "m", 2, "canary", time.time(), path=v2, fraction=0.25,
        ))
        sc._drain_control()
        _wait_warm(sc.registry, ModelId("m", 2))

        events = _events("m", 1024)
        vals = _values(_run(sc, events))
        share = sum(1 for v in vals if v == 2.0) / len(vals)
        assert abs(share - 0.25) < 0.05
        # per-key-stable: the replay routes every record identically
        assert _values(_run(sc, events)) == vals
        # assignment matches the pure split function exactly
        for (name, rec), v in zip(events, vals):
            expected = 2.0 if rsplit.assign_candidate(
                "m", 2, 0.25, rec["_key"]
            ) else 1.0
            assert v == expected

    def test_shadow_stage_serves_incumbent_only_no_sink_leakage(
        self, tmp_path
    ):
        v1 = _write_const(tmp_path, "v1.pmml", 1.0)
        v2 = _write_const(tmp_path, "v2.pmml", 2.0)
        ctrl = ControlSource()
        sc = DynamicScorer(control=ctrl, batch_size=64, auto_rollout=False)
        ctrl.push(AddMessage("m", 1, v1, timestamp=1.0))
        _run(sc, _events("m", 1))
        ctrl.push(RolloutMessage("m", 2, "shadow", time.time(), path=v2))
        sc._drain_control()
        _wait_warm(sc.registry, ModelId("m", 2))

        events = _events("m", 512)
        out = _run(sc, events)
        assert len(out) == len(events)  # exactly one emission per record
        assert set(_values(out)) == {1.0}  # incumbent serves everything
        snap = sc.metrics.struct_snapshot()["counters"]
        assert snap.get('rollout_candidate_records{model="m"}', 0) == 0
        assert snap['rollout_shadow_compared{model="m"}'] == 512
        assert snap['rollout_shadow_disagree{model="m"}'] == 512

    def test_cold_candidate_slice_stays_on_incumbent(self, tmp_path):
        v1 = _write_const(tmp_path, "v1.pmml", 1.0)
        v2 = _write_const(tmp_path, "v2.pmml", 2.0)
        ctrl = ControlSource()
        sc = DynamicScorer(control=ctrl, batch_size=64, auto_rollout=False)
        orig_load = sc.registry._load

        def stall_v2(info):
            if "v2" in info.path:
                time.sleep(1.5)
            return orig_load(info)

        sc.registry._load = stall_v2
        ctrl.push(AddMessage("m", 1, v1, timestamp=1.0))
        _run(sc, _events("m", 1))
        ctrl.push(RolloutMessage(
            "m", 2, "canary", time.time(), path=v2, fraction=0.5,
        ))
        sc._drain_control()
        t0 = time.monotonic()
        out = _run(sc, _events("m", 128))
        dt = time.monotonic() - t0
        # candidate still compiling: its slice scores on the incumbent,
        # nothing stalls, nothing goes empty
        assert set(_values(out)) == {1.0}
        assert dt < 1.0, f"canary batch stalled {dt:.2f}s on a cold candidate"


class TestGuardrails:
    def _scorer_with_rollout(self, tmp_path, spec, slow_candidate=False,
                             candidate_const=2.0):
        v1 = _write_const(tmp_path, "v1.pmml", 1.0)
        v2 = _write_const(tmp_path, "v2slow.pmml" if slow_candidate
                          else "v2.pmml", candidate_const)
        ctrl = ControlSource()
        sc = DynamicScorer(control=ctrl, batch_size=64, auto_rollout=False)
        if slow_candidate:
            _inject_slow(sc.registry, "v2slow", 0.05)
        ctrl.push(AddMessage("m", 1, v1, timestamp=1.0))
        _run(sc, _events("m", 1))
        return sc, ctrl, v2

    def test_disagreeing_candidate_rolled_back_under_canary(self, tmp_path):
        spec = GuardrailSpec(
            min_samples=50, window_s=30.0, promote_after_s=3600.0,
            max_disagree_rate=0.02,
        )
        sc, ctrl, v2 = self._scorer_with_rollout(tmp_path, spec)
        ctrl.push(RolloutMessage(
            "m", 2, "canary", time.time(), path=v2, fraction=0.25,
            guardrails=spec,
        ))
        sc._drain_control()
        _wait_warm(sc.registry, ModelId("m", 2))
        _run(sc, _events("m", 512))
        decisions = sc.rollout_controller.tick()
        assert len(decisions) == 1 and decisions[0]["action"] == "rollback"
        assert "disagreement" in decisions[0]["reason"]
        # incumbent keeps serving; the candidate is gone from the registry
        assert sc.registry.rollout("m") is None
        assert sc.registry.resolve("m") == ModelId("m", 1)
        assert sc.registry.resolve("m", 2) is None
        out = _run(sc, _events("m", 64))
        assert set(_values(out)) == {1.0}
        # the flight recorder holds the decision event with its reason
        evs = [e for e in flight.events() if e["kind"] == "rollout_rollback"
               and e.get("name") == "m"]
        assert evs and "disagreement" in evs[-1]["reason"]
        snap = sc.rollout_controller.metrics.struct_snapshot()["counters"]
        assert snap['rollout_rollbacks{model="m"}'] == 1

    def test_slow_candidate_rolled_back_on_latency(self, tmp_path):
        # byte-identical semantics (no disagreement), +50ms per dispatch:
        # only the latency guardrail can catch it
        spec = GuardrailSpec(
            min_samples=8, window_s=60.0, promote_after_s=3600.0,
            max_latency_ratio=2.0, max_disagree_rate=1.0,
        )
        sc, ctrl, v2 = self._scorer_with_rollout(
            tmp_path, spec, slow_candidate=True, candidate_const=1.0,
        )
        ctrl.push(RolloutMessage(
            "m", 2, "canary", time.time(), path=v2, fraction=0.25,
            guardrails=spec,
        ))
        sc._drain_control()
        _wait_warm(sc.registry, ModelId("m", 2))
        # put an incumbent-assigned key FIRST in every batch so the
        # incumbent group launches (and FIFO-completes) ahead of the
        # slow candidate: its latency baseline stays unpolluted by the
        # candidate's injected sleep
        events = _events("m", 10 * 64)
        for off in range(0, len(events), 64):
            chunk = events[off : off + 64]
            for j, (_nm, rec) in enumerate(chunk):
                if not rsplit.assign_candidate("m", 2, 0.25, rec["_key"]):
                    chunk[0], chunk[j] = chunk[j], chunk[0]
                    break
            events[off : off + 64] = chunk
        _run(sc, events)
        decisions = sc.rollout_controller.tick()
        assert len(decisions) == 1 and decisions[0]["action"] == "rollback"
        assert "p99" in decisions[0]["reason"]
        assert sc.registry.rollout("m") is None
        assert sc.registry.resolve("m") == ModelId("m", 1)

    def test_healthy_candidate_promotes_shadow_to_canary_to_full(
        self, tmp_path
    ):
        spec = GuardrailSpec(
            min_samples=50, window_s=60.0, promote_after_s=0.0,
            canary_fraction=0.25,
            # identical-speed twins on a noisy CPU host: the latency
            # guardrail is not under test here, keep it out of the way
            max_latency_ratio=1000.0,
        )
        # candidate scores identically: zero disagreement, same speed
        sc, ctrl, v2 = self._scorer_with_rollout(
            tmp_path, spec, candidate_const=1.0,
        )
        ctrl.push(RolloutMessage(
            "m", 2, "shadow", time.time(), path=v2, guardrails=spec,
        ))
        sc._drain_control()
        _wait_warm(sc.registry, ModelId("m", 2))

        events = _events("m", 512)
        _run(sc, events)
        d1 = sc.rollout_controller.tick()
        assert [d["stage"] for d in d1] == ["canary"]
        st = sc.registry.rollout("m")
        assert st.stage == "canary" and st.fraction == 0.25

        # canary stage: the split is live and per-key stable
        vals = _values(_run(sc, events))
        assert _values(_run(sc, events)) == vals
        snap = sc.metrics.struct_snapshot()["counters"]
        assert snap['rollout_candidate_records{model="m"}'] > 0
        d2 = sc.rollout_controller.tick()
        assert [d["stage"] for d in d2] == ["full"]
        assert sc.registry.rollout("m") is None
        # promoted: latest-wins routing now serves the candidate
        assert sc.registry.resolve("m") == ModelId("m", 2)
        snap = sc.rollout_controller.metrics.struct_snapshot()["counters"]
        assert snap['rollout_promotions{model="m"}'] == 2


class TestReviewRegressions:
    def test_superseding_rollout_drops_the_abandoned_candidate(self):
        """Starting a rollout of v3 while v2 is mid-canary must not hand
        the never-promoted v2 latest-wins traffic: it is dropped like a
        rollback, not left as the newest served version."""
        reg = ModelRegistry(async_warmup=False)
        reg.apply(AddMessage("m", 1, "/tmp/v1.pmml", 1.0))
        reg.apply(RolloutMessage(
            "m", 2, "canary", 2.0, path="/tmp/v2.pmml", fraction=0.2,
        ))
        reg.apply(RolloutMessage("m", 3, "shadow", 3.0, path="/tmp/v3.pmml"))
        st = reg.rollout("m")
        assert st is not None and st.candidate_version == 3
        assert reg.resolve("m", 2) is None, "abandoned candidate still served"
        assert reg.resolve("m") == ModelId("m", 1)
        # a late rollback frame for the superseded v2 is a harmless no-op
        assert not reg.apply(RolloutMessage("m", 2, "rollback", 4.0))
        assert reg.rollout("m").candidate_version == 3

    def test_failed_candidate_counts_errors_not_records(self, tmp_path):
        """A failing candidate group must land ONLY in the error counter:
        counting its lanes as served records would halve the controller's
        error rate and pollute the latency baseline."""
        v1 = _write_const(tmp_path, "v1.pmml", 1.0)
        v2 = _write_const(tmp_path, "v2.pmml", 2.0)
        ctrl = ControlSource()
        sc = DynamicScorer(control=ctrl, batch_size=64, auto_rollout=False)

        class _Poisoned(_SlowModel):
            def predict(self, X, M):
                out = self._inner.predict(X, M)

                class _Boom:
                    def block_until_ready(self):
                        raise RuntimeError("injected candidate poison")

                return _Boom()

        orig = sc.registry._load
        sc.registry._load = lambda info: (
            _Poisoned(orig(info), 0.0) if "v2" in info.path else orig(info)
        )
        ctrl.push(AddMessage("m", 1, v1, timestamp=1.0))
        _run(sc, _events("m", 1))
        ctrl.push(RolloutMessage(
            "m", 2, "canary", time.time(), path=v2, fraction=0.5,
        ))
        sc._drain_control()
        _wait_warm(sc.registry, ModelId("m", 2))
        out = _run(sc, _events("m", 128))
        # stream lives; candidate lanes are empty, incumbent lanes score
        vals = _values(out)
        assert None in vals and 1.0 in vals and 2.0 not in vals
        snap = sc.metrics.struct_snapshot()
        counters = snap["counters"]
        assert counters['rollout_candidate_errors{model="m"}'] > 0
        assert counters.get('rollout_candidate_records{model="m"}', 0) == 0
        hists = snap["histograms"]
        assert 'rollout_candidate_latency_s{model="m"}' not in hists or (
            hists['rollout_candidate_latency_s{model="m"}']["n"] == 0
        )

    def test_keyed_control_delivers_every_names_decision(self):
        """Two concurrent rollouts: a worker that missed BOTH decisions
        must receive both on one beat — a single-slot control document
        would silently drop the earlier rollback."""
        from flink_jpmml_tpu.parallel.health import (
            HealthCoordinator, HealthReporter,
        )

        applied = []
        coord = HealthCoordinator(timeout_s=5.0)
        # both decisions published BEFORE the worker first connects
        coord.set_control({"rollout": to_wire(
            RolloutMessage("a", 2, "rollback", 1.0)
        )}, key="rollout:a")
        coord.set_control({"rollout": to_wire(
            RolloutMessage("b", 5, "full", 2.0)
        )}, key="rollout:b")
        rep = HealthReporter(
            coord.host, coord.port, "w0", interval_s=0.05,
            on_control=applied.append,
        )
        try:
            deadline = time.monotonic() + 10.0
            while len(applied) < 2 and time.monotonic() < deadline:
                time.sleep(0.02)
            names = sorted(d["rollout"]["name"] for d in applied)
            assert names == ["a", "b"], applied
        finally:
            rep.stop()
            coord.close()


class TestCheckpointDurability:
    def test_restore_mid_canary_resumes_stage_and_split(self, tmp_path):
        v1 = _write_const(tmp_path, "v1.pmml", 1.0)
        v2 = _write_const(tmp_path, "v2.pmml", 2.0)
        ctrl = ControlSource()
        sc = DynamicScorer(control=ctrl, batch_size=64, auto_rollout=False)
        ctrl.push(AddMessage("m", 1, v1, timestamp=1.0))
        _run(sc, _events("m", 1))
        spec = GuardrailSpec(promote_after_s=123.0)
        ctrl.push(RolloutMessage(
            "m", 2, "canary", 777.0, path=v2, fraction=0.25,
            guardrails=spec,
        ))
        sc._drain_control()
        _wait_warm(sc.registry, ModelId("m", 2))
        events = _events("m", 512)
        vals = _values(_run(sc, events))

        state = sc.state()  # what the pipeline checkpoints

        sc2 = DynamicScorer(
            control=ControlSource(), batch_size=64, auto_rollout=False
        )
        sc2.restore(state)
        st = sc2.registry.rollout("m")
        # same stage, fraction, spec, and dwell clock — NOT a re-flip
        assert st is not None and st.stage == "canary"
        assert st.fraction == 0.25
        assert st.stage_since == 777.0
        assert st.spec.promote_after_s == 123.0
        _wait_warm(sc2.registry, ModelId("m", 1))
        _wait_warm(sc2.registry, ModelId("m", 2))
        # the identical split: every key routes as it did pre-restore
        assert _values(_run(sc2, events)) == vals

    def test_restore_while_warm_in_flight_no_double_compile(self, tmp_path):
        v1 = _write_const(tmp_path, "v1.pmml", 1.0)
        reg = ModelRegistry(batch_size=8)
        loads = {}
        orig = reg._load

        def slow_load(info):
            loads[info.path] = loads.get(info.path, 0) + 1
            time.sleep(0.5)
            return orig(info)

        reg._load = slow_load
        mid = ModelId("m", 1)
        reg.apply(AddMessage("m", 1, v1, timestamp=1.0))
        assert reg.is_warming(mid)
        state = reg.state()
        reg.restore(state)  # warm still mid-compile
        # the in-flight warm is re-attributed, not duplicated
        model = reg.model(mid)  # joins the warm — never serves cold
        assert model is not None
        assert loads[v1] == 1, f"double compile: {loads}"
        assert reg.warm_error(mid) is None
        # and the result is attributed: no further compile on re-ask
        assert reg.model_if_warm(mid) is model

    def test_restore_with_changed_path_rewarns(self, tmp_path):
        v1 = _write_const(tmp_path, "v1.pmml", 1.0)
        v1b = _write_const(tmp_path, "v1b.pmml", 3.0)
        reg = ModelRegistry(batch_size=8, async_warmup=False)
        reg.apply(AddMessage("m", 1, v1, timestamp=1.0))
        assert reg.model(ModelId("m", 1)) is not None
        reg.restore({"served": {"m_1": v1b}})
        # different document: the old compile must not be served
        m = reg.model(ModelId("m", 1))
        out = m.score_records([{"a": 0.0}])
        assert out[0].score.value == 3.0


class TestFleetConvergence:
    def test_broadcast_rollback_converges_a_beating_worker(self, tmp_path):
        from flink_jpmml_tpu.parallel.health import (
            HealthCoordinator, HealthReporter,
        )
        from flink_jpmml_tpu.runtime.supervisor import rollout_control_hook

        v1 = _write_const(tmp_path, "v1.pmml", 1.0)
        v2 = _write_const(tmp_path, "v2.pmml", 2.0)
        reg = ModelRegistry(batch_size=8, async_warmup=False)
        reg.apply(AddMessage("m", 1, v1, timestamp=1.0))
        reg.apply(RolloutMessage(
            "m", 2, "canary", 2.0, path=v2, fraction=0.2,
        ))
        assert reg.rollout("m") is not None

        coord = HealthCoordinator(timeout_s=5.0)
        rep = HealthReporter(
            coord.host, coord.port, "w0", interval_s=0.05,
            on_control=rollout_control_hook(reg),
        )
        try:
            # the supervisor-side decision, broadcast over the beat reply
            coord.set_control({
                "rollout": to_wire(RolloutMessage("m", 2, "rollback", 3.0))
            })
            deadline = time.monotonic() + 10.0
            while reg.rollout("m") is not None:
                assert time.monotonic() < deadline, "never converged"
                time.sleep(0.02)
            assert reg.resolve("m", 2) is None  # candidate dropped
            assert reg.resolve("m") == ModelId("m", 1)
        finally:
            rep.stop()
            coord.close()

    def test_rollout_book_forwards_and_tracks(self):
        from flink_jpmml_tpu.rollout.controller import RolloutBook

        sent = []
        book = RolloutBook(sent.append)
        msg = RolloutMessage("m", 2, "canary", 1.0)
        assert book.apply(msg)
        assert book.rollouts()["m"].stage == "canary"
        assert sent == [msg]
        assert book.apply(RolloutMessage("m", 2, "rollback", 2.0))
        assert book.rollouts() == {}
        assert len(sent) == 2  # terminal frames forward too
