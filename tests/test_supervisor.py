"""runtime/supervisor.py: automatic restart-from-checkpoint (SURVEY.md
§6 "Failure detection / elastic recovery", recovery half).

The flagship drill is the last test: a worker process scoring a GBM
over a real Kafka wire stream is SIGKILLed mid-stream; the supervisor
detects the death and respawns it with NO operator action; the worker
restores the committed offset from its checkpoint and drains the rest;
the merged emission log proves exactly-once per committed offset
(records below the restore point appear exactly once; duplicates exist
only in the uncommitted replay window — the at-least-once tail).
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

# NOT module-slow-marked wholesale: the unit/group classes are seconds-
# cheap and deadline-polled (fast inner loop); only the heartbeat-wedge
# and kill/resume drills (multi-second sleeps, 60k-record stream) keep
# the slow mark below.

from flink_jpmml_tpu.runtime.supervisor import (
    RestartPolicy, Supervisor, WorkerSpec,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _py(body: str) -> list:
    return [sys.executable, "-c", textwrap.dedent(body)]


def _wait(pred, timeout_s: float, interval_s: float = 0.02) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval_s)
    return pred()


def _stable(pred, hold_s: float, timeout_s: float,
            interval_s: float = 0.02) -> bool:
    """True once ``pred()`` has held CONTINUOUSLY for ``hold_s`` within
    the deadline — the de-flaked form of 'sleep then assert': a
    transient violation (a slow spawn under parallel CPU load) restarts
    the hold clock instead of failing the test."""
    deadline = time.monotonic() + timeout_s
    since = None
    while time.monotonic() < deadline:
        if pred():
            if since is None:
                since = time.monotonic()
            if time.monotonic() - since >= hold_s:
                return True
        else:
            since = None
        time.sleep(interval_s)
    return False


def _settles(value_fn, hold_s: float, timeout_s: float,
             interval_s: float = 0.05) -> bool:
    """True once ``value_fn()`` stops changing for ``hold_s`` within the
    deadline (e.g. a restart counter that must quiesce — at WHATEVER
    value load-induced extra kills left it at)."""
    deadline = time.monotonic() + timeout_s
    last = value_fn()
    t0 = time.monotonic()
    while time.monotonic() < deadline:
        cur = value_fn()
        if cur != last:
            last, t0 = cur, time.monotonic()
        elif time.monotonic() - t0 >= hold_s:
            return True
        time.sleep(interval_s)
    return False


class TestRestartPolicy:
    def test_backoff_ceiling_grows_and_caps(self):
        # the jitter draw is uniform(0, ceiling); rng pinned at 1.0
        # exposes the capped-exponential ceiling schedule — the SAME
        # schedule as utils/retry.Backoff (kafka reconnects), so a
        # fleet's restart storm decorrelates instead of synchronizing
        p = RestartPolicy(backoff_s=0.5, max_backoff_s=3.0)
        one = lambda: 1.0  # noqa: E731
        assert p.backoff(1, rng=one) == 0.5
        assert p.backoff(2, rng=one) == 1.0
        assert p.backoff(3, rng=one) == 2.0
        assert p.backoff(4, rng=one) == 3.0  # capped
        assert p.backoff(10, rng=one) == 3.0
        assert p.backoff_ceiling(10) == 3.0
        # full jitter: the draw scales the ceiling
        assert p.backoff(3, rng=lambda: 0.25) == pytest.approx(0.5)
        # a configured multiplier is honored (1.0 = fixed-delay
        # ceiling, still jittered; 3.0 grows faster than the default)
        flat = RestartPolicy(backoff_s=0.5, backoff_multiplier=1.0,
                             max_backoff_s=3.0)
        assert flat.backoff(6, rng=one) == 0.5
        steep = RestartPolicy(backoff_s=0.5, backoff_multiplier=3.0,
                              max_backoff_s=50.0)
        assert steep.backoff(3, rng=one) == pytest.approx(4.5)

    def test_backoff_draws_stay_under_ceiling(self):
        p = RestartPolicy(backoff_s=0.1, max_backoff_s=1.0)
        for k in range(1, 12):
            ceil = p.backoff_ceiling(k)
            for _ in range(32):
                assert 0.0 <= p.backoff(k) <= ceil

    def test_backoff_env_override(self, monkeypatch):
        monkeypatch.setenv("FJT_RESTART_BASE_S", "0.25")
        monkeypatch.setenv("FJT_RESTART_CAP_S", "0.5")
        p = RestartPolicy(backoff_s=5.0, max_backoff_s=50.0)
        assert p.backoff(1, rng=lambda: 1.0) == 0.25
        assert p.backoff(4, rng=lambda: 1.0) == 0.5  # env cap wins


class TestSupervisorUnit:
    def test_clean_exit_is_finished_not_restarted(self):
        sup = Supervisor(
            [WorkerSpec("w0", _py("pass"))],
            policy=RestartPolicy(backoff_s=0.01),
            heartbeat_timeout_s=None,
        )
        sup.start()
        try:
            assert _wait(lambda: sup.status()["w0"]["finished"], 10.0)
            # poll-with-deadline, not sleep-and-sample: finished must
            # HOLD (no respawn) for a beat
            assert _stable(
                lambda: (lambda st: st["finished"] and st["restarts"] == 0
                         and not st["gave_up"])(sup.status()["w0"]),
                hold_s=0.2, timeout_s=10.0,
            ), sup.status()
        finally:
            sup.stop()

    def test_crash_restarts_then_gives_up(self):
        gave_up = []
        sup = Supervisor(
            [WorkerSpec("w0", _py("import sys; sys.exit(3)"))],
            policy=RestartPolicy(max_restarts=2, backoff_s=0.01),
            heartbeat_timeout_s=None,
            on_give_up=gave_up.append,
        )
        sup.start()
        try:
            assert _wait(lambda: sup.status()["w0"]["gave_up"], 15.0)
            st = sup.status()["w0"]
            # max_restarts=2: initial + 2 respawns all failed, then stop
            assert st["restarts"] == 2
            # the callback fires AFTER the sweep that flips the status
            # flag (outside the lock, behind the flight dump's file
            # I/O): poll for it, don't sample it
            assert _wait(lambda: gave_up == ["w0"], 10.0), gave_up
        finally:
            sup.stop()

    def test_failure_rate_window_forgives_old_failures(self, tmp_path):
        # worker crashes once, then (second incarnation) runs forever:
        # inside a window policy the early failure ages out of the
        # budget instead of counting against it for the process lifetime
        flag = tmp_path / "crashed-once"
        body = f"""
        import os, time, sys
        flag = {str(flag)!r}
        if not os.path.exists(flag):
            open(flag, "w").close()
            sys.exit(1)
        time.sleep(60)
        """
        sup = Supervisor(
            [WorkerSpec("w0", _py(body))],
            policy=RestartPolicy(
                max_restarts=1, backoff_s=0.01, window_s=5.0
            ),
            heartbeat_timeout_s=None,
        )
        sup.start()
        try:
            assert _wait(lambda: sup.status()["w0"]["restarts"] == 1, 10.0)
            assert _stable(
                lambda: (lambda st: st["alive"] and not st["gave_up"])(
                    sup.status()["w0"]
                ),
                hold_s=0.3, timeout_s=10.0,
            ), sup.status()
        finally:
            sup.stop()

    def test_restart_streak_exported_to_workers(self, tmp_path):
        # the supervisor half of crash-loop fingerprinting: every
        # incarnation is told how many consecutive failures preceded it
        log = tmp_path / "streaks.log"
        body = f"""
        import os, sys, time
        with open({str(log)!r}, "a") as f:
            f.write(os.environ.get("FJT_RESTART_STREAK", "?") + "\\n")
        n = len(open({str(log)!r}).read().split())
        if n < 3:
            sys.exit(1)
        time.sleep(60)
        """
        sup = Supervisor(
            [WorkerSpec("w0", _py(body))],
            policy=RestartPolicy(max_restarts=5, backoff_s=0.01),
            heartbeat_timeout_s=None,
        )
        sup.start()
        try:
            assert _wait(
                lambda: log.exists()
                and len(log.read_text().split()) >= 3, 15.0,
            ), log.read_text() if log.exists() else "no log"
            assert log.read_text().split()[:3] == ["0", "1", "2"]
        finally:
            sup.stop()

    def test_two_workers_independent(self):
        sup = Supervisor(
            [
                WorkerSpec("crasher", _py("import sys; sys.exit(2)")),
                WorkerSpec("steady", _py("import time; time.sleep(60)")),
            ],
            policy=RestartPolicy(max_restarts=1, backoff_s=0.01),
            heartbeat_timeout_s=None,
        )
        sup.start()
        try:
            assert _wait(
                lambda: sup.status()["crasher"]["gave_up"], 15.0
            )
            st = sup.status()
            assert st["steady"]["alive"] and not st["steady"]["gave_up"]
        finally:
            sup.stop()


class TestGroupRestart:
    """restart_group=True — Flink's full-job restart: any failure tears
    down every worker and the whole set respawns after one shared
    backoff (the right semantics for a jax.distributed process group,
    whose collectives cannot survive a dead rank)."""

    def test_one_death_restarts_all(self, tmp_path):
        sup = Supervisor(
            [
                WorkerSpec("r0", _py("import time; time.sleep(120)")),
                WorkerSpec("r1", _py("import time; time.sleep(120)")),
                WorkerSpec("r2", _py("import time; time.sleep(120)")),
            ],
            policy=RestartPolicy(max_restarts=3, backoff_s=0.05),
            heartbeat_timeout_s=None,
            restart_group=True,
        )
        sup.start()
        try:
            assert _wait(
                lambda: all(
                    s["alive"] for s in sup.status().values()
                ), 15.0,
            ), sup.status()
            pids = {w: s["pid"] for w, s in sup.status().items()}
            os.kill(pids["r1"], signal.SIGKILL)
            # ALL three must come back as new incarnations (>= 1: a
            # load-delayed group respawn may legitimately strike twice)
            assert _wait(
                lambda: all(
                    s["alive"] and s["restarts"] >= 1
                    for s in sup.status().values()
                ), 20.0,
            ), sup.status()
            new_pids = {w: s["pid"] for w, s in sup.status().items()}
            assert all(new_pids[w] != pids[w] for w in pids)
        finally:
            sup.stop()

    def test_group_budget_is_shared(self):
        # one chronically-crashing rank exhausts the ONE group budget;
        # every worker ends gave_up and on_give_up fires per worker
        gave_up = []
        sup = Supervisor(
            [
                WorkerSpec("r0", _py("import sys; sys.exit(9)")),
                WorkerSpec("r1", _py("import time; time.sleep(120)")),
            ],
            policy=RestartPolicy(max_restarts=2, backoff_s=0.02),
            heartbeat_timeout_s=None,
            restart_group=True,
            on_give_up=gave_up.append,
        )
        sup.start()
        try:
            assert _wait(
                lambda: all(
                    s["gave_up"] for s in sup.status().values()
                ), 20.0,
            ), sup.status()
            # callbacks trail the status flip (fired post-sweep, after
            # the flight dumps' file I/O): poll-with-deadline
            assert _wait(
                lambda: sorted(gave_up) == ["r0", "r1"], 10.0
            ), gave_up
            # the healthy rank was torn down with the group, not left
            # half-running against dead collectives (SIGKILL delivery
            # is async: wait, don't sample)
            assert _wait(
                lambda: not sup.status()["r1"]["alive"], 10.0
            ), sup.status()
        finally:
            sup.stop()


class TestHeartbeatKill:
    pytestmark = pytest.mark.slow  # multi-second wedge sleeps

    def test_wedged_worker_is_killed_and_restarted(self, tmp_path):
        # incarnation 1 never beats (a wedged device call: alive but
        # silent) -> heartbeat death -> supervisor SIGKILLs it -> the
        # respawned incarnation beats and stays up
        flag = tmp_path / "wedged-once"
        body = f"""
        import os, sys, time
        sys.path.insert(0, {REPO!r})
        from flink_jpmml_tpu.runtime.supervisor import reporter_from_env
        flag = {str(flag)!r}
        if not os.path.exists(flag):
            open(flag, "w").close()
            time.sleep(120)  # wedged: no heartbeat, no exit
        rep = reporter_from_env()
        assert rep is not None
        time.sleep(120)  # healthy half: beats in the background
        """
        sup = Supervisor(
            [WorkerSpec("w0", _py(body))],
            policy=RestartPolicy(max_restarts=8, backoff_s=0.01),
            # generous under parallel CPU load: a scheduler-starved beat
            # gap must not read as a wedge (the wedged incarnation never
            # beats at all, so detection doesn't need a tight timeout)
            heartbeat_timeout_s=2.0,
            # must exceed worker STARTUP (package import) time — a
            # too-tight first-beat deadline kills workers mid-import
            first_beat_timeout_s=15.0,
        )
        sup.start()
        try:
            assert _wait(
                lambda: sup.status()["w0"]["restarts"] >= 1, 60.0
            ), sup.status()

            # the healthy incarnation beats: the restart counter must
            # QUIESCE (at whatever value startup thrash left it) and the
            # worker stay alive — deadline-polled, not sleep-and-sample
            assert _settles(
                lambda: sup.status()["w0"]["restarts"],
                hold_s=2.5, timeout_s=30.0,
            ), sup.status()
            st = sup.status()["w0"]
            assert st["alive"] and not st["gave_up"], st
        finally:
            sup.stop()


_DRILL_WORKER = """
import os, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml_file
from flink_jpmml_tpu.runtime.block import BlockPipeline
from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
from flink_jpmml_tpu.runtime.kafka import KafkaBlockSource
from flink_jpmml_tpu.runtime.supervisor import reporter_from_env
from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig

import jax
jax.config.update("jax_platforms", "cpu")

host, port, topic, pmml, ckdir, outfile, total = (
    sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4],
    sys.argv[5], sys.argv[6], int(sys.argv[7]),
)
rep = reporter_from_env()
doc = parse_pmml_file(pmml)
cm = compile_pmml(doc, batch_size=64)
out = open(outfile, "a", buffering=1)

def sink(o, n, first_off):
    out.write(f"E {{first_off}} {{n}}\\n")

src = KafkaBlockSource(host, port, topic, n_cols=5, max_wait_ms=20)
pipe = BlockPipeline(
    src, cm, sink,
    RuntimeConfig(
        batch=BatchConfig(size=64, deadline_us=2000),
        checkpoint_interval_s=0.05,
    ),
    checkpoint=CheckpointManager(ckdir),
)
restored = pipe.restore()
out.write(f"R {{pipe.committed_offset if restored else 0}}\\n")
pipe.start()
while pipe.committed_offset < total:
    time.sleep(0.01)
pipe.stop(); pipe.join(timeout=30.0)
src.close()
out.close()
"""


class TestKillResumeDrill:
    pytestmark = pytest.mark.slow  # 60k-record broker stream, minutes-scale

    def test_kill9_auto_restart_resumes_exactly(self, tmp_path):
        from assets.generate import gen_gbm
        from flink_jpmml_tpu.runtime.kafka import MiniKafkaBroker

        pmml = gen_gbm(str(tmp_path), n_trees=10, depth=3, n_features=5)
        rng = np.random.default_rng(5)
        # large enough that the stream takes whole seconds: the parent
        # polls committed() every 50 ms and must observe a MID-stream
        # commit window — at 4k records the worker could race 0 → N
        # between two polls and the drill would never see "in progress"
        N = 60_000
        data = rng.normal(0, 1.5, size=(N, 5)).astype(np.float32)
        outfile = tmp_path / "emissions.log"
        outfile.touch()
        ckdir = tmp_path / "ck"

        broker = MiniKafkaBroker(topic="drill")
        sup = None
        try:
            broker.append_rows(data)
            spec = WorkerSpec(
                "scorer",
                [
                    sys.executable, "-c",
                    _DRILL_WORKER.format(repo=REPO),
                    broker.host, str(broker.port), "drill", pmml,
                    str(ckdir), str(outfile), str(N),
                ],
            )
            sup = Supervisor(
                [spec],
                # headroom for parallel CPU load: a scheduler-starved
                # beat gap must not burn the restart budget on spurious
                # wedge kills (the drill's own SIGKILL is the only
                # intended failure)
                policy=RestartPolicy(max_restarts=5, backoff_s=0.05),
                heartbeat_timeout_s=5.0,
            )
            sup.start()

            def committed():
                try:
                    from flink_jpmml_tpu.runtime.checkpoint import (
                        CheckpointManager,
                    )
                    st = CheckpointManager(str(ckdir)).load_latest()
                    return st["source_offset"] if st else 0
                except Exception:
                    return 0

            # let it commit real progress, then kill -9 mid-stream
            assert _wait(lambda: 0 < committed() < N, 60.0, 0.05), (
                "worker never committed progress"
            )
            pid = sup.status()["scorer"]["pid"]
            os.kill(pid, signal.SIGKILL)

            # NO operator action from here on: the supervisor restarts
            # the worker, which resumes from its checkpoint and drains
            assert _wait(
                lambda: sup.status()["scorer"]["finished"], 120.0, 0.1
            ), f"drill did not finish: {sup.status()}"
            assert sup.status()["scorer"]["restarts"] >= 1
        finally:
            if sup is not None:
                sup.stop()
            broker.close()

        # ---- exactly-once per committed offset ----
        emitted = []   # (first_off, n) per sink call, in order
        restores = []  # committed offset each incarnation started from
        for ln in outfile.read_text().splitlines():
            kind, *rest = ln.split()
            if kind == "E":
                emitted.append((int(rest[0]), int(rest[1])))
            elif kind == "R":
                restores.append(int(rest[0]))
        assert restores[0] == 0 and len(restores) >= 2
        c = restores[-1]  # the post-kill incarnation's restore point
        assert 0 < c < N
        covered = np.zeros(N, np.int64)
        for off, n in emitted:
            covered[off : off + n] += 1
        # no gaps anywhere; below the restore point exactly once;
        # duplicates confined to the uncommitted replay window
        assert (covered >= 1).all(), (
            f"gaps at {np.flatnonzero(covered == 0)[:5]}"
        )
        assert (covered[:c] == 1).all(), (
            f"dups below restore point at "
            f"{np.flatnonzero(covered[:c] > 1)[:5]}"
        )
        assert (covered <= 2).all()
