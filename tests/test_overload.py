"""Overload plane (serving/overload.py): adaptive batching, admission
control, and the shed invariants ISSUE 8 pins:

- shedding is LANE-ORDERED — low priority always sheds first, the shed
  set is always a prefix of the lane order;
- hysteresis (band + dwell) prevents flapping under a sawtooth load;
- a shed record NEVER reaches the sink or the rollout shadow diff —
  on the block path (offsets commit, sink untouched) and on the
  dynamic-scorer path (empty prediction, no dispatch, no mirror).
"""

import json
import pathlib
import time

import numpy as np
import pytest

from flink_jpmml_tpu.models.control import AddMessage, RolloutMessage
from flink_jpmml_tpu.models.core import ModelId
from flink_jpmml_tpu.obs import recorder as flight
from flink_jpmml_tpu.runtime.sources import ControlSource
from flink_jpmml_tpu.serving import overload as overload_mod
from flink_jpmml_tpu.serving.overload import (
    AdaptiveBatcher,
    AdmissionController,
)
from flink_jpmml_tpu.serving.scorer import DynamicScorer
from flink_jpmml_tpu.utils.metrics import MetricsRegistry

_CONST_XML = """<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
  <Header/>
  <DataDictionary numberOfFields="2">
    <DataField name="a" optype="continuous" dataType="double"/>
    <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <RegressionModel functionName="regression">
    <MiningSchema>
      <MiningField name="y" usageType="target"/>
      <MiningField name="a"/>
    </MiningSchema>
    <RegressionTable intercept="{c}"/>
  </RegressionModel></PMML>"""


def _write_const(tmp_path, name, c):
    p = pathlib.Path(tmp_path, name)
    p.write_text(_CONST_XML.format(c=c))
    return str(p)


def _wait_warm(reg, mid, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if reg.model_if_warm(mid) is not None:
            return
        err = reg.warm_error(mid)
        assert err is None, f"warm of {mid} failed: {err!r}"
        time.sleep(0.01)
    raise AssertionError(f"{mid} never warmed")


def _forced_controller(metrics, lanes, level, pressure=1.0):
    """A controller driven to ``level`` through its own tick machinery
    (fake clock + fake pressure), then frozen there."""
    t = [0.0]
    p = [pressure]
    adm = AdmissionController(
        metrics, lanes=lanes, dwell_s=0.1, interval_s=0.01,
        on_threshold=0.8, off_threshold=0.3,
        pressure_fn=lambda: p[0], clock=lambda: t[0],
    )
    while adm.shed_level < level:
        t[0] += 0.2
        adm.tick()
        assert t[0] < 100.0, "controller never reached the target level"
    p[0] = 0.5  # inside the band: the level freezes
    return adm


class TestAdaptiveBatcher:
    def test_fit_and_deadline_cap(self, tmp_path):
        m = MetricsRegistry()
        b = AdaptiveBatcher(
            metrics=m, deadline_s=0.010, target_frac=0.8,
            min_records=64, max_records=8192,
            path=str(tmp_path / "cap.json"),
        )
        # synthetic truth: c0 = 2 ms, c1 = 10 µs/record
        for n in (128, 512, 2048):
            for _ in range(3):
                b.observe(n, 0.002 + 1e-5 * n)
        c0, c1 = b.coefficients()
        assert c0 == pytest.approx(0.002, rel=0.2)
        assert c1 == pytest.approx(1e-5, rel=0.2)
        # budget = 8 ms − c0 ⇒ ~600 records
        cap = b.max_records()
        assert 400 <= cap <= 800
        assert b.propose([128, 256, 512, 1024]) == 512
        assert m.snapshot()["adaptive_batch"] == float(cap)

    def test_single_size_uses_origin_model(self, tmp_path):
        b = AdaptiveBatcher(
            deadline_s=0.010, min_records=16,
            path=str(tmp_path / "cap.json"),
        )
        b.observe(100, 0.001)  # 10 µs/record through the origin
        assert b.max_records() == 800

    def test_no_deadline_means_no_cap(self, tmp_path, monkeypatch):
        monkeypatch.delenv("FJT_SLO_TARGET_MS", raising=False)
        b = AdaptiveBatcher(path=str(tmp_path / "cap.json"))
        b.observe(100, 0.001)
        assert not b.enabled
        assert b.max_records() is None
        assert b.propose([64, 4096]) == 4096  # throughput default

    def test_persistence_roundtrip_and_corruption(self, tmp_path):
        path = str(tmp_path / "cap.json")
        b = AdaptiveBatcher(deadline_s=0.01, model="m", backend="b",
                            path=path)
        b.observe(128, 0.002)
        b.observe(512, 0.006)
        b.flush()
        data = json.loads(pathlib.Path(path).read_text())
        assert "m|b" in data["entries"]
        # a fresh process predicts BEFORE its first observation
        b2 = AdaptiveBatcher(deadline_s=0.01, model="m", backend="b",
                             path=path)
        assert b2.coefficients() == pytest.approx(b.coefficients())
        assert b2.max_records() is not None
        # corruption reads as empty, never raises
        pathlib.Path(path).write_text("\x00garbage{{{")
        b3 = AdaptiveBatcher(deadline_s=0.01, model="m", backend="b",
                             path=path)
        assert b3.coefficients() is None
        b3.observe(128, 0.002)
        b3.flush()  # and the rewrite recovers the file
        assert "m|b" in json.loads(
            pathlib.Path(path).read_text()
        )["entries"]

    def test_drift_triggers_reestimate(self, tmp_path):
        b = AdaptiveBatcher(deadline_s=0.01,
                            path=str(tmp_path / "cap.json"))
        for _ in range(4):
            b.observe(256, 0.002)
        c1_before = b.coefficients()[1]
        # the workload got 5x slower (new model version, thermal
        # throttle): sustained drift must re-estimate, not average out
        for _ in range(12):
            b.observe(256, 0.010)
        c1_after = b.coefficients()[1]
        assert c1_after > 2.0 * c1_before
        kinds = [e["kind"] for e in flight.events()]
        assert "capacity_reestimated" in kinds


class TestAdmissionLaneOrder:
    """Property: at every level, the shed set is exactly the
    lowest-priority prefix — for any lane configuration."""

    @pytest.mark.parametrize("lanes", [
        ("low", "normal", "high"),
        ("bulk", "batch", "interactive", "system"),
        ("only",),
    ])
    def test_shed_is_priority_prefix_at_every_level(self, lanes):
        for level in range(len(lanes) + 1):
            adm = _forced_controller(MetricsRegistry(), lanes, level)
            assert adm.shed_level == level
            assert adm.shed_lanes() == lanes[:level]
            for i, lane in enumerate(lanes):
                assert adm.admit(lane) == (i >= level)

    def test_unknown_lane_is_never_shed(self):
        adm = _forced_controller(
            MetricsRegistry(), ("low", "high"), level=2
        )
        assert adm.admit("mystery") is True

    def test_counters_and_gauge(self):
        m = MetricsRegistry()
        adm = _forced_controller(m, ("low", "high"), level=1)
        assert adm.admit("low", n=10) is False
        assert adm.admit("high", n=7) is True
        snap = m.snapshot()
        assert snap['shed_records{lane="low"}'] == 10
        assert snap["admitted_records"] == 7
        assert snap["shed_level"] == 1.0
        assert adm.counts() == {"admitted": 7.0, "shed": {"low": 10.0}}

    def test_inverted_band_rejected(self):
        with pytest.raises(ValueError, match="hysteresis band"):
            AdmissionController(
                MetricsRegistry(), on_threshold=0.5, off_threshold=0.6,
            )


class TestAdmissionHysteresis:
    def _controller(self, dwell=0.5):
        t = [0.0]
        p = [0.0]
        adm = AdmissionController(
            MetricsRegistry(), lanes=("low", "normal", "high"),
            dwell_s=dwell, interval_s=0.01,
            on_threshold=0.8, off_threshold=0.3,
            pressure_fn=lambda: p[0], clock=lambda: t[0],
        )
        return adm, t, p

    def test_sawtooth_never_flaps(self):
        """A sawtooth crossing the on-threshold every other tick (period
        << dwell) must never raise the level: each dip resets the dwell
        clock. This is the anti-flap property the band + dwell buy."""
        adm, t, p = self._controller(dwell=0.5)
        for i in range(200):
            t[0] += 0.05
            p[0] = 0.95 if i % 2 == 0 else 0.1
            adm.tick()
        assert adm.shed_level == 0

    def test_sustained_pressure_climbs_one_lane_per_dwell(self):
        adm, t, p = self._controller(dwell=0.5)
        p[0] = 0.95
        levels = []
        for _ in range(40):
            t[0] += 0.1
            adm.tick()
            levels.append(adm.shed_level)
        # monotone climb, one lane at a time, ~one per dwell period
        assert levels[-1] == 3
        assert all(b - a in (0, 1) for a, b in zip(levels, levels[1:]))
        assert levels.index(1) >= 4  # not before the first full dwell

    def test_recovery_requires_sustained_calm(self):
        adm, t, p = self._controller(dwell=0.5)
        p[0] = 0.95
        for _ in range(12):
            t[0] += 0.1
            adm.tick()
        assert adm.shed_level >= 2
        level_at_peak = adm.shed_level
        # brief calm below off — shorter than the dwell — must not
        # lower the level...
        p[0] = 0.1
        for _ in range(3):
            t[0] += 0.1
            adm.tick()
        p[0] = 0.5  # back inside the band
        t[0] += 0.1
        adm.tick()
        assert adm.shed_level == level_at_peak
        # ...sustained calm recovers, one lane per dwell
        p[0] = 0.1
        for _ in range(40):
            t[0] += 0.1
            adm.tick()
        assert adm.shed_level == 0

    def test_transitions_record_flight_events(self):
        adm, t, p = self._controller(dwell=0.2)
        p[0] = 0.95
        for _ in range(6):
            t[0] += 0.1
            adm.tick()
        events = [
            e for e in flight.events()
            if e["kind"] == "shed_level_change"
        ]
        assert events and events[-1]["direction"] == "up"
        assert events[-1]["lane"] in ("low", "normal", "high")


class TestScorerShedInvariants:
    """ISSUE 8's pinned invariant on the record path: a shed record
    never reaches the sink (it emits empty, unscored) and never reaches
    the rollout shadow diff (no mirror, no candidate traffic)."""

    def _scorer(self, tmp_path, level):
        m = MetricsRegistry()
        adm = _forced_controller(m, ("low", "normal", "high"), level)
        ctrl = ControlSource()
        sc = DynamicScorer(
            control=ctrl, batch_size=32, metrics=m, admission=adm,
            auto_rollout=False,
        )
        ctrl.push(AddMessage(
            "m", 1, _write_const(tmp_path, "v1.pmml", 1.0),
            timestamp=time.time(),
        ))
        sc._drain_control()
        _wait_warm(sc.registry, ModelId("m", 1))
        return sc, ctrl

    @staticmethod
    def _events(n, lane):
        return [
            ("m", {"a": 0.0, "_key": f"k{i}", "_lane": lane})
            for i in range(n)
        ]

    def test_shed_lane_emits_empty_and_is_never_scored(self, tmp_path):
        sc, _ = self._scorer(tmp_path, level=1)
        out = sc.finish(sc.submit(
            self._events(8, "low") + self._events(8, "normal")
        ))
        assert len(out) == 16  # C5 totality holds through shedding
        low, normal = out[:8], out[8:]
        assert all(p.is_empty for p, _ in low)
        assert all(not p.is_empty for p, _ in normal)
        counts = sc.admission.counts()
        assert counts["shed"] == {"low": 8.0}
        assert counts["admitted"] == 8.0

    def test_shed_never_reaches_shadow_diff(self, tmp_path):
        sc, ctrl = self._scorer(tmp_path, level=1)
        # a shadow rollout mirroring ALL incumbent traffic
        ctrl.push(RolloutMessage(
            "m", 2, "shadow", time.time(),
            path=_write_const(tmp_path, "v2.pmml", 1.0),
        ))
        sc._drain_control()
        _wait_warm(sc.registry, ModelId("m", 2))
        sc.finish(sc.submit(
            self._events(16, "low") + self._events(16, "normal")
        ))
        snap = sc.metrics.struct_snapshot()["counters"]
        compared = snap.get('rollout_shadow_compared{model="m"}', 0.0)
        # only the ADMITTED records may be mirrored; every shadow spec
        # defaults to full sampling, so compared == admitted-served
        assert 0 < compared <= 16
        assert snap.get('rollout_candidate_records{model="m"}', 0.0) == 0
        assert snap['shed_records{lane="low"}'] == 16

    def test_disabled_admission_admits_everything(self, tmp_path):
        sc, _ = self._scorer(tmp_path, level=3)
        sc.admission.enabled = False
        out = sc.finish(sc.submit(self._events(8, "low")))
        assert all(not p.is_empty for p, _ in out)

    def test_shed_never_advances_the_watermark(self, tmp_path):
        """A shed record was DROPPED, not delivered: its event time
        must not advance watermark_ts (the fleet-MIN freshness claim)
        nor book record_staleness_s — the record-path twin of the block
        path's discard_stamps."""
        m = MetricsRegistry()
        adm = _forced_controller(m, ("low", "normal"), level=1)
        ctrl = ControlSource()
        sc = DynamicScorer(
            control=ctrl, batch_size=32, metrics=m, admission=adm,
            auto_rollout=False,
            event_time_fn=lambda ev: ev[1].get("_ts"),
        )
        ctrl.push(AddMessage(
            "m", 1, _write_const(tmp_path, "v1.pmml", 1.0),
            timestamp=time.time(),
        ))
        sc._drain_control()
        _wait_warm(sc.registry, ModelId("m", 1))
        t_old, t_new = time.time() - 100.0, time.time()

        def ev(i, lane, ts):
            return ("m", {"a": 0.0, "_key": f"k{i}", "_lane": lane,
                          "_ts": ts})

        # served records carry OLD event times; the freshest times ride
        # the shed lane — a leak would report the worker 100 s fresher
        # than its delivered stream actually is
        sc.finish(sc.submit(
            [ev(i, "normal", t_old) for i in range(4)]
            + [ev(i, "low", t_new) for i in range(4, 8)]
        ))
        wm = m.snapshot().get("watermark_ts")
        assert wm is not None and wm <= t_old + 1e-3
        n_stale = m.histogram("record_staleness_s").count()
        # only the served batch's two bounding observations booked
        assert n_stale == 2


class TestBlockShedPath:
    """The block path's shed protocol: refused batches ride the FIFO
    window as no-ops — offsets commit in order, the sink is NEVER
    called, the shed counter carries the record count."""

    def _run(self, level):
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.runtime.block import (
            BlockPipeline, FiniteBlockSource,
        )

        doc = parse_pmml(_CONST_XML.format(c=2.5))
        cm = compile_pmml(doc, batch_size=32)
        m = MetricsRegistry()
        adm = _forced_controller(m, ("block",), level)
        data = np.zeros((256, 1), np.float32)
        sunk = []

        def sink(out, n, first_off):
            sunk.append((first_off, n))

        pipe = BlockPipeline(
            FiniteBlockSource(data, block_size=32), cm, sink,
            metrics=m, in_flight=2, use_native=False, admission=adm,
        )
        pipe.run_until_exhausted(timeout=60.0)
        return pipe, sunk, m

    def test_full_shed_commits_offsets_without_sinking(self):
        pipe, sunk, m = self._run(level=1)
        assert sunk == []  # the sink never saw a shed record
        assert pipe.committed_offset == 256  # offsets still commit
        snap = m.snapshot()
        assert snap['shed_records{lane="block"}'] == 256
        assert snap["records_out"] == 0
        # shed no-ops are UNACCOUNTED window entries: counting them as
        # dispatches would dilute the pressure score's window-full
        # fraction exactly while the shed rate peaks
        assert snap["dispatches"] == 0

    def test_lane_mismatch_rejected_at_construction(self):
        """A shed_lane the controller doesn't know would climb levels
        and report shedding while refusing nothing — the wire must fail
        loudly, not no-op silently."""
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.runtime.block import (
            BlockPipeline, FiniteBlockSource,
        )
        from flink_jpmml_tpu.utils.exceptions import (
            InputValidationException,
        )

        cm = compile_pmml(parse_pmml(_CONST_XML.format(c=1.0)),
                          batch_size=32)
        adm = AdmissionController(
            MetricsRegistry(), lanes=("low", "normal", "high"),
        )
        with pytest.raises(InputValidationException,
                           match="could never shed"):
            BlockPipeline(
                FiniteBlockSource(np.zeros((32, 1), np.float32), 32),
                cm, lambda *a: None, use_native=False, admission=adm,
            )

    def test_disabled_admission_sinks_everything(self):
        pipe, sunk, m = self._run(level=0)
        assert sum(n for _, n in sunk) == 256
        assert pipe.committed_offset == 256
        assert m.snapshot().get('shed_records{lane="block"}', 0) == 0


class TestLatencyModeCalibration:
    def test_calibration_fits_and_respects_deadline(self, tmp_path,
                                                    monkeypatch):
        """bench latency mode's compiled-batch chooser: the batcher is
        fitted from real timed dispatches, the chosen size is one of
        the calibrated candidates, and a brutally tight deadline forces
        the smallest candidate (the knob actually steers the choice)."""
        import argparse

        from flink_jpmml_tpu import bench as bench_mod
        from flink_jpmml_tpu.assets_gen import gen_gbm
        from flink_jpmml_tpu.pmml import parse_pmml_file

        monkeypatch.setenv(
            "FJT_AUTOTUNE_CACHE", str(tmp_path / "at.json")
        )
        doc = parse_pmml_file(
            gen_gbm(str(tmp_path), n_trees=5, depth=2, n_features=4)
        )
        rng = np.random.default_rng(0)
        data = rng.normal(size=(1024, 4)).astype(np.float32)

        def args(deadline_us):
            return argparse.Namespace(
                trees=5, depth=2, features=4, latency_batch=1024,
                latency_deadline_us=deadline_us,
            )

        chosen, cm, batcher = bench_mod._calibrate_latency_batch(
            doc, data, args(deadline_us=500_000), True
        )
        # half a second of budget: every candidate fits, largest wins
        assert chosen == 1024 and cm.batch_size == 1024
        assert batcher.coefficients() is not None
        assert len(batcher.state()["sizes"]) == 3
        chosen_tight, cm_tight, _ = bench_mod._calibrate_latency_batch(
            doc, data, args(deadline_us=1), True
        )
        # a 1 µs deadline fits nothing: the chooser degrades to the
        # smallest calibrated size instead of keeping the static 1024
        assert chosen_tight == 64 and cm_tight.batch_size == 64


class TestOverloadSummary:
    def test_summary_and_fjt_top_panel(self, tmp_path, capsys):
        m = MetricsRegistry()
        adm = _forced_controller(m, ("low", "high"), level=1)
        adm.admit("low", n=5)
        adm.admit("high", n=9)
        m.gauge("slo_deadline_ms").set(10.0)
        m.gauge("adaptive_batch").set(512.0)
        for _ in range(20):
            m.histogram("batch_latency_s").observe(0.004)
        struct = m.struct_snapshot()
        s = overload_mod.summary(struct)
        assert s["shed_records"] == {"low": 5.0}
        assert s["admitted_records"] == 9.0
        assert s["adaptive_batch"] == 512.0
        assert s["deadline_ms"] == 10.0
        assert s["latency_source"] == "batch_latency_s"
        assert s["p99_vs_deadline_ratio"] <= 1.0
        # the CLI panel renders the same struct from a dump file
        from flink_jpmml_tpu.cli import top_main

        dump = tmp_path / "varz.json"
        dump.write_text(json.dumps(struct))
        assert top_main(["--overload", str(dump)]) == 0
        out = capsys.readouterr().out
        assert "deadline 10.0 ms" in out
        assert "MET" in out
        assert "low" in out and "shed" in out

    def test_empty_struct_renders_fallback(self, tmp_path, capsys):
        from flink_jpmml_tpu.cli import top_main

        assert overload_mod.summary({"gauges": {}, "counters": {}}) is None
        dump = tmp_path / "varz.json"
        dump.write_text(json.dumps(MetricsRegistry().struct_snapshot()))
        assert top_main(["--overload", str(dump)]) == 0
        assert "no overload telemetry" in capsys.readouterr().out
