"""utils/demo.py: the examples' demo-safe backend bootstrap."""

import sys

from flink_jpmml_tpu.utils.demo import demo_backend


class TestDemoBackend:
    def test_platform_flag_parsed_and_stripped(self, monkeypatch):
        monkeypatch.setattr(
            sys, "argv", ["ex.py", "--platform", "cpu", "--trees", "7"]
        )
        # conftest already pins the cpu backend; the flag path must
        # force the same and strip its own args, leaving the example's
        assert demo_backend() == "cpu"
        assert sys.argv == ["ex.py", "--trees", "7"]

    def test_env_var_default(self, monkeypatch):
        monkeypatch.setattr(sys, "argv", ["ex.py"])
        monkeypatch.setenv("FJT_PLATFORM", "cpu")
        assert demo_backend() == "cpu"

    def test_resolved_backend_returned_without_flag(self, monkeypatch):
        # no flag, no env: the watchdog path resolves the default
        # backend (cpu under the test conftest) and disarms. Stub execv
        # so a pathologically slow init can't replace the pytest
        # process wholesale — firing the stub is itself a failure.
        import os

        fired = []
        monkeypatch.setattr(os, "execv", lambda *a: fired.append(a))
        monkeypatch.setattr(sys, "argv", ["ex.py"])
        monkeypatch.delenv("FJT_PLATFORM", raising=False)
        assert demo_backend(timeout_s=30.0) == "cpu"
        assert not fired, "watchdog fired during a healthy resolve"
