"""OverlappedDispatcher unit tests + sync-vs-overlapped parity.

The depth-K in-flight window (runtime/pipeline.py) is the concurrency
core every scoring path now runs through; these tests pin its contract:
FIFO completion, depth bounds, exception propagation from an in-flight
batch, drain-on-close — and that the overlapped block pipeline produces
byte-identical scores to the synchronous one on CPU.
"""

import time

import numpy as np
import pytest

from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml_file
from flink_jpmml_tpu.runtime.block import BlockPipeline, FiniteBlockSource
from flink_jpmml_tpu.runtime.pipeline import (
    DispatcherClosed,
    OverlappedDispatcher,
)
from flink_jpmml_tpu.utils.metrics import MetricsRegistry
from flink_jpmml_tpu.utils.profiling import overlap_stats


class _Leaf:
    """Test double for an async device result: readiness is observable
    and can be delayed or poisoned."""

    def __init__(self, tag, delay_s=0.0, fail=None):
        self.tag = tag
        self.delay_s = delay_s
        self.fail = fail
        self.fetched = False
        self.prefetched = False

    def copy_to_host_async(self):
        self.prefetched = True

    def block_until_ready(self):
        if self.delay_s:
            time.sleep(self.delay_s)
        if self.fail is not None:
            raise self.fail
        self.fetched = True

    def is_ready(self):
        # mirrors jax.Array.is_ready(): False while the async result is
        # still in flight (here: an unelapsed delay nobody waited on)
        return self.fetched or self.delay_s == 0.0


class TestDispatcherUnit:
    def test_fifo_ordering_under_reversed_readiness(self):
        # later launches become ready FIRST; completion must still be
        # launch order (the commit protocol rides on it)
        seen = []
        disp = OverlappedDispatcher(
            depth=2, complete=lambda out, meta: seen.append(meta)
        )
        for i in range(10):
            disp.launch(
                lambda i=i: _Leaf(i, delay_s=max(0.0, (5 - i) * 0.001)),
                meta=i,
            )
        disp.flush()
        assert seen == list(range(10))

    def test_depth_bound_and_prefetch(self):
        disp = OverlappedDispatcher(depth=3)
        leaves = []
        for i in range(10):
            leaf = _Leaf(i)
            leaves.append(leaf)
            disp.launch(lambda leaf=leaf: leaf)
            assert len(disp) <= 3  # never exceeds the window after launch
            assert leaf.prefetched  # D2H queued at launch, not at fetch
        # the first 7 were forced out by the window; the last 3 pend
        assert [lf.fetched for lf in leaves] == [True] * 7 + [False] * 3
        disp.flush()
        assert all(lf.fetched for lf in leaves)

    def test_depth_zero_is_synchronous(self):
        # the latency operating point: every launch finishes its own batch
        disp = OverlappedDispatcher(depth=0)
        leaf = _Leaf(0)
        disp.launch(lambda: leaf)
        assert leaf.fetched and len(disp) == 0

    def test_unbounded_depth_never_blocks_in_launch(self):
        # depth=None: the Scorer contract's shape — submit-side launches
        # must not block on device completion; the caller bounds the
        # window itself via wait/finish
        disp = OverlappedDispatcher(depth=None)
        leaves = [_Leaf(i) for i in range(20)]
        for leaf in leaves:
            disp.launch(lambda leaf=leaf: leaf)
        assert len(disp) == 20  # nothing was force-finished
        assert not any(lf.fetched for lf in leaves)
        disp.flush()
        assert all(lf.fetched for lf in leaves)

    def test_wait_on_failed_handle_reraises_every_time(self):
        # a fetch failure must not be handed back as a completed result
        disp = OverlappedDispatcher(depth=None)
        bad = disp.launch(
            lambda: _Leaf("bad", fail=RuntimeError("device died"))
        )
        with pytest.raises(RuntimeError, match="device died"):
            disp.wait(bad)
        # the poisoned entry left the window, but a retry must re-raise
        # the original error, never return the unsynchronized output
        with pytest.raises(RuntimeError, match="device died"):
            disp.wait(bad)

    def test_wait_on_abandoned_handle_still_synchronizes(self):
        # wait() must never hand back an unsynchronized result — even
        # for a handle the window dropped via abandon()
        disp = OverlappedDispatcher(depth=None)
        ok = disp.launch(lambda: _Leaf("ok"))
        bad = disp.launch(
            lambda: _Leaf("bad", fail=RuntimeError("late device error"))
        )
        disp.abandon()
        out = disp.wait(ok)  # fetched directly, not returned raw
        assert out.fetched
        with pytest.raises(RuntimeError, match="late device error"):
            disp.wait(bad)

    def test_inflight_error_propagates_and_window_survives(self):
        seen = []
        disp = OverlappedDispatcher(
            depth=8, complete=lambda out, meta: seen.append(meta)
        )
        disp.launch(lambda: _Leaf("a"), meta="a")
        disp.launch(lambda: _Leaf("bad", fail=RuntimeError("device died")),
                    meta="bad")
        disp.launch(lambda: _Leaf("b"), meta="b")
        with pytest.raises(RuntimeError, match="device died"):
            disp.flush()
        # the poisoned entry left the window (no wedged flushes) and the
        # batches behind it remain drainable
        assert seen == ["a"]
        disp.flush()
        assert seen == ["a", "b"]

    def test_launch_error_propagates(self):
        disp = OverlappedDispatcher(depth=2)
        with pytest.raises(ValueError, match="encode exploded"):
            disp.launch(lambda: (_ for _ in ()).throw(
                ValueError("encode exploded")
            ))
        assert len(disp) == 0

    def test_wait_finishes_fifo_up_to_handle(self):
        seen = []
        disp = OverlappedDispatcher(
            depth=8, complete=lambda out, meta: seen.append(meta)
        )
        h1 = disp.launch(lambda: _Leaf(1), meta=1)
        h2 = disp.launch(lambda: _Leaf(2), meta=2)
        h3 = disp.launch(lambda: _Leaf(3), meta=3)
        out = disp.wait(h2)
        assert out.tag == 2 and seen == [1, 2] and len(disp) == 1
        disp.wait(h1)  # already finished: no-op
        assert seen == [1, 2]
        disp.wait(h3)
        assert seen == [1, 2, 3]

    def test_close_drains_and_refuses_further_launches(self):
        seen = []
        disp = OverlappedDispatcher(
            depth=4, complete=lambda out, meta: seen.append(meta)
        )
        for i in range(3):
            disp.launch(lambda i=i: _Leaf(i), meta=i)
        disp.close()
        assert seen == [0, 1, 2] and len(disp) == 0
        with pytest.raises(DispatcherClosed):
            disp.launch(lambda: _Leaf(9))

    def test_abandon_drops_without_fetching(self):
        disp = OverlappedDispatcher(depth=4)
        leaves = [_Leaf(i) for i in range(3)]
        for leaf in leaves:
            disp.launch(lambda leaf=leaf: leaf)
        assert disp.abandon() == 3
        assert len(disp) == 0
        assert not any(lf.fetched for lf in leaves)

    def test_window_full_counts_only_blocking_launches(self):
        """A healthy overlapped pipeline's steady state is a window
        trimmed to exactly depth: the overshoot inside launch must not
        count as saturation when the oldest batch is already done —
        that read pressure_window ≈ 1.0 (and fired permanent
        pressure_breach events) on every busy default-config pipeline
        (review finding, pinned)."""
        m = MetricsRegistry()
        disp = OverlappedDispatcher(depth=2, metrics=m)
        for i in range(20):
            disp.launch(lambda i=i: _Leaf(i))  # instantly ready
        assert m.counter("window_full_launches").get() == 0
        # a genuinely in-flight oldest batch: the trim blocks → counted
        disp.launch(lambda: _Leaf("slow", delay_s=0.02))
        disp.launch(lambda: _Leaf("slow2", delay_s=0.02))
        disp.launch(lambda: _Leaf("fast"))
        assert m.counter("window_full_launches").get() == 1
        disp.flush()
        assert m.counter("dispatches").get() == 23

    def test_stall_and_depth_metrics(self):
        m = MetricsRegistry()
        disp = OverlappedDispatcher(depth=2, metrics=m)
        t0 = time.monotonic()
        for i in range(4):
            disp.launch(lambda: _Leaf(0, delay_s=0.02))
        disp.flush()
        elapsed = time.monotonic() - t0
        snap = m.snapshot()
        assert snap["dispatches"] == 4
        assert 0 < snap["h2d_stall_s"] <= elapsed + 0.1
        assert snap["inflight_depth_max"] == 2
        stats = overlap_stats(m, elapsed)
        assert 0.0 <= stats["overlap_efficiency"] <= 1.0
        assert stats["h2d_stall_ms"] == pytest.approx(
            1000 * snap["h2d_stall_s"], abs=0.002  # field rounds to µs
        )


class TestSyncOverlapParity:
    @pytest.fixture(scope="class")
    def gbm(self, tmp_path_factory):
        from assets.generate import gen_gbm

        tmp = tmp_path_factory.mktemp("disp_gbm")
        doc = parse_pmml_file(
            gen_gbm(str(tmp), n_trees=20, depth=4, n_features=6)
        )
        return compile_pmml(doc, batch_size=128)

    def _scores(self, cm, data, **kw):
        got = np.full((data.shape[0],), np.nan, np.float32)

        def sink(out, n, first_off):
            vals = np.asarray(
                out.value if hasattr(out, "value") else out, np.float32
            )[:n]
            got[first_off : first_off + n] = vals

        pipe = BlockPipeline(
            FiniteBlockSource(data, block_size=100),
            cm, sink, use_native=False, **kw,
        )
        pipe.run_until_exhausted(timeout=60.0)
        assert not np.isnan(got).any()
        return got, pipe

    def test_overlapped_matches_synchronous_byte_exact(self, gbm):
        rng = np.random.default_rng(11)
        data = rng.normal(0.0, 1.5, size=(1000, 6)).astype(np.float32)
        data[rng.random(size=data.shape) < 0.05] = np.nan

        sync, _ = self._scores(gbm, data, in_flight=1)
        over, pipe = self._scores(
            gbm, data, in_flight=3, max_dispatch_chunks=4
        )
        # byte-identical, not allclose: the overlapped window reorders
        # nothing and computes the same program on the same batches
        np.testing.assert_array_equal(sync, over)
        assert pipe.metrics.snapshot()["dispatches"] >= 1

    def test_donation_path_scores_identically(self, gbm):
        # donate=True on CPU: XLA ignores the donation (0 hits) but the
        # staged-dispatch path must still produce identical scores
        rng = np.random.default_rng(12)
        data = rng.normal(0.0, 1.5, size=(600, 6)).astype(np.float32)
        plain, _ = self._scores(gbm, data, in_flight=2, donate=False)
        donated, pipe = self._scores(gbm, data, in_flight=2, donate=True)
        np.testing.assert_array_equal(plain, donated)
        assert pipe.metrics.snapshot()["donation_hits"] >= 0


class TestAggregationOffsets:
    def test_wrap_inside_first_batch_keeps_real_offsets(self, monkeypatch):
        """A cycling source's wrap-to-0 landing INSIDE the first drained
        batch must surface the REAL per-record offsets (concatenated
        from the ring's chunks), never a fabricated contiguous range."""
        from flink_jpmml_tpu.runtime.block import BlockPipelineBase

        pipe = BlockPipelineBase(
            source=None, sink=lambda *a: None, arity=2, batch_size=4,
            config=None, metrics=None, use_native=False, in_flight=1,
            checkpoint=None, max_dispatch_chunks=4,
        )
        ring = pipe._ring
        # chunk A: offsets 6..7 (tail of the log), chunk B: wrap to 0..5
        ring.push_block(np.full((2, 2), 1.0, np.float32), 6)
        ring.push_block(np.full((6, 2), 2.0, np.float32), 0)
        X, offs = ring.drain(1000, 0)
        assert X.shape[0] == 4  # first batch spans the wrap
        assert offs.tolist() == [6, 7, 0, 1]
        X2, offs2, n = pipe._aggregate_full_batches(X, offs, 4)
        # the second FULL batch (offsets 2..5) is NOT contiguous with the
        # first batch's real tail (offset 1 → 2 IS contiguous here), so
        # aggregation may take it; what matters is offsets stay REAL
        assert n == offs2.shape[0] == X2.shape[0]
        assert offs2[:4].tolist() == [6, 7, 0, 1]
        if n == 8:
            assert offs2.tolist() == [6, 7, 0, 1, 2, 3, 4, 5]

    def test_discontinuous_extra_batch_is_carried(self):
        from flink_jpmml_tpu.runtime.block import BlockPipelineBase

        pipe = BlockPipelineBase(
            source=None, sink=lambda *a: None, arity=1, batch_size=4,
            config=None, metrics=None, use_native=False, in_flight=1,
            checkpoint=None, max_dispatch_chunks=4,
        )
        ring = pipe._ring
        ring.push_block(np.ones((4, 1), np.float32), 100)
        ring.push_block(np.ones((4, 1), np.float32), 0)  # wrap at a batch edge
        X, offs = ring.drain(1000, 0)
        assert offs.tolist() == [100, 101, 102, 103]
        X2, offs2, n = pipe._aggregate_full_batches(X, offs, 4)
        # the wrapped batch must NOT be aggregated across the gap...
        assert n == 4
        assert offs2.tolist() == [100, 101, 102, 103]
        # ...and must be carried (not lost) for the next loop iteration
        assert len(pipe._carry_drain) == 1
        carry_X, carry_offs = pipe._carry_drain[0]
        assert carry_offs.tolist() == [0, 1, 2, 3]
        assert carry_X.shape[0] == 4
