"""BayesianNetworkModel (discrete, fully-observed Markov blanket):
compiled vs oracle vs hand-computed posterior on the classic
rain/sprinkler/grass network."""

import numpy as np
import pytest

from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml
from flink_jpmml_tpu.pmml.interp import evaluate
from flink_jpmml_tpu.utils.exceptions import ModelLoadingException

BN = """<PMML version="4.3"><DataDictionary>
  <DataField name="rain" optype="categorical" dataType="string">
    <Value value="yes"/><Value value="no"/></DataField>
  <DataField name="sprinkler" optype="categorical" dataType="string">
    <Value value="on"/><Value value="off"/></DataField>
  <DataField name="grass" optype="categorical" dataType="string">
    <Value value="wet"/><Value value="dry"/></DataField>
  </DataDictionary>
  <BayesianNetworkModel functionName="classification">
  <MiningSchema><MiningField name="rain" usageType="target"/>
    <MiningField name="sprinkler"/><MiningField name="grass"/></MiningSchema>
  <BayesianNetworkNodes>
    <DiscreteNode name="rain">
      <ValueProbability value="yes" probability="0.2"/>
      <ValueProbability value="no" probability="0.8"/>
    </DiscreteNode>
    <DiscreteNode name="sprinkler">
      <DiscreteConditionalProbability>
        <ParentValue parent="rain" value="yes"/>
        <ValueProbability value="on" probability="0.01"/>
        <ValueProbability value="off" probability="0.99"/>
      </DiscreteConditionalProbability>
      <DiscreteConditionalProbability>
        <ParentValue parent="rain" value="no"/>
        <ValueProbability value="on" probability="0.4"/>
        <ValueProbability value="off" probability="0.6"/>
      </DiscreteConditionalProbability>
    </DiscreteNode>
    <DiscreteNode name="grass">
      <DiscreteConditionalProbability>
        <ParentValue parent="sprinkler" value="on"/>
        <ParentValue parent="rain" value="yes"/>
        <ValueProbability value="wet" probability="0.99"/>
        <ValueProbability value="dry" probability="0.01"/>
      </DiscreteConditionalProbability>
      <DiscreteConditionalProbability>
        <ParentValue parent="sprinkler" value="on"/>
        <ParentValue parent="rain" value="no"/>
        <ValueProbability value="wet" probability="0.9"/>
        <ValueProbability value="dry" probability="0.1"/>
      </DiscreteConditionalProbability>
      <DiscreteConditionalProbability>
        <ParentValue parent="sprinkler" value="off"/>
        <ParentValue parent="rain" value="yes"/>
        <ValueProbability value="wet" probability="0.8"/>
        <ValueProbability value="dry" probability="0.2"/>
      </DiscreteConditionalProbability>
      <DiscreteConditionalProbability>
        <ParentValue parent="sprinkler" value="off"/>
        <ParentValue parent="rain" value="no"/>
        <ValueProbability value="wet" probability="0.0"/>
        <ValueProbability value="dry" probability="1.0"/>
      </DiscreteConditionalProbability>
    </DiscreteNode>
  </BayesianNetworkNodes>
  </BayesianNetworkModel></PMML>"""


def _hand_posterior(sprinkler, grass):
    p_spr = {"yes": {"on": 0.01, "off": 0.99}, "no": {"on": 0.4, "off": 0.6}}
    p_grass = {
        ("on", "yes"): {"wet": 0.99, "dry": 0.01},
        ("on", "no"): {"wet": 0.9, "dry": 0.1},
        ("off", "yes"): {"wet": 0.8, "dry": 0.2},
        ("off", "no"): {"wet": 0.0, "dry": 1.0},
    }
    prior = {"yes": 0.2, "no": 0.8}
    score = {
        s: prior[s] * p_spr[s][sprinkler] * p_grass[(sprinkler, s)][grass]
        for s in ("yes", "no")
    }
    z = sum(score.values())
    return {s: v / z for s, v in score.items()}


class TestBayesianNetwork:
    def test_posterior_parity_all_evidence(self):
        doc = parse_pmml(BN)
        cm = compile_pmml(doc)
        for sprinkler in ("on", "off"):
            for grass in ("wet", "dry"):
                rec = {"sprinkler": sprinkler, "grass": grass}
                hand = _hand_posterior(sprinkler, grass)
                o = evaluate(doc, rec)
                assert o.probabilities["yes"] == pytest.approx(
                    hand["yes"], rel=1e-9
                )
                p = cm.score_records([rec])[0]
                win = max(hand, key=hand.get)
                assert o.label == win and p.target.label == win
                assert p.target.probabilities["yes"] == pytest.approx(
                    hand["yes"], rel=1e-4
                )
                assert p.score.value == pytest.approx(hand[win], rel=1e-4)

    def test_zero_probability_state(self):
        # sprinkler=off, grass=wet: P(wet|off,no)=0 kills rain=no entirely
        doc = parse_pmml(BN)
        cm = compile_pmml(doc)
        rec = {"sprinkler": "off", "grass": "wet"}
        p = cm.score_records([rec])[0]
        assert p.target.label == "yes"
        assert p.target.probabilities["no"] == pytest.approx(0.0, abs=1e-6)

    def test_impossible_evidence_empty_both_paths(self):
        # P(wet | off, yes) = 0 AND P(wet | off, no) = 0: the evidence is
        # impossible under every target state — oracle and compiled must
        # BOTH score an empty lane, not a softmax of log-clamp residue
        xml = BN.replace(
            '<ParentValue parent="sprinkler" value="off"/>\n        '
            '<ParentValue parent="rain" value="yes"/>\n        '
            '<ValueProbability value="wet" probability="0.8"/>\n        '
            '<ValueProbability value="dry" probability="0.2"/>',
            '<ParentValue parent="sprinkler" value="off"/>\n        '
            '<ParentValue parent="rain" value="yes"/>\n        '
            '<ValueProbability value="wet" probability="0.0"/>\n        '
            '<ValueProbability value="dry" probability="1.0"/>',
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rec = {"sprinkler": "off", "grass": "wet"}
        assert evaluate(doc, rec).value is None
        assert cm.score_records([rec])[0].is_empty
        # and the possible combos still score
        ok = {"sprinkler": "on", "grass": "wet"}
        assert not cm.score_records([ok])[0].is_empty

    def test_missing_or_unknown_evidence_empty(self):
        doc = parse_pmml(BN)
        cm = compile_pmml(doc)
        assert cm.score_records([{"sprinkler": None, "grass": "wet"}])[0].is_empty
        assert evaluate(doc, {"sprinkler": None, "grass": "wet"}).value is None
        assert evaluate(doc, {"sprinkler": "sideways", "grass": "wet"}).value is None

    def test_rejections(self):
        # hidden (non-active, non-target) node
        with pytest.raises(ModelLoadingException, match="fully-observed"):
            parse_pmml(BN.replace('<MiningField name="sprinkler"/>', ""))
        # unknown parent (renamed consistently in both sprinkler rows)
        sprinkler_block = BN[
            BN.index('<DiscreteNode name="sprinkler">'):
            BN.index('<DiscreteNode name="grass">')
        ]
        with pytest.raises(ModelLoadingException, match="unknown parent"):
            parse_pmml(BN.replace(
                sprinkler_block,
                sprinkler_block.replace('parent="rain"', 'parent="wind"'),
            ))
        # value lists must agree across rows
        with pytest.raises(ModelLoadingException, match="disagree"):
            parse_pmml(BN.replace(
                '<ValueProbability value="on" probability="0.4"/>',
                '<ValueProbability value="ON" probability="0.4"/>',
            ))

    def test_dp_sharded(self):
        from flink_jpmml_tpu.parallel import make_mesh
        from flink_jpmml_tpu.parallel.sharding import dp_sharded
        from flink_jpmml_tpu.utils.config import MeshConfig
        from flink_jpmml_tpu.compile import prepare

        import jax

        if len(jax.devices()) < 8:
            # FJT_TEST_PLATFORM=default on a 1-chip host: the virtual
            # 8-CPU mesh is unavailable; the sharding path is covered by
            # the CPU-mesh run (tests/conftest.py)
            pytest.skip("needs the 8-device virtual mesh")

        doc = parse_pmml(BN)
        cm = compile_pmml(doc)
        rng = np.random.default_rng(0)
        recs = [
            {
                "sprinkler": str(rng.choice(["on", "off"])),
                "grass": str(rng.choice(["wet", "dry"])),
            }
            for _ in range(64)
        ]
        X, M = prepare.from_records(cm.field_space, recs)
        ref = cm.predict(X, M)
        sm = dp_sharded(cm, make_mesh(MeshConfig(data=8, model=1)))
        out = sm.predict(X, M)
        np.testing.assert_allclose(
            np.asarray(out.value), np.asarray(ref.value), rtol=1e-5
        )
        np.testing.assert_array_equal(
            np.asarray(out.label_idx), np.asarray(ref.label_idx)
        )
