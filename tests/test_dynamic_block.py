"""Dynamic serving on the block path (serving/block.py): VERDICT r2
missing #2 — Add/warm/swap/Del at block speed, no in-flight drain, offsets
exactly-once across the swap, records held (not lost) through registry
gaps."""

import pathlib
import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # serving swap/SLO drills (-m 'not slow' = fast inner loop)

from assets.generate import gen_gbm
from flink_jpmml_tpu.models.control import AddMessage, DelMessage
from flink_jpmml_tpu.runtime.block import CyclingBlockSource, FiniteBlockSource
from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
from flink_jpmml_tpu.runtime.sources import ControlSource
from flink_jpmml_tpu.serving.block import DynamicBlockPipeline
from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig

F = 4
B = 32


def _gbms(tmp_path, *specs):
    """specs: (subdir, n_trees, depth[, n_features]) → pmml paths."""
    out = []
    for spec in specs:
        sub, n_trees, depth = spec[:3]
        nf = spec[3] if len(spec) > 3 else F
        d = pathlib.Path(tmp_path, sub)
        d.mkdir(parents=True, exist_ok=True)
        out.append(
            gen_gbm(str(d), n_trees=n_trees, depth=depth, n_features=nf)
        )
    return out


def _slow_loader(reg, slow_substr, delay_s):
    orig = reg._load

    def load(info):
        if slow_substr in info.path:
            time.sleep(delay_s)
        return orig(info)

    reg._load = load


class _RecordingSink:
    """Collects (first_offset, n, model_key, t_wall) per sunk batch."""

    def __init__(self, decode_every: int = 0):
        self.rows = []
        self.decoded = []
        self._lock = threading.Lock()
        self._decode_every = decode_every

    def __call__(self, out, n, first_off, decode):
        if self._decode_every and len(self.rows) % self._decode_every == 0:
            preds = decode(out, n)
            with self._lock:
                self.decoded.append((first_off, preds))
        with self._lock:
            self.rows.append(
                (first_off, n, decode.model_key, time.monotonic())
            )

    def total(self):
        with self._lock:
            return sum(n for _, n, _, _ in self.rows)

    def assert_offsets_contiguous(self, start=0):
        with self._lock:
            rows = list(self.rows)
        expect = start
        for first, n, _, _ in rows:
            assert first == expect, f"offset gap: {first} != {expect}"
            expect = first + n


def _cfg():
    return RuntimeConfig(batch=BatchConfig(size=B, deadline_us=2000))


def _wait(cond, timeout=30.0, msg="condition never held"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError(msg)


class TestDynamicBlockPipeline:
    def test_add_warm_swap_del_cycle_no_stall(self, tmp_path):
        """Blocks score continuously while v2 warms (its fetch sleeps
        1.2s); the swap happens between batches; Del of v2 falls back to
        v1; offsets stay contiguous end to end."""
        v1, v2 = _gbms(tmp_path, ("v1", 3, 3), ("v2", 40, 4))
        rng = np.random.default_rng(0)
        data = rng.normal(0, 1.5, size=(1024, F)).astype(np.float32)
        ctrl = ControlSource()
        sink = _RecordingSink()
        pipe = DynamicBlockPipeline(
            CyclingBlockSource(data, block_size=64),
            ctrl, sink, name="m", arity=F, batch_size=B,
            config=_cfg(), use_native=False,
        )
        _slow_loader(pipe.registry, "v2", 1.2)
        ctrl.push(AddMessage("m", 1, v1, timestamp=1.0))
        pipe.start()
        try:
            _wait(lambda: sink.total() > 0, msg="v1 never served")
            _wait(lambda: pipe.serving_key == "m_1")
            t_add = time.monotonic()
            ctrl.push(AddMessage("m", 2, v2, timestamp=2.0))
            _wait(lambda: pipe.serving_key == "m_2", timeout=60.0,
                  msg="v2 never swapped in")
            t_swap = time.monotonic()
            assert t_swap - t_add >= 1.2  # the warm was genuinely slow
            # continuity through the warm window: no sink gap anywhere
            # near the 1.2s+compile stall the swap would cost if done
            # synchronously
            with sink._lock:
                stamps = [t for _, _, _, t in sink.rows
                          if t_add - 0.5 <= t <= t_swap + 0.5]
            gaps = np.diff(stamps)
            assert len(stamps) > 10
            assert gaps.max() < 0.6, f"stall {gaps.max():.2f}s during warm"
            ctrl.push(DelMessage("m", 2, timestamp=3.0))
            _wait(lambda: pipe.serving_key == "m_1",
                  msg="Del never fell back to v1")
        finally:
            pipe.stop()
            pipe.join(timeout=30.0)
        sink.assert_offsets_contiguous()
        # batches before the swap were scored (and decodable) by v1,
        # after it by v2 — both keys must appear
        keys = {k for _, _, k, _ in sink.rows}
        assert {"m_1", "m_2"} <= keys

    def test_dynamic_serving_over_kafka_wire(self, tmp_path):
        """C6 on the Kafka wire: Add v1 → score → Add v2 → swap, the
        stream arriving as real record batches through KafkaBlockSource,
        offsets contiguous end to end."""
        from flink_jpmml_tpu.runtime.kafka import (
            KafkaBlockSource, MiniKafkaBroker,
        )

        v1, v2 = _gbms(tmp_path, ("v1", 3, 3), ("v2", 8, 3))
        rng = np.random.default_rng(7)
        data = rng.normal(0, 1.5, size=(4096, F)).astype(np.float32)
        broker = MiniKafkaBroker(topic="dyn")
        broker.append_rows(data)
        ctrl = ControlSource()
        sink = _RecordingSink()
        src = KafkaBlockSource(
            broker.host, broker.port, "dyn", n_cols=F, max_wait_ms=20
        )
        pipe = DynamicBlockPipeline(
            src, ctrl, sink, name="m", arity=F, batch_size=B,
            config=_cfg(), use_native=False,
        )
        ctrl.push(AddMessage("m", 1, v1, timestamp=1.0))
        pipe.start()
        try:
            _wait(lambda: sink.total() > 256, msg="v1 never served")
            ctrl.push(AddMessage("m", 2, v2, timestamp=2.0))
            _wait(lambda: pipe.serving_key == "m_2", timeout=60.0,
                  msg="v2 never swapped in")
            # the finite log may drain before the swap lands: produce a
            # second wave so v2 provably scores live Kafka traffic
            broker.append_rows(data[:1024])
            _wait(
                lambda: any(
                    k == "m_2" for _, _, k, _ in list(sink.rows)
                ),
                msg="no batch scored by v2",
            )
        finally:
            pipe.stop()
            pipe.join(timeout=30.0)
            src.close()
            broker.close()
        sink.assert_offsets_contiguous()
        keys = {k for _, _, k, _ in sink.rows}
        assert {"m_1", "m_2"} <= keys

    def test_records_held_not_lost_through_registry_gap(self, tmp_path):
        """Stream starts before any model is served: batches are held
        (ring backpressure), never dropped; once a model arrives every
        record scores exactly once."""
        (v1,) = _gbms(tmp_path, ("v1", 3, 3))
        rng = np.random.default_rng(1)
        n_total = 500
        data = rng.normal(size=(n_total, F)).astype(np.float32)
        ctrl = ControlSource()
        sink = _RecordingSink(decode_every=3)
        pipe = DynamicBlockPipeline(
            FiniteBlockSource(data, block_size=100),
            ctrl, sink, name="m", arity=F, batch_size=B,
            config=_cfg(), use_native=False,
        )
        pipe.start()
        time.sleep(0.4)  # stream runs with nothing served
        assert sink.total() == 0
        ctrl.push(AddMessage("m", 1, v1, timestamp=1.0))
        deadline = time.monotonic() + 60.0
        while sink.total() < n_total and time.monotonic() < deadline:
            time.sleep(0.02)
        pipe._drain_all = True
        pipe.stop()
        pipe.join(timeout=30.0)
        assert sink.total() == n_total
        sink.assert_offsets_contiguous()
        assert pipe.committed_offset == n_total
        # decode works through the sink's 4th argument
        assert sink.decoded and all(
            len(p) > 0 for _, p in sink.decoded
        )

    def test_arity_mismatch_quarantined_not_served(self, tmp_path):
        bad, good = _gbms(tmp_path, ("bad", 3, 3, 6), ("good", 3, 3))
        rng = np.random.default_rng(2)
        data = rng.normal(size=(256, F)).astype(np.float32)
        ctrl = ControlSource()
        sink = _RecordingSink()
        pipe = DynamicBlockPipeline(
            CyclingBlockSource(data, block_size=64),
            ctrl, sink, name="m", arity=F, batch_size=B,
            config=_cfg(), use_native=False,
        )
        ctrl.push(AddMessage("m", 1, bad, timestamp=1.0))
        pipe.start()
        try:
            _wait(
                lambda: pipe.metrics.counter("arity_rejected_models").get()
                >= 1,
                msg="mismatched model never rejected",
            )
            assert pipe.serving_key is None and sink.total() == 0
            ctrl.push(AddMessage("m", 2, good, timestamp=2.0))
            _wait(lambda: pipe.serving_key == "m_2", timeout=60.0)
            _wait(lambda: sink.total() > 0)
        finally:
            pipe.stop()
            pipe.join(timeout=30.0)
        sink.assert_offsets_contiguous()

    def test_checkpoint_resume_across_swap(self, tmp_path):
        """Kill after a swap; a fresh pipeline restores the committed
        offset AND the served-model metadata, then finishes the stream
        from exactly where the first left off."""
        v1, v2 = _gbms(tmp_path, ("v1", 3, 3), ("v2", 5, 3))
        rng = np.random.default_rng(3)
        n_total = 6000
        data = rng.normal(size=(n_total, F)).astype(np.float32)
        ckpt = CheckpointManager(str(pathlib.Path(tmp_path, "ck")))

        class _Throttled(FiniteBlockSource):
            """Paces ingest so the stream outlives the v2 warm."""

            def poll(self):
                r = super().poll()
                if r is not None:
                    time.sleep(0.05)
                return r

        ctrl = ControlSource()
        sink1 = _RecordingSink()
        p1 = DynamicBlockPipeline(
            _Throttled(data, block_size=50),
            ctrl, sink1, name="m", arity=F, batch_size=B,
            config=_cfg(), use_native=False, checkpoint=ckpt,
        )
        ctrl.push(AddMessage("m", 1, v1, timestamp=1.0))
        p1.start()
        _wait(lambda: sink1.total() > 0, msg="first run never scored")
        ctrl.push(AddMessage("m", 2, v2, timestamp=2.0))
        _wait(lambda: p1.serving_key == "m_2", timeout=60.0)
        _wait(lambda: sink1.total() > 200)
        p1.stop()  # kill mid-stream: uncommitted backlog is discarded
        p1.join(timeout=30.0)
        done1 = p1.committed_offset
        assert 0 < done1 < n_total

        ctrl2 = ControlSource()  # nothing pushed: state comes from ckpt
        sink2 = _RecordingSink()
        p2 = DynamicBlockPipeline(
            FiniteBlockSource(data, block_size=100),
            ctrl2, sink2, name="m", arity=F, batch_size=B,
            config=_cfg(), use_native=False, checkpoint=ckpt,
        )
        assert p2.restore()
        assert p2.committed_offset == done1
        # restored registry still serves both versions; newest wins
        assert {m.key() for m in p2.registry.served} == {"m_1", "m_2"}
        p2.run_until_exhausted(timeout=60.0)
        assert p2.serving_key is not None  # restored metadata re-warmed
        sink2.assert_offsets_contiguous(start=done1)
        assert done1 + sink2.total() == n_total


class TestReviewRegressions:
    """Round-3 code-review findings on this module, pinned."""

    def test_del_readd_same_version_new_document_swaps(self, tmp_path):
        """Del('m',1) + Add('m',1, different doc) must adopt the NEW
        compiled model even though the (name, version) key is unchanged
        — adoption is judged per compiled instance."""
        a, b = _gbms(tmp_path, ("a", 3, 3), ("b", 17, 4))
        rng = np.random.default_rng(7)
        data = rng.normal(size=(512, F)).astype(np.float32)
        ctrl = ControlSource()
        sink = _RecordingSink()
        pipe = DynamicBlockPipeline(
            CyclingBlockSource(data, block_size=64),
            ctrl, sink, name="m", arity=F, batch_size=B,
            config=_cfg(), use_native=False,
        )
        ctrl.push(AddMessage("m", 1, a, timestamp=1.0))
        pipe.start()
        try:
            _wait(lambda: pipe.serving_key == "m_1", timeout=60.0)
            model_a = pipe._current.model
            ctrl.push(DelMessage("m", 1, timestamp=2.0))
            ctrl.push(AddMessage("m", 1, b, timestamp=3.0))
            _wait(
                lambda: pipe._current is not None
                and pipe._current.model is not model_a,
                timeout=60.0,
                msg="re-Add with a new document never swapped in",
            )
            assert pipe.serving_key == "m_1"  # same id, new weights
        finally:
            pipe.stop()
            pipe.join(timeout=30.0)
        sink.assert_offsets_contiguous()

    def test_run_until_exhausted_bounded_when_nothing_servable(
        self, tmp_path
    ):
        """A finite stream with no servable model must not hang the
        drain: the hold is bounded, the loop gives up, records stay
        uncommitted (replayable)."""
        rng = np.random.default_rng(8)
        data = rng.normal(size=(200, F)).astype(np.float32)
        sink = _RecordingSink()
        pipe = DynamicBlockPipeline(
            FiniteBlockSource(data, block_size=50),
            ControlSource(), sink, name="m", arity=F, batch_size=B,
            config=_cfg(), use_native=False,
            drain_hold_timeout_s=1.0,
        )
        t0 = time.monotonic()
        pipe.run_until_exhausted(timeout=30.0)
        assert time.monotonic() - t0 < 20.0
        for t in pipe._threads:
            assert not t.is_alive()
        assert sink.total() == 0
        assert pipe.committed_offset == 0  # nothing falsely committed

    def test_arity_quarantine_cleared_by_registry_change(self, tmp_path):
        """A corrected document re-Added under the same (name, version)
        must serve — the quarantine resets on any registry change."""
        bad, good = _gbms(tmp_path, ("bad", 3, 3, 6), ("good", 3, 3))
        rng = np.random.default_rng(9)
        data = rng.normal(size=(256, F)).astype(np.float32)
        ctrl = ControlSource()
        sink = _RecordingSink()
        pipe = DynamicBlockPipeline(
            CyclingBlockSource(data, block_size=64),
            ctrl, sink, name="m", arity=F, batch_size=B,
            config=_cfg(), use_native=False,
        )
        ctrl.push(AddMessage("m", 1, bad, timestamp=1.0))
        pipe.start()
        try:
            _wait(
                lambda: pipe.metrics.counter("arity_rejected_models").get()
                >= 1,
                msg="mismatched model never rejected",
            )
            ctrl.push(DelMessage("m", 1, timestamp=2.0))
            ctrl.push(AddMessage("m", 1, good, timestamp=3.0))
            _wait(lambda: pipe.serving_key == "m_1", timeout=60.0,
                  msg="corrected re-Add stayed quarantined")
            _wait(lambda: sink.total() > 0)
        finally:
            pipe.stop()
            pipe.join(timeout=30.0)


class TestIdleStreamControl:
    def test_ring_idle_bounded_drain(self):
        """drain(idle_timeout_us>=0) returns empty on an open, starved
        ring instead of parking forever — both ring implementations."""
        from flink_jpmml_tpu.runtime import native
        from flink_jpmml_tpu.runtime.block import _PyRing

        rings = [_PyRing(64, 4, 16)]
        if native.available():
            rings.append(native.NativeRing(64, 4, 16))
        for ring in rings:
            t0 = time.monotonic()
            X, offs = ring.drain(1000, 30_000)
            dt = time.monotonic() - t0
            assert X.shape[0] == 0 and offs.shape[0] == 0
            assert 0.01 < dt < 2.0, f"idle drain took {dt:.3f}s"
            ring.close()

    def test_control_applies_on_idle_stream(self, tmp_path):
        """No records flowing at all: Add must still kick the background
        warm and the pipeline must adopt the model (the review found the
        score thread parked in ring.drain, deaf to control)."""
        from flink_jpmml_tpu.runtime.block import BlockSource

        (v1,) = _gbms(tmp_path, ("v1", 3, 3))

        class _Starved(BlockSource):
            def poll(self):
                time.sleep(0.001)
                return None

        ctrl = ControlSource()
        sink = _RecordingSink()
        pipe = DynamicBlockPipeline(
            _Starved(), ctrl, sink, name="m", arity=F, batch_size=B,
            config=_cfg(), use_native=False,
        )
        pipe.start()
        try:
            time.sleep(0.2)  # score thread parked on the starved ring
            ctrl.push(AddMessage("m", 1, v1, timestamp=1.0))
            _wait(
                lambda: pipe.serving_key == "m_1",
                timeout=60.0,
                msg="Add never applied while the stream was idle",
            )
            assert sink.total() == 0  # adopted with zero records flowing
        finally:
            pipe.stop()
            pipe.join(timeout=30.0)


class TestKafkaDynamicServing:
    def test_add_swap_over_kafka_wire(self, tmp_path):
        """The marquee combination end to end: dynamic serving at block
        speed fed by the real Kafka wire protocol — records stream
        continuously while a model is added, upgraded (background warm +
        swap), and the offsets stay contiguous through it all."""
        from flink_jpmml_tpu.runtime.kafka import (
            KafkaBlockSource, MiniKafkaBroker,
        )

        v1, v2 = _gbms(tmp_path, ("v1", 6, 3), ("v2", 12, 4))
        rng = np.random.default_rng(3)
        N = 6000
        data = rng.normal(0, 1.5, size=(N, F)).astype(np.float32)
        broker = MiniKafkaBroker(topic="feed")
        try:
            # live feed: first half now, second half only after the
            # swap — so v2 deterministically serves real records
            broker.append_rows(data[: N // 2])
            src = KafkaBlockSource(
                broker.host, broker.port, "feed", n_cols=F, max_wait_ms=20
            )
            ctrl = ControlSource()
            sink = _RecordingSink()
            pipe = DynamicBlockPipeline(
                src, ctrl, sink, name="m", arity=F, batch_size=B,
                config=_cfg(), use_native=False,
            )
            ctrl.push(AddMessage("m", 1, v1, timestamp=1.0))
            pipe.start()
            _wait(lambda: sink.total() > 500, msg="v1 never served")
            assert pipe.serving_key == "m_1"
            ctrl.push(AddMessage("m", 2, v2, timestamp=2.0))
            _wait(lambda: pipe.serving_key == "m_2", msg="swap to v2")
            broker.append_rows(data[N // 2 :])
            _wait(
                lambda: sink.total() >= N,
                msg="stream never drained", timeout=30.0,
            )
            pipe.stop()
            pipe.join(timeout=15.0)
            src.close()
            sink.assert_offsets_contiguous()
            keys = {k for _, _, k, _ in sink.rows}
            assert keys == {"m_1", "m_2"}  # both versions actually served
        finally:
            broker.close()
