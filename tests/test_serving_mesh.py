"""Dynamic serving × mesh (round-4 VERDICT #4b/#5): the registry/block
serving plane produces and swaps ShardedModels when a mesh is
configured — warm = parse + mesh-aware compile + re-jit in the
background, swap between batches exactly like single-device serving.

Runs on the virtual 8-CPU mesh (tests/conftest.py)."""

import pathlib
import threading
import time

import numpy as np
import pytest

from flink_jpmml_tpu.assets_gen import gen_stacked
from flink_jpmml_tpu.models.control import AddMessage
from flink_jpmml_tpu.parallel.mesh import make_mesh
from flink_jpmml_tpu.parallel.sharding import ShardedModel
from flink_jpmml_tpu.runtime.block import CyclingBlockSource
from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
from flink_jpmml_tpu.runtime.sources import ControlSource
from flink_jpmml_tpu.serving.block import DynamicBlockPipeline
from flink_jpmml_tpu.serving.registry import ModelRegistry
from flink_jpmml_tpu.utils.config import (
    BatchConfig, CompileConfig, MeshConfig, RuntimeConfig,
)
from flink_jpmml_tpu.utils.exceptions import InputValidationException

F = 256  # wide enough to TP-shard under a lowered threshold
B = 32
CFG = CompileConfig(tp_wide_threshold=64)


def _stacked(tmp_path, sub, n_trees):
    d = pathlib.Path(tmp_path, sub)
    d.mkdir(parents=True, exist_ok=True)
    return gen_stacked(
        str(d), n_trees=n_trees, depth=3, n_features=F, wide_lr=True
    )


class _Sink:
    def __init__(self):
        self.rows = []
        self._lock = threading.Lock()

    def __call__(self, out, n, first_off, decode):
        with self._lock:
            self.rows.append((first_off, n, decode.model_key))

    def total(self):
        with self._lock:
            return sum(n for _, n, _ in self.rows)


def _wait(cond, timeout=60.0, msg="condition never held"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError(msg)


class TestRegistryMesh:
    def test_warm_produces_sharded_model(self, tmp_path):
        mesh = make_mesh(MeshConfig(data=4, model=2))
        reg = ModelRegistry(
            batch_size=B, compile_config=CFG, mesh=mesh,
            async_warmup=False,
        )
        path = _stacked(tmp_path, "v1", 3)
        reg.apply(AddMessage("m", 1, path, timestamp=1.0))
        from flink_jpmml_tpu.models.core import ModelId

        model = reg.model(ModelId("m", 1))
        assert isinstance(model, ShardedModel)
        assert model.tp_sharded_leaves  # the wide LR stage is TP-sharded

    def test_restore_warms_sharded(self, tmp_path):
        mesh = make_mesh(MeshConfig(data=4, model=2))
        path = _stacked(tmp_path, "v1", 3)
        reg = ModelRegistry(batch_size=B, compile_config=CFG, mesh=mesh)
        reg.apply(AddMessage("m", 1, path, timestamp=1.0))
        state = reg.state()

        reg2 = ModelRegistry(batch_size=B, compile_config=CFG, mesh=mesh)
        reg2.restore(state)
        from flink_jpmml_tpu.models.core import ModelId

        _wait(
            lambda: reg2.model_if_warm(ModelId("m", 1)) is not None,
            msg="restored registry never warmed",
        )
        assert isinstance(
            reg2.model_if_warm(ModelId("m", 1)), ShardedModel
        )


class TestDynamicBlockMesh:
    def test_swap_drill_on_mesh(self, tmp_path):
        """Add v1 → serve sharded → Add v2 → background mesh-compile →
        swap between batches; offsets contiguous; both versions score
        through ShardedModel on the virtual 8-device mesh."""
        mesh = make_mesh(MeshConfig(data=4, model=2))
        v1 = _stacked(tmp_path, "v1", 3)
        v2 = _stacked(tmp_path, "v2", 8)
        rng = np.random.default_rng(0)
        data = rng.normal(0, 1.0, size=(1024, F)).astype(np.float32)
        ctrl = ControlSource()
        sink = _Sink()
        pipe = DynamicBlockPipeline(
            CyclingBlockSource(data, block_size=64),
            ctrl, sink, name="m", arity=F, batch_size=B,
            config=RuntimeConfig(batch=BatchConfig(size=B, deadline_us=2000)),
            compile_config=CFG,
            use_native=False,
            mesh=mesh,
        )
        ctrl.push(AddMessage("m", 1, v1, timestamp=1.0))
        pipe.start()
        try:
            _wait(lambda: sink.total() > 0, msg="v1 never served")
            assert pipe.serving_key == "m_1"
            cur = pipe._current.model
            assert isinstance(cur, ShardedModel)
            assert cur.tp_sharded_leaves
            assert pipe.backend == "f32"  # rank wire is single-device
            ctrl.push(AddMessage("m", 2, v2, timestamp=2.0))
            _wait(lambda: pipe.serving_key == "m_2",
                  msg="v2 never swapped in")
            assert isinstance(pipe._current.model, ShardedModel)
            _wait(lambda: sink.total() > 256)
        finally:
            pipe.stop()
            pipe.join(timeout=30.0)
        # offsets exactly-once across the swap
        expect = 0
        for first, n, _ in sink.rows:
            assert first == expect
            expect = first + n
        assert {k for _, _, k in sink.rows} >= {"m_1", "m_2"}

    def test_checkpoint_restore_under_mesh(self, tmp_path):
        """Kill/restart with a mesh configured: the restored pipeline
        re-warms its served models AS ShardedModels and resumes at the
        committed offset (VERDICT r4 weak #4: restore under the mesh)."""
        mesh = make_mesh(MeshConfig(data=4, model=2))
        v1 = _stacked(tmp_path, "v1", 3)
        rng = np.random.default_rng(1)
        data = rng.normal(0, 1.0, size=(2048, F)).astype(np.float32)
        ckdir = str(tmp_path / "ck")
        cfg = RuntimeConfig(
            batch=BatchConfig(size=B, deadline_us=2000),
            checkpoint_interval_s=0.05,
        )
        ctrl = ControlSource()
        sink = _Sink()
        pipe = DynamicBlockPipeline(
            CyclingBlockSource(data, block_size=64),
            ctrl, sink, name="m", arity=F, batch_size=B,
            config=cfg, compile_config=CFG, use_native=False, mesh=mesh,
            checkpoint=CheckpointManager(ckdir),
        )
        ctrl.push(AddMessage("m", 1, v1, timestamp=1.0))
        pipe.start()
        _wait(lambda: pipe.committed_offset > 64)
        pipe.stop()
        pipe.join(timeout=30.0)
        committed = pipe.committed_offset
        assert committed > 0

        ctrl2 = ControlSource()
        sink2 = _Sink()
        pipe2 = DynamicBlockPipeline(
            CyclingBlockSource(data, block_size=64),
            ctrl2, sink2, name="m", arity=F, batch_size=B,
            config=cfg, compile_config=CFG, use_native=False, mesh=mesh,
            checkpoint=CheckpointManager(ckdir),
        )
        assert pipe2.restore()
        assert pipe2.committed_offset == committed
        # the restored registry re-serves m_1 (no new Add) sharded
        pipe2.start()
        try:
            _wait(lambda: sink2.total() > 0, msg="restored never served")
            assert isinstance(pipe2._current.model, ShardedModel)
            assert sink2.rows[0][0] == committed  # resumes exactly
        finally:
            pipe2.stop()
            pipe2.join(timeout=30.0)

    def test_indivisible_batch_rejected(self, tmp_path):
        mesh = make_mesh(MeshConfig(data=4, model=2))
        with pytest.raises(InputValidationException, match="divide"):
            DynamicBlockPipeline(
                CyclingBlockSource(
                    np.zeros((64, F), np.float32), block_size=64
                ),
                ControlSource(), lambda *a: None, name="m", arity=F,
                batch_size=30, mesh=mesh,
            )
