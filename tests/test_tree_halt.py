"""lastPrediction / returnLastPrediction halting strategies vs the oracle.

The oracle (interp._eval_tree) returns the last *scored* node on the path
when a missing value halts traversal; the iterative backend tracks that
ancestor's node index per (record, tree) lane.
"""

import numpy as np

from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml
from flink_jpmml_tpu.pmml.interp import evaluate


def _tree_doc(strategy, ntc=None, interior_scores=True):
    s0 = ' score="0.5"' if interior_scores else ""
    s1 = ' score="0.7"' if interior_scores else ""
    ntc_attr = f' noTrueChildStrategy="{ntc}"' if ntc else ""
    return parse_pmml(f"""<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
      <Header/>
      <DataDictionary numberOfFields="3">
        <DataField name="a" optype="continuous" dataType="double"/>
        <DataField name="b" optype="continuous" dataType="double"/>
        <DataField name="y" optype="continuous" dataType="double"/>
      </DataDictionary>
      <TreeModel functionName="regression" missingValueStrategy="{strategy}"
                 splitCharacteristic="binarySplit"{ntc_attr}>
        <MiningSchema>
          <MiningField name="y" usageType="target"/>
          <MiningField name="a"/><MiningField name="b"/>
        </MiningSchema>
        <Node id="0"{s0}><True/>
          <Node id="1"{s1}>
            <SimplePredicate field="a" operator="lessThan" value="0"/>
            <Node id="3" score="1.0">
              <SimplePredicate field="b" operator="lessThan" value="0"/>
            </Node>
            <Node id="4" score="2.0">
              <SimplePredicate field="b" operator="greaterOrEqual" value="0"/>
            </Node>
          </Node>
          <Node id="2" score="3.0">
            <SimplePredicate field="a" operator="greaterOrEqual" value="0"/>
          </Node>
        </Node>
      </TreeModel></PMML>""")


def _check(doc, records):
    cm = compile_pmml(doc)
    got = cm.score_records(records)
    for rec, pred in zip(records, got):
        exp = evaluate(doc, rec)
        if exp.value is None:
            assert pred.is_empty, f"{rec}: expected empty, got {pred}"
        else:
            assert not pred.is_empty, f"{rec}: expected {exp.value}, got empty"
            assert abs(pred.score.value - exp.value) < 1e-6, (
                f"{rec}: {pred.score.value} != {exp.value}"
            )


RECORDS = [
    {"a": -1.0, "b": -1.0},   # leaf 3
    {"a": -1.0, "b": 1.0},    # leaf 4
    {"a": 1.0, "b": 0.0},     # leaf 2
    {"a": -1.0},              # b missing at depth 2
    {"b": 1.0},               # a missing at root
    {},                       # everything missing
]


class TestLastPrediction:
    def test_interior_scores_return_last_scored(self):
        _check(_tree_doc("lastPrediction"), RECORDS)

    def test_no_interior_scores_yield_empty_on_halt(self):
        # halting with no scored ancestor -> EmptyScore (oracle: EvalResult())
        _check(_tree_doc("lastPrediction", interior_scores=False), RECORDS)

    def test_none_with_return_last_prediction(self):
        _check(
            _tree_doc("none", ntc="returnLastPrediction"), RECORDS
        )

    def test_none_with_null_prediction_ntc(self):
        _check(_tree_doc("none", ntc="returnNullPrediction"), RECORDS)

    def test_ensemble_of_halting_trees(self):
        # sum of two lastPrediction trees inside a MiningModel
        import xml.etree.ElementTree as ET

        doc1 = _tree_doc("lastPrediction")
        xml = f"""<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
          <Header/>
          <DataDictionary numberOfFields="3">
            <DataField name="a" optype="continuous" dataType="double"/>
            <DataField name="b" optype="continuous" dataType="double"/>
            <DataField name="y" optype="continuous" dataType="double"/>
          </DataDictionary>
          <MiningModel functionName="regression">
            <MiningSchema>
              <MiningField name="y" usageType="target"/>
              <MiningField name="a"/><MiningField name="b"/>
            </MiningSchema>
            <Segmentation multipleModelMethod="sum">
              <Segment><True/>
                <TreeModel functionName="regression" missingValueStrategy="lastPrediction" splitCharacteristic="binarySplit">
                  <MiningSchema><MiningField name="y" usageType="target"/><MiningField name="a"/><MiningField name="b"/></MiningSchema>
                  <Node id="0" score="0.25"><True/>
                    <Node id="1" score="1.5"><SimplePredicate field="a" operator="lessThan" value="0"/></Node>
                    <Node id="2" score="-2.0"><SimplePredicate field="a" operator="greaterOrEqual" value="0"/></Node>
                  </Node>
                </TreeModel>
              </Segment>
              <Segment><True/>
                <TreeModel functionName="regression" missingValueStrategy="lastPrediction" splitCharacteristic="binarySplit">
                  <MiningSchema><MiningField name="y" usageType="target"/><MiningField name="a"/><MiningField name="b"/></MiningSchema>
                  <Node id="0" score="0.75"><True/>
                    <Node id="1" score="4.0"><SimplePredicate field="b" operator="lessThan" value="1"/></Node>
                    <Node id="2" score="8.0"><SimplePredicate field="b" operator="greaterOrEqual" value="1"/></Node>
                  </Node>
                </TreeModel>
              </Segment>
            </Segmentation>
          </MiningModel></PMML>"""
        _check(parse_pmml(xml), RECORDS)


import pytest

WEIGHTED_CONF = """<PMML version="4.3"><DataDictionary>
  <DataField name="x" optype="continuous" dataType="double"/>
  <DataField name="cls" optype="categorical" dataType="string">
    <Value value="a"/><Value value="b"/></DataField>
  </DataDictionary>
  <TreeModel functionName="classification"
      missingValueStrategy="weightedConfidence">
  <MiningSchema><MiningField name="cls" usageType="target"/>
    <MiningField name="x"/></MiningSchema>
  <Node id="0" recordCount="100"><True/>
    <Node id="L" recordCount="60" score="a">
      <SimplePredicate field="x" operator="lessThan" value="0"/>
      <ScoreDistribution value="a" recordCount="45"/>
      <ScoreDistribution value="b" recordCount="15"/>
    </Node>
    <Node id="R" recordCount="40" score="b">
      <SimplePredicate field="x" operator="greaterOrEqual" value="0"/>
      <ScoreDistribution value="a" recordCount="8"/>
      <ScoreDistribution value="b" recordCount="32"/>
    </Node>
  </Node></TreeModel></PMML>"""

AGG_NODES = """<PMML version="4.3"><DataDictionary>
  <DataField name="x" optype="continuous" dataType="double"/>
  <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <TreeModel functionName="regression"
      missingValueStrategy="aggregateNodes">
  <MiningSchema><MiningField name="y" usageType="target"/>
    <MiningField name="x"/></MiningSchema>
  <Node id="0" recordCount="10"><True/>
    <Node id="L" recordCount="7" score="2.0">
      <SimplePredicate field="x" operator="lessThan" value="1"/></Node>
    <Node id="R" recordCount="3" score="10.0">
      <SimplePredicate field="x" operator="greaterOrEqual" value="1"/></Node>
  </Node></TreeModel></PMML>"""


class TestWeightedStrategies:
    def test_weighted_confidence_observed_and_missing(self):
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.pmml.interp import evaluate

        doc = parse_pmml(WEIGHTED_CONF)
        cm = compile_pmml(doc)
        # observed: deterministic leaf confidences
        for x, exp_a in ((-1.0, 45 / 60), (2.0, 8 / 40)):
            o = evaluate(doc, {"x": x})
            p = cm.score_records([{"x": x}])[0]
            assert o.probabilities["a"] == pytest.approx(exp_a)
            assert p.target.probabilities["a"] == pytest.approx(
                exp_a, abs=1e-5
            )
        # missing x: both leaves weighted 60/40 by recordCount
        exp_a = 0.6 * (45 / 60) + 0.4 * (8 / 40)
        o = evaluate(doc, {"x": None})
        p = cm.score_records([{"x": None}])[0]
        assert o.probabilities["a"] == pytest.approx(exp_a)
        assert o.label == "a"  # 0.53 vs 0.47
        assert p.target.probabilities["a"] == pytest.approx(exp_a, abs=1e-5)
        assert p.target.label == "a"

    def test_aggregate_nodes_observed_and_missing(self):
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.pmml.interp import evaluate

        doc = parse_pmml(AGG_NODES)
        cm = compile_pmml(doc)
        for x, exp in ((0.0, 2.0), (5.0, 10.0)):
            assert evaluate(doc, {"x": x}).value == pytest.approx(exp)
            assert cm.score_records([{"x": x}])[0].score.value == (
                pytest.approx(exp, rel=1e-6)
            )
        exp = 0.7 * 2.0 + 0.3 * 10.0
        assert evaluate(doc, {"x": None}).value == pytest.approx(exp)
        assert cm.score_records([{"x": None}])[0].score.value == (
            pytest.approx(exp, rel=1e-5)
        )

    def test_nested_partial_missing(self):
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.pmml.interp import evaluate

        # second level splits on a different field: missing only below
        xml = AGG_NODES.replace(
            '<Node id="L" recordCount="7" score="2.0">\n      '
            '<SimplePredicate field="x" operator="lessThan" value="1"/></Node>',
            '<Node id="L" recordCount="7">\n      '
            '<SimplePredicate field="x" operator="lessThan" value="1"/>\n'
            '      <Node id="LL" recordCount="5" score="1.0">\n        '
            '<SimplePredicate field="z" operator="lessThan" value="0"/></Node>\n'
            '      <Node id="LR" recordCount="2" score="4.0">\n        '
            '<SimplePredicate field="z" operator="greaterOrEqual" value="0"/>'
            "</Node>\n    </Node>",
        ).replace(
            "<DataDictionary>",
            '<DataDictionary><DataField name="z" optype="continuous" '
            'dataType="double"/>',
        ).replace(
            '<MiningField name="x"/>',
            '<MiningField name="x"/><MiningField name="z"/>',
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        # x observed (goes left), z missing: leaves LL/LR weighted 5/2
        exp = (5 / 7) * 1.0 + (2 / 7) * 4.0
        rec = {"x": 0.0, "z": None}
        assert evaluate(doc, rec).value == pytest.approx(exp)
        assert cm.score_records([rec])[0].score.value == pytest.approx(
            exp, rel=1e-5
        )

    def test_requires_record_count(self):
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.utils.exceptions import (
            ModelCompilationException,
        )

        xml = AGG_NODES.replace(' recordCount="7"', "")
        with pytest.raises(ModelCompilationException, match="recordCount"):
            compile_pmml(parse_pmml(xml))


class TestWeightedStrategyEdges:
    def test_deterministic_path_uses_leaf_score(self):
        """A leaf whose score attr disagrees with its max confidence:
        on a fully-observed path weightedConfidence must behave exactly
        like the boolean backends (leaf score wins)."""
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.pmml.interp import evaluate

        xml = WEIGHTED_CONF.replace(
            '<ScoreDistribution value="a" recordCount="45"/>\n      '
            '<ScoreDistribution value="b" recordCount="15"/>',
            '<ScoreDistribution value="a" recordCount="24"/>\n      '
            '<ScoreDistribution value="b" recordCount="36"/>',
        )  # leaf L: score="a" but b has higher confidence
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rec = {"x": -1.0}  # deterministic: leaf L
        o = evaluate(doc, rec)
        p = cm.score_records([rec])[0]
        assert o.label == "a" and p.target.label == "a"
        # fractional path still aggregates and argmaxes
        exp_a = 0.6 * (24 / 60) + 0.4 * (8 / 40)
        o = evaluate(doc, {"x": None})
        p = cm.score_records([{"x": None}])[0]
        assert o.label == "b" == p.target.label
        assert o.probabilities["a"] == pytest.approx(exp_a)

    def test_ensemble_of_weighted_trees(self):
        """A majorityVote ensemble of all-True weightedConfidence trees
        must route through the per-segment path, not the fused one."""
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.pmml.interp import evaluate

        tree = WEIGHTED_CONF[
            WEIGHTED_CONF.index('<TreeModel'):
            WEIGHTED_CONF.index('</TreeModel>') + len('</TreeModel>')
        ]
        xml = WEIGHTED_CONF[:WEIGHTED_CONF.index('<TreeModel')].replace(
            "<TreeModel", ""
        ) + f"""<MiningModel functionName="classification">
  <MiningSchema><MiningField name="cls" usageType="target"/>
    <MiningField name="x"/></MiningSchema>
  <Segmentation multipleModelMethod="majorityVote">
    <Segment><True/>{tree}</Segment>
    <Segment><True/>{tree}</Segment>
  </Segmentation></MiningModel></PMML>"""
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)  # must not raise
        for rec in ({"x": -1.0}, {"x": 2.0}, {"x": None}):
            o = evaluate(doc, rec)
            p = cm.score_records([rec])[0]
            assert p.target.label == o.label, rec

    def test_leaf_score_outside_distributions(self):
        """A leaf score absent from every ScoreDistribution still names
        the class: deterministic paths return it (confidence 0) on both
        engines."""
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.pmml.interp import evaluate

        xml = WEIGHTED_CONF.replace(
            '<Node id="L" recordCount="60" score="a">',
            '<Node id="L" recordCount="60" score="other">',
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rec = {"x": -1.0}  # deterministic: leaf L
        o = evaluate(doc, rec)
        p = cm.score_records([rec])[0]
        assert o.label == "other" == p.target.label
        assert o.probabilities["other"] == pytest.approx(0.0)
        assert p.target.probabilities["other"] == pytest.approx(
            0.0, abs=1e-6
        )
