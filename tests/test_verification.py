"""<ModelVerification>: embedded test-vector replay at load time —
passing documents serve, mismatching documents are refused."""

import pathlib

import pytest

from flink_jpmml_tpu.api import ModelReader
from flink_jpmml_tpu.api.reader import clear_model_cache
from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml
from flink_jpmml_tpu.utils.exceptions import (
    ModelLoadingException,
    ModelVerificationException,
)

# regression model: y = 2*x1 - 3*x2 + 0.5
REG = """<PMML version="4.3" xmlns:data="http://example.com/data">
  <DataDictionary>
  <DataField name="x1" optype="continuous" dataType="double"/>
  <DataField name="x2" optype="continuous" dataType="double"/>
  <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <RegressionModel functionName="regression">
  <MiningSchema><MiningField name="y" usageType="target"/>
    <MiningField name="x1"/><MiningField name="x2"/></MiningSchema>
  <RegressionTable intercept="0.5">
    <NumericPredictor name="x1" coefficient="2.0"/>
    <NumericPredictor name="x2" coefficient="-3.0"/>
  </RegressionTable>
  <ModelVerification recordCount="2" fieldCount="3">
    <VerificationFields>
      <VerificationField field="x1" column="data:x1"/>
      <VerificationField field="x2" column="data:x2"/>
      <VerificationField field="y" column="data:y" precision="1E-5"/>
    </VerificationFields>
    <InlineTable>
      <row><data:x1>1.0</data:x1><data:x2>2.0</data:x2>
        <data:y>{y1}</data:y></row>
      <row><data:x1>-0.5</data:x1><data:x2>0.25</data:x2>
        <data:y>{y2}</data:y></row>
    </InlineTable>
  </ModelVerification>
  </RegressionModel></PMML>"""

# classification: verify label + per-class probability
CLS = """<PMML version="4.3"><DataDictionary>
  <DataField name="x" optype="continuous" dataType="double"/>
  <DataField name="cls" optype="categorical" dataType="string">
    <Value value="pos"/><Value value="neg"/></DataField>
  </DataDictionary>
  <RegressionModel functionName="classification"
      normalizationMethod="softmax">
  <MiningSchema><MiningField name="cls" usageType="target"/>
    <MiningField name="x"/></MiningSchema>
  <RegressionTable intercept="0.0" targetCategory="pos">
    <NumericPredictor name="x" coefficient="1.0"/>
  </RegressionTable>
  <RegressionTable intercept="0.0" targetCategory="neg"/>
  <ModelVerification recordCount="1" fieldCount="3">
    <VerificationFields>
      <VerificationField field="x" column="x"/>
      <VerificationField field="cls" column="cls"/>
      <VerificationField field="probability(pos)" column="p_pos"
          precision="1E-4"/>
    </VerificationFields>
    <InlineTable>
      <row><x>2.0</x><cls>{label}</cls><p_pos>{p}</p_pos></row>
    </InlineTable>
  </ModelVerification>
  </RegressionModel></PMML>"""


def _write(tmp_path, xml, name="m.pmml"):
    p = pathlib.Path(tmp_path, name)
    p.write_text(xml)
    return str(p)


class TestModelVerification:
    def test_correct_vectors_load(self, tmp_path):
        clear_model_cache()
        path = _write(tmp_path, REG.format(y1="-3.5", y2="-1.25"))
        cm = ModelReader(path).load()
        assert cm.has_verification and cm.verify() == []

    def test_wrong_expectation_refused(self, tmp_path):
        clear_model_cache()
        path = _write(tmp_path, REG.format(y1="-3.5", y2="7.0"))
        with pytest.raises(ModelVerificationException, match="row 1"):
            ModelReader(path).load()
        # an explicit opt-out still loads (operator override)
        cm = ModelReader(path).load(verify=False)
        assert len(cm.verify()) == 1
        # verification failures are load failures for callers that catch
        # the typed hierarchy
        assert issubclass(ModelVerificationException, ModelLoadingException)

    def test_precision_window(self, tmp_path):
        clear_model_cache()
        # expected off by 1e-7 relative: inside 1e-5 precision
        path = _write(tmp_path, REG.format(y1="-3.4999998", y2="-1.25"))
        assert ModelReader(path).load().verify() == []

    def test_below_floor_tolerance_warns_when_loosened(self, tmp_path):
        clear_model_cache()
        # precision 1E-8 is below the f32 floor: the clamp (a deliberate
        # deviation from JPMML, which honors declared tolerances) must be
        # observable as a warning
        xml = REG.replace('precision="1E-5"', 'precision="1E-8"')
        path = _write(tmp_path, xml.format(y1="-3.5", y2="-1.25"))
        with pytest.warns(UserWarning, match="noise floor"):
            cm = ModelReader(path).load()
        assert cm.verify() == []

    def test_at_floor_tolerance_does_not_warn(self, tmp_path):
        clear_model_cache()
        path = _write(tmp_path, REG.format(y1="-3.5", y2="-1.25"))
        import warnings as _w

        with _w.catch_warnings():
            _w.simplefilter("error")
            assert ModelReader(path).load().verify() == []

    def test_classification_label_and_probability(self, tmp_path):
        import math

        clear_model_cache()
        p_pos = 1.0 / (1.0 + math.exp(-2.0))
        path = _write(
            tmp_path, CLS.format(label="pos", p=f"{p_pos:.6f}")
        )
        assert ModelReader(path).load().verify() == []
        clear_model_cache()
        bad = _write(
            tmp_path, CLS.format(label="neg", p=f"{p_pos:.6f}"), "bad.pmml"
        )
        with pytest.raises(ModelVerificationException, match="label"):
            ModelReader(bad).load()

    def test_unknown_expectation_column(self, tmp_path):
        doc = parse_pmml(REG.format(y1="-3.5", y2="-1.25").replace(
            'field="y" column="data:y"', 'field="zzz" column="data:y"'
        ))
        cm = compile_pmml(doc)
        assert any("not an input" in p for p in cm.verify())

    def test_malformed_verification_rejected(self):
        with pytest.raises(ModelLoadingException):
            parse_pmml(REG.format(y1="1", y2="1").replace(
                "<VerificationFields>", "<VerificationFields/>"
            ).replace(
                '<VerificationField field="x1" column="data:x1"/>', ""
            ).replace(
                '<VerificationField field="x2" column="data:x2"/>', ""
            ).replace(
                '<VerificationField field="y" column="data:y" '
                'precision="1E-5"/>', ""
            ).replace("</VerificationFields>", ""))


CAT = """<PMML version="4.3"><DataDictionary>
  <DataField name="grade" optype="categorical" dataType="string">
    <Value value="2"/><Value value="4"/></DataField>
  <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <RegressionModel functionName="regression">
  <MiningSchema><MiningField name="y" usageType="target"/>
    <MiningField name="grade"/></MiningSchema>
  <RegressionTable intercept="1.0">
    <CategoricalPredictor name="grade" value="4" coefficient="10.0"/>
  </RegressionTable>
  <ModelVerification recordCount="2" fieldCount="2">
    <VerificationFields>
      <VerificationField field="grade" column="grade"/>
      <VerificationField field="y" column="y"/>
    </VerificationFields>
    <InlineTable>
      <row><grade>4</grade><y>11.0</y></row>
      <row><grade>2</grade><y>1.0</y></row>
    </InlineTable>
  </ModelVerification>
  </RegressionModel></PMML>"""

NUMLABEL = """<PMML version="4.3"><DataDictionary>
  <DataField name="x" optype="continuous" dataType="double"/>
  <DataField name="cls" optype="categorical" dataType="string">
    <Value value="0"/><Value value="1"/></DataField>
  </DataDictionary>
  <RegressionModel functionName="classification"
      normalizationMethod="softmax">
  <MiningSchema><MiningField name="cls" usageType="target"/>
    <MiningField name="x"/></MiningSchema>
  <RegressionTable intercept="0.0" targetCategory="1">
    <NumericPredictor name="x" coefficient="1.0"/>
  </RegressionTable>
  <RegressionTable intercept="0.0" targetCategory="0"/>
  <ModelVerification recordCount="1" fieldCount="2">
    <VerificationFields>
      <VerificationField field="x" column="x"/>
      <VerificationField field="cls" column="cls"/>
    </VerificationFields>
    <InlineTable><row><x>3.0</x><cls>1</cls></row></InlineTable>
  </ModelVerification>
  </RegressionModel></PMML>"""


class TestVerificationEdgeCases:
    def test_numeric_looking_categorical_input(self, tmp_path):
        # category "4" must ride the codec, not float-coerce past it
        clear_model_cache()
        path = _write(tmp_path, CAT)
        assert ModelReader(path).load().verify() == []

    def test_numeric_class_label_expectation(self, tmp_path):
        # classification predictedValue compares as the LABEL "1", never
        # against the winning probability
        clear_model_cache()
        path = _write(tmp_path, NUMLABEL)
        assert ModelReader(path).load().verify() == []

    def test_cache_does_not_bypass_verification(self, tmp_path):
        clear_model_cache()
        path = _write(tmp_path, REG.format(y1="-3.5", y2="7.0"))
        # operator override loads (and caches) the failing model...
        ModelReader(path).load(verify=False)
        # ...but a default load must STILL refuse it, cache hit or not
        with pytest.raises(ModelVerificationException):
            ModelReader(path).load()


class TestDefaultTolerances:
    def test_spec_default_precision_passes_f32_outputs(self, tmp_path):
        """Producer-default tolerances (precision 1e-6, zeroThreshold
        1e-16) must not refuse a correct model over float32 arithmetic:
        the replay floors them to f32-realistic values."""
        clear_model_cache()
        xml = REG.format(y1="-3.5", y2="-1.25").replace(
            ' precision="1E-5"', ""
        )
        # an expectation off by ~4e-5 relative: fails the raw 1e-6
        # default but sits inside the f32 floor
        xml = xml.replace("-3.5</data:y>", "-3.50011</data:y>")
        path = _write(tmp_path, xml)
        assert ModelReader(path).load().verify() == []
        # a genuinely wrong expectation still fails through the floor
        clear_model_cache()
        bad = _write(
            tmp_path,
            REG.format(y1="-3.51", y2="-1.25").replace(
                ' precision="1E-5"', ""
            ),
            "bad.pmml",
        )
        with pytest.raises(ModelVerificationException):
            ModelReader(bad).load()
