"""Unit tests for the Prediction ADT and control/identity models.

Mirrors the reference's ``PredictionSpec`` and model specs (SURVEY.md §5).
"""

import math

import pytest

from flink_jpmml_tpu.models.control import AddMessage, DelMessage
from flink_jpmml_tpu.models.core import ModelId
from flink_jpmml_tpu.models.prediction import (
    EmptyScore,
    Prediction,
    Score,
    decode_batch,
)


class TestPrediction:
    def test_of_value(self):
        p = Prediction.of(3.5)
        assert not p.is_empty
        assert p.score == Score(3.5)
        assert p.score.get_or_else(0.0) == 3.5

    def test_of_nan_is_empty(self):
        p = Prediction.of(float("nan"))
        assert p.is_empty
        assert isinstance(p.score, EmptyScore)
        assert p.score.get_or_else(-1.0) == -1.0

    def test_of_none_is_empty(self):
        assert Prediction.of(None).is_empty

    def test_decode_batch_masks_invalid_lanes(self):
        preds = decode_batch(
            values=[1.0, 2.0, float("nan"), 4.0],
            valid=[True, False, True, True],
        )
        assert [p.is_empty for p in preds] == [False, True, True, False]
        assert preds[0].score == Score(1.0)
        assert preds[3].score == Score(4.0)

    def test_decode_batch_with_labels(self):
        preds = decode_batch(
            values=[0.0, 1.0],
            valid=[True, True],
            labels=["setosa", "virginica"],
            probabilities=[{"setosa": 0.9}, {"virginica": 0.8}],
        )
        assert preds[0].target.label == "setosa"
        assert math.isclose(preds[1].target.probabilities["virginica"], 0.8)


class TestModelId:
    def test_key_roundtrip(self):
        mid = ModelId("kmeans-iris", 3)
        assert ModelId.from_key(mid.key()) == mid

    def test_rejects_separator_in_name(self):
        with pytest.raises(ValueError):
            ModelId("bad_name", 1)

    def test_rejects_negative_version(self):
        with pytest.raises(ValueError):
            ModelId("m", -1)


class TestControlMessages:
    def test_add_del_model_id(self):
        add = AddMessage("m", 1, "/tmp/m.pmml", 10.0)
        rm = DelMessage("m", 1, 11.0)
        assert add.model_id == rm.model_id == ModelId("m", 1)


class TestDonateBatches:
    def test_donate_flag_never_breaks_scoring(self, tmp_path):
        """CompileConfig.donate_batches passes donate_argnums through to
        jax.jit. For scoring workloads the outputs are almost always
        smaller than the batch inputs, so XLA usually deems the donated
        buffers unusable and warns — exactly why the flag defaults off
        (utils/config.py). This is the regression guard for the flag
        itself: a donation-enabled compile must still score identically
        (warning contained, not leaked into the suite)."""
        import warnings

        import numpy as np

        from assets.generate import gen_gbm
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml_file
        from flink_jpmml_tpu.utils.config import CompileConfig

        doc = parse_pmml_file(
            gen_gbm(str(tmp_path), n_trees=12, depth=3, n_features=5)
        )
        cm = compile_pmml(doc, batch_size=32)
        cm_d = compile_pmml(
            doc, batch_size=32,
            config=CompileConfig(donate_batches=True),
        )
        rng = np.random.default_rng(17)
        base = rng.normal(0, 1.5, size=(32, 5)).astype(np.float32)
        ref = np.asarray(cm.predict(base.copy(), np.isnan(base)).value)
        with warnings.catch_warnings():
            # "donated buffers were not usable" is the expected outcome
            # on these shapes, not suite noise
            warnings.simplefilter("ignore", UserWarning)
            # fresh buffers per donated call (donation invalidates them)
            got = np.asarray(
                cm_d.predict(base.copy(), np.isnan(base)).value
            )
            q = cm_d.quantized_scorer()
            got_q = (
                np.asarray(q.predict_wire(q.wire.encode(base.copy())))
                if q is not None
                else None
            )
        np.testing.assert_allclose(got, ref, rtol=1e-6)
        if got_q is not None:
            np.testing.assert_allclose(got_q, ref, rtol=1e-4, atol=1e-5)
