"""Mesh-aware compile (BASELINE config 5): compile_pmml(..., mesh=) must
feature-shard the stacked model's wide linear stage over the ``model``
axis INSIDE the compiled scorer — not as a standalone building block —
and agree with the oracle and the unsharded compile (up to f32
reduction reordering across the psum split).

Runs on the virtual 8-CPU mesh (tests/conftest.py); the driver's
dryrun_multichip exercises the same path.
"""

import numpy as np
import pytest

from flink_jpmml_tpu.assets_gen import gen_stacked
from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.parallel.mesh import make_mesh
from flink_jpmml_tpu.parallel.sharding import ShardedModel, mesh_sharded
from flink_jpmml_tpu.pmml import parse_pmml_file
from flink_jpmml_tpu.pmml.interp import evaluate
from flink_jpmml_tpu.utils.config import CompileConfig, MeshConfig

WIDE_F = 10_000  # config 5's 10k-dim feature space


@pytest.fixture(scope="module")
def wide_doc(tmp_path_factory):
    out = tmp_path_factory.mktemp("wide_stacked")
    path = gen_stacked(
        str(out), n_trees=10, depth=3, n_features=WIDE_F, wide_lr=True
    )
    return parse_pmml_file(path)


def _records(doc, n, seed=3):
    rng = np.random.default_rng(seed)
    X = rng.normal(0.0, 1.0, size=(n, WIDE_F)).astype(np.float32)
    fields = doc.active_fields
    return X, [dict(zip(fields, row.tolist())) for row in X]


class TestMeshCompile:
    def test_wide_stage_is_tp_sharded(self, wide_doc):
        mesh = make_mesh(MeshConfig(data=4, model=2))
        sm = compile_pmml(wide_doc, batch_size=64, mesh=mesh)
        assert isinstance(sm, ShardedModel)
        # the wide LR's [10k] coefficient vector must be model-axis
        # sharded; the narrow calibration/tree params replicate
        assert sm.tp_sharded_leaves, "no param leaf was TP-sharded"
        assert any("num_coefs" in leaf for leaf in sm.tp_sharded_leaves)

    def test_sharded_matches_unsharded_and_oracle(self, wide_doc):
        mesh = make_mesh(MeshConfig(data=4, model=2))
        sm = compile_pmml(wide_doc, batch_size=64, mesh=mesh)
        cm = compile_pmml(wide_doc, batch_size=64)
        X, recs = _records(wide_doc, 64)
        got = sm.score_records(recs)
        want = cm.score_records(recs)
        for g, w in zip(got, want):
            assert not g.is_empty and not w.is_empty
            assert g.score.value == pytest.approx(
                w.score.value, rel=2e-5, abs=1e-6
            )
        # oracle spot-diff (per-record python interpreter, so few lanes)
        for i in (0, 17, 63):
            o = evaluate(wide_doc, recs[i])
            assert got[i].score.value == pytest.approx(
                o.value, rel=2e-3, abs=1e-4
            )

    def test_missing_and_invalid_lanes_survive_sharding(self, wide_doc):
        mesh = make_mesh(MeshConfig(data=4, model=2))
        sm = compile_pmml(wide_doc, batch_size=64, mesh=mesh)
        cm = compile_pmml(wide_doc, batch_size=64)
        _, recs = _records(wide_doc, 8)
        recs[1]["f17"] = None  # missing numeric → lane semantics
        recs[3] = {k: v for k, v in recs[3].items() if k != "f0"}
        got = sm.score_records(recs)
        want = cm.score_records(recs)
        for g, w in zip(got, want):
            assert g.is_empty == w.is_empty
            if not g.is_empty:
                assert g.score.value == pytest.approx(
                    w.score.value, rel=2e-5, abs=1e-6
                )

    def test_pure_dp_mesh_has_no_tp_leaves(self, wide_doc):
        mesh = make_mesh(MeshConfig(data=8, model=1))
        sm = compile_pmml(wide_doc, batch_size=64, mesh=mesh)
        assert sm.tp_sharded_leaves == ()

    def test_narrow_model_stays_replicated(self, tmp_path):
        path = gen_stacked(
            str(tmp_path), n_trees=5, depth=3, n_features=32, wide_lr=True
        )
        doc = parse_pmml_file(path)
        mesh = make_mesh(MeshConfig(data=4, model=2))
        sm = compile_pmml(doc, batch_size=32, mesh=mesh)
        assert sm.tp_sharded_leaves == ()  # nothing crosses the threshold
        cm = compile_pmml(doc, batch_size=32)
        rng = np.random.default_rng(0)
        X = rng.normal(size=(32, 32)).astype(np.float32)
        recs = [dict(zip(doc.active_fields, r.tolist())) for r in X]
        for g, w in zip(sm.score_records(recs), cm.score_records(recs)):
            assert g.score.value == pytest.approx(w.score.value, rel=1e-6)

    def test_threshold_is_configurable(self, tmp_path):
        path = gen_stacked(
            str(tmp_path), n_trees=5, depth=3, n_features=64, wide_lr=True
        )
        doc = parse_pmml_file(path)
        mesh = make_mesh(MeshConfig(data=4, model=2))
        sm = compile_pmml(
            doc, batch_size=32, mesh=mesh,
            config=CompileConfig(tp_wide_threshold=64),
        )
        assert any("num_coefs" in leaf for leaf in sm.tp_sharded_leaves)

    def test_verification_replays_through_sharded_jit(self):
        # <ModelVerification> must validate the jit that will actually
        # serve: the GSPMD re-jit, not the unsharded base
        from tests.test_verification import REG
        from flink_jpmml_tpu.pmml import parse_pmml

        mesh = make_mesh(MeshConfig(data=4, model=2))
        good = parse_pmml(REG.format(y1="-3.5", y2="-1.25"))
        sm = compile_pmml(good, batch_size=8, mesh=mesh)
        assert sm.has_verification and sm.verify() == []
        bad = parse_pmml(REG.format(y1="-3.5", y2="99.0"))
        sm_bad = compile_pmml(bad, batch_size=8, mesh=mesh)
        assert sm_bad.verify()  # mismatch reported, not swallowed

    def test_mesh_sharded_direct_on_compiled_model(self, wide_doc):
        # the two-step spelling (compile, then shard) is equivalent
        mesh = make_mesh(MeshConfig(data=2, model=4))
        cm = compile_pmml(wide_doc, batch_size=32)
        sm = mesh_sharded(cm, mesh, wide_threshold=4096)
        _, recs = _records(wide_doc, 32, seed=9)
        for g, w in zip(sm.score_records(recs), cm.score_records(recs)):
            assert g.score.value == pytest.approx(
                w.score.value, rel=2e-5, abs=1e-6
            )
