"""GeneralRegressionModel and NaiveBayesModel families, golden-diffed
compiled vs oracle vs hand-computed values (R glm / multinom export
shapes)."""

import math

import numpy as np
import pytest

from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml
from flink_jpmml_tpu.pmml.interp import evaluate

GLM = """<PMML version="4.3"><DataDictionary>
  <DataField name="x1" optype="continuous" dataType="double"/>
  <DataField name="x2" optype="continuous" dataType="double"/>
  <DataField name="color" optype="categorical" dataType="string">
    <Value value="red"/><Value value="blue"/></DataField>
  <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <GeneralRegressionModel functionName="regression"
      modelType="{model_type}" {link_attr}>
  <MiningSchema><MiningField name="y" usageType="target"/>
    <MiningField name="x1"/><MiningField name="x2"/>
    <MiningField name="color"/></MiningSchema>
  <ParameterList>
    <Parameter name="p0" label="intercept"/>
    <Parameter name="p1"/>
    <Parameter name="p2"/>
    <Parameter name="p3"/>
  </ParameterList>
  <FactorList><Predictor name="color"/></FactorList>
  <CovariateList><Predictor name="x1"/><Predictor name="x2"/>
  </CovariateList>
  <PPMatrix>
    <PPCell value="1" predictorName="x1" parameterName="p1"/>
    <PPCell value="2" predictorName="x2" parameterName="p2"/>
    <PPCell value="red" predictorName="color" parameterName="p3"/>
    <PPCell value="1" predictorName="x1" parameterName="p3"/>
  </PPMatrix>
  <ParamMatrix>
    <PCell parameterName="p0" beta="0.5"/>
    <PCell parameterName="p1" beta="2.0"/>
    <PCell parameterName="p2" beta="-1.0"/>
    <PCell parameterName="p3" beta="3.0"/>
  </ParamMatrix>
  </GeneralRegressionModel></PMML>"""


def _eta(x1, x2, color):
    # p0=1 (intercept); p1=x1; p2=x2²; p3=[color==red]·x1
    return (
        0.5 + 2.0 * x1 - 1.0 * x2 * x2 + 3.0 * (1.0 if color == "red" else 0.0) * x1
    )


class TestGeneralRegression:
    def _parity(self, xml, n=150, seed=0):
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rng = np.random.default_rng(seed)
        recs = [
            {
                "x1": float(a),
                "x2": float(b),
                "color": str(rng.choice(["red", "blue"])),
            }
            for a, b in rng.normal(0, 1, size=(n, 2))
        ]
        for rec, p in zip(recs, cm.score_records(recs)):
            o = evaluate(doc, rec)
            assert not p.is_empty and o.value is not None
            # f32 device vs f64 oracle: link tails (cloglog/probit near
            # saturation) cost a few ulps more than the linear case, and
            # TPU transcendentals (exp/erf) carry ~1-2 extra ulps vs CPU
            assert p.score.value == pytest.approx(
                o.value, rel=2e-3, abs=4e-6
            ), rec
            if o.label is not None:
                assert p.target.label == o.label, rec
        return doc

    def test_general_linear_hand_values(self):
        doc = self._parity(GLM.format(model_type="generalLinear",
                                      link_attr=""))
        o = evaluate(doc, {"x1": 1.0, "x2": 2.0, "color": "red"})
        assert o.value == pytest.approx(_eta(1.0, 2.0, "red"))
        o = evaluate(doc, {"x1": -0.5, "x2": 1.0, "color": "blue"})
        assert o.value == pytest.approx(_eta(-0.5, 1.0, "blue"))

    @pytest.mark.parametrize("link,inv", [
        ("log", math.exp),
        ("logit", lambda e: 1 / (1 + math.exp(-e))),
        ("cloglog", lambda e: 1 - math.exp(-math.exp(e))),
        ("probit", lambda e: 0.5 * (1 + math.erf(e / math.sqrt(2)))),
        ("cauchit", lambda e: 0.5 + math.atan(e) / math.pi),
    ])
    def test_generalized_links(self, link, inv):
        doc = self._parity(GLM.format(
            model_type="generalizedLinear",
            link_attr=f'linkFunction="{link}"',
        ))
        e = _eta(0.3, -0.4, "red")
        o = evaluate(doc, {"x1": 0.3, "x2": -0.4, "color": "red"})
        assert o.value == pytest.approx(inv(e), rel=1e-6)

    def test_missing_predictor_is_empty_lane(self):
        doc = parse_pmml(GLM.format(model_type="generalLinear",
                                    link_attr=""))
        cm = compile_pmml(doc)
        preds = cm.score_records([
            {"x1": 1.0, "x2": 1.0, "color": "red"},
            {"x2": 1.0, "color": "red"},  # x1 missing
            {"x1": 1.0, "x2": 1.0},       # color missing
        ])
        assert [p.is_empty for p in preds] == [False, True, True]
        assert evaluate(doc, {"x2": 1.0, "color": "red"}).is_missing


MULTINOMIAL = """<PMML version="4.3"><DataDictionary>
  <DataField name="x" optype="continuous" dataType="double"/>
  <DataField name="species" optype="categorical" dataType="string">
    <Value value="a"/><Value value="b"/><Value value="c"/></DataField>
  </DataDictionary>
  <GeneralRegressionModel functionName="classification"
      modelType="multinomialLogistic">
  <MiningSchema><MiningField name="species" usageType="target"/>
    <MiningField name="x"/></MiningSchema>
  <ParameterList><Parameter name="p0"/><Parameter name="p1"/>
  </ParameterList>
  <CovariateList><Predictor name="x"/></CovariateList>
  <PPMatrix><PPCell value="1" predictorName="x" parameterName="p1"/>
  </PPMatrix>
  <ParamMatrix>
    <PCell targetCategory="a" parameterName="p0" beta="0.2"/>
    <PCell targetCategory="a" parameterName="p1" beta="1.5"/>
    <PCell targetCategory="b" parameterName="p0" beta="-0.3"/>
    <PCell targetCategory="b" parameterName="p1" beta="-0.8"/>
  </ParamMatrix>
  </GeneralRegressionModel></PMML>"""


class TestMultinomialLogistic:
    def test_reference_category_softmax(self):
        doc = parse_pmml(MULTINOMIAL)
        # reference resolves to the target's last declared value: "c"
        assert doc.model.target_reference_category == "c"
        cm = compile_pmml(doc)
        rng = np.random.default_rng(1)
        recs = [{"x": float(v)} for v in rng.normal(0, 2, size=100)]
        for rec, p in zip(recs, cm.score_records(recs)):
            o = evaluate(doc, rec)
            assert p.target.label == o.label, rec
            for k in ("a", "b", "c"):
                assert p.target.probabilities[k] == pytest.approx(
                    o.probabilities[k], rel=1e-4, abs=1e-6
                )
        # hand check at x = 1: eta_a = 1.7, eta_b = -1.1, eta_c = 0
        x = 1.0
        za, zb, zc = 0.2 + 1.5 * x, -0.3 - 0.8 * x, 0.0
        s = math.exp(za) + math.exp(zb) + math.exp(zc)
        o = evaluate(doc, {"x": x})
        assert o.probabilities["a"] == pytest.approx(math.exp(za) / s)
        assert o.label == "a"


NAIVE_BAYES = """<PMML version="4.3"><DataDictionary>
  <DataField name="outlook" optype="categorical" dataType="string">
    <Value value="sunny"/><Value value="rain"/></DataField>
  <DataField name="temp" optype="continuous" dataType="double"/>
  <DataField name="play" optype="categorical" dataType="string">
    <Value value="yes"/><Value value="no"/></DataField>
  </DataDictionary>
  <NaiveBayesModel functionName="classification" threshold="0.001">
  <MiningSchema><MiningField name="play" usageType="target"/>
    <MiningField name="outlook" invalidValueTreatment="asIs"/>
    <MiningField name="temp"/></MiningSchema>
  <BayesInputs>
    <BayesInput fieldName="outlook">
      <PairCounts value="sunny"><TargetValueCounts>
        <TargetValueCount value="yes" count="6"/>
        <TargetValueCount value="no" count="1"/>
      </TargetValueCounts></PairCounts>
      <PairCounts value="rain"><TargetValueCounts>
        <TargetValueCount value="yes" count="4"/>
        <TargetValueCount value="no" count="9"/>
      </TargetValueCounts></PairCounts>
    </BayesInput>
    <BayesInput fieldName="temp">
      <TargetValueStats>
        <TargetValueStat value="yes"><GaussianDistribution
          mean="22.0" variance="9.0"/></TargetValueStat>
        <TargetValueStat value="no"><GaussianDistribution
          mean="10.0" variance="16.0"/></TargetValueStat>
      </TargetValueStats>
    </BayesInput>
  </BayesInputs>
  <BayesOutput fieldName="play"><TargetValueCounts>
    <TargetValueCount value="yes" count="10"/>
    <TargetValueCount value="no" count="10"/>
  </TargetValueCounts></BayesOutput>
  </NaiveBayesModel></PMML>"""


class TestNaiveBayes:
    def test_parity_and_hand_value(self):
        doc = parse_pmml(NAIVE_BAYES)
        cm = compile_pmml(doc)
        rng = np.random.default_rng(2)
        recs = []
        for _ in range(150):
            rec = {}
            if rng.random() > 0.2:
                rec["outlook"] = str(rng.choice(["sunny", "rain", "fog"]))
            if rng.random() > 0.2:
                rec["temp"] = float(rng.uniform(-5, 35))
            recs.append(rec)
        for rec, p in zip(recs, cm.score_records(recs)):
            o = evaluate(doc, rec)
            assert not p.is_empty
            assert p.target.label == o.label, rec
            for k in ("yes", "no"):
                assert p.target.probabilities[k] == pytest.approx(
                    o.probabilities[k], rel=1e-4, abs=1e-6
                )
        # hand computation: sunny, temp 20
        def gauss(x, m, v):
            return math.exp(-((x - m) ** 2) / (2 * v)) / math.sqrt(
                2 * math.pi * v
            )

        l_yes = 10 * (6 / 10) * gauss(20.0, 22.0, 9.0)
        l_no = 10 * (1 / 10) * gauss(20.0, 10.0, 16.0)
        o = evaluate(doc, {"outlook": "sunny", "temp": 20.0})
        assert o.label == "yes"
        assert o.probabilities["yes"] == pytest.approx(
            l_yes / (l_yes + l_no), rel=1e-6
        )

    def test_all_missing_scores_priors(self):
        doc = parse_pmml(NAIVE_BAYES)
        cm = compile_pmml(doc)
        # equal priors (10/10): argmax tie → first label on both paths
        p = cm.score_records([{}])[0]
        o = evaluate(doc, {})
        assert not p.is_empty and o.label == p.target.label == "yes"
        assert p.target.probabilities["yes"] == pytest.approx(0.5)

    def test_zero_count_takes_threshold(self):
        xml = NAIVE_BAYES.replace('value="no" count="1"', 'value="no" count="0"')
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rec = {"outlook": "sunny"}
        o = evaluate(doc, rec)
        p = cm.score_records([rec])[0]
        # P(sunny|no) = 0 → threshold 0.001
        l_yes, l_no = 10 * 0.6, 10 * 0.001
        assert o.probabilities["no"] == pytest.approx(
            l_no / (l_yes + l_no), rel=1e-6
        )
        assert p.target.probabilities["no"] == pytest.approx(
            o.probabilities["no"], rel=1e-4
        )


class TestReviewRegressions:
    def test_multinomial_glm_in_mining_segment_resolves_reference(self):
        """A multinomialLogistic GLM nested in a MiningModel segment must
        resolve its reference category at parse time like a top-level
        one (review: the oracle raised while the compiled path scored)."""
        inner = MULTINOMIAL.split("<GeneralRegressionModel", 1)[1]
        inner = "<GeneralRegressionModel" + inner.rsplit("</PMML>", 1)[0]
        xml = MULTINOMIAL.split("<GeneralRegressionModel", 1)[0] + f"""
          <MiningModel functionName="classification">
          <MiningSchema><MiningField name="species" usageType="target"/>
            <MiningField name="x"/></MiningSchema>
          <Segmentation multipleModelMethod="selectFirst">
            <Segment><True/>{inner}</Segment>
          </Segmentation></MiningModel></PMML>"""
        doc = parse_pmml(xml)
        seg_model = doc.model.segmentation.segments[0].model
        assert seg_model.target_reference_category == "c"
        cm = compile_pmml(doc)
        rng = np.random.default_rng(5)
        for v in rng.normal(0, 2, size=30):
            rec = {"x": float(v)}
            o = evaluate(doc, rec)  # must not raise
            p = cm.score_records([rec])[0]
            assert p.target.label == o.label

    def test_negative_base_fractional_exponent_is_nan_not_complex(self):
        xml = GLM.format(model_type="generalLinear", link_attr="").replace(
            '<PPCell value="2" predictorName="x2" parameterName="p2"/>',
            '<PPCell value="0.5" predictorName="x2" parameterName="p2"/>',
        )
        doc = parse_pmml(xml)
        o = evaluate(doc, {"x1": 1.0, "x2": -2.0, "color": "blue"})
        assert not isinstance(o.value, complex)
        assert o.value != o.value  # NaN, matching jnp.power

    def test_duplicate_pcells_sum_on_both_paths(self):
        xml = GLM.format(model_type="generalLinear", link_attr="").replace(
            '<PCell parameterName="p1" beta="2.0"/>',
            '<PCell parameterName="p1" beta="2.0"/>'
            '<PCell parameterName="p1" beta="3.0"/>',
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rec = {"x1": 1.0, "x2": 0.0, "color": "blue"}
        o = evaluate(doc, rec)
        p = cm.score_records([rec])[0]
        assert o.value == pytest.approx(0.5 + 5.0)  # betas summed
        assert p.score.value == pytest.approx(o.value)

    def test_missing_beta_rejected_at_parse(self):
        from flink_jpmml_tpu.utils.exceptions import ModelLoadingException

        xml = GLM.format(model_type="generalLinear", link_attr="").replace(
            '<PCell parameterName="p1" beta="2.0"/>',
            '<PCell parameterName="p1"/>',
        )
        with pytest.raises(ModelLoadingException, match="beta"):
            parse_pmml(xml)

    def test_zero_count_without_threshold_typed_error_on_both_paths(self):
        from flink_jpmml_tpu.utils.exceptions import (
            ModelCompilationException,
        )

        xml = NAIVE_BAYES.replace(' threshold="0.001"', "").replace(
            'value="no" count="1"', 'value="no" count="0"'
        )
        doc = parse_pmml(xml)
        with pytest.raises(ModelCompilationException, match="threshold"):
            compile_pmml(doc)
        with pytest.raises(ModelCompilationException, match="threshold"):
            evaluate(doc, {"outlook": "sunny"})


ORDINAL = """<PMML version="4.3"><DataDictionary>
  <DataField name="x1" optype="continuous" dataType="double"/>
  <DataField name="grade" optype="ordinal" dataType="string">
    <Value value="low"/><Value value="mid"/><Value value="high"/>
  </DataField></DataDictionary>
  <GeneralRegressionModel functionName="classification"
      modelType="ordinalMultinomial" cumulativeLinkFunction="{clink}">
  <MiningSchema><MiningField name="grade" usageType="target"/>
    <MiningField name="x1"/></MiningSchema>
  <ParameterList>
    <Parameter name="p0" label="threshold"/>
    <Parameter name="p1"/>
  </ParameterList>
  <CovariateList><Predictor name="x1"/></CovariateList>
  <PPMatrix>
    <PPCell value="1" predictorName="x1" parameterName="p1"/>
  </PPMatrix>
  <ParamMatrix>
    <PCell parameterName="p0" targetCategory="low" beta="-1.0"/>
    <PCell parameterName="p0" targetCategory="mid" beta="1.5"/>
    <PCell parameterName="p1" beta="0.8"/>
  </ParamMatrix>
  </GeneralRegressionModel></PMML>"""


class TestOrdinalMultinomial:
    @staticmethod
    def _inv(clink, eta):
        import math

        if clink == "logit":
            return 1.0 / (1.0 + math.exp(-eta))
        if clink == "probit":
            return 0.5 * (1.0 + math.erf(eta / math.sqrt(2.0)))
        if clink == "cloglog":
            return 1.0 - math.exp(-math.exp(eta))
        raise AssertionError(clink)

    @pytest.mark.parametrize("clink", ["logit", "probit", "cloglog"])
    def test_cumulative_link_parity(self, clink):
        from flink_jpmml_tpu.pmml import parse_pmml

        doc = parse_pmml(ORDINAL.format(clink=clink))
        cm = compile_pmml(doc)
        for x1 in (-2.0, -0.5, 0.0, 0.7, 3.0):
            rec = {"x1": x1}
            shared = 0.8 * x1
            c1 = self._inv(clink, -1.0 + shared)  # P(y <= low)
            c2 = self._inv(clink, 1.5 + shared)  # P(y <= mid)
            hand = {"low": c1, "mid": c2 - c1, "high": 1.0 - c2}
            o = evaluate(doc, rec)
            p = cm.score_records([rec])[0]
            for cat, exp in hand.items():
                assert o.probabilities[cat] == pytest.approx(exp, abs=1e-12)
                assert p.target.probabilities[cat] == pytest.approx(
                    exp, abs=2e-5
                )
            win = max(hand, key=hand.get)
            assert o.label == win and p.target.label == win

    def test_missing_input_and_rejections(self):
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.utils.exceptions import (
            ModelCompilationException,
            ModelLoadingException,
        )

        doc = parse_pmml(ORDINAL.format(clink="logit"))
        cm = compile_pmml(doc)
        assert cm.score_records([{"x1": None}])[0].is_empty
        assert evaluate(doc, {"x1": None}).value is None
        # no declared target values -> no ordinal scale
        with pytest.raises(ModelLoadingException, match="declared values"):
            parse_pmml(ORDINAL.format(clink="logit").replace(
                '<Value value="low"/><Value value="mid"/>'
                '<Value value="high"/>', ""
            ))
        # a threshold on the LAST category is meaningless
        import dataclasses

        bad = dataclasses.replace(doc, model=dataclasses.replace(
            doc.model,
            p_cells=doc.model.p_cells + (
                type(doc.model.p_cells[0])(
                    parameter="p0", beta=9.9, target_category="high"
                ),
            ),
        ))
        with pytest.raises(ModelCompilationException, match="LAST"):
            compile_pmml(bad)


COX = """<PMML version="4.3"><DataDictionary>
  <DataField name="age" optype="continuous" dataType="double"/>
  <DataField name="t" optype="continuous" dataType="double"/>
  <DataField name="surv" optype="continuous" dataType="double"/>
  </DataDictionary>
  <GeneralRegressionModel functionName="regression"
      modelType="CoxRegression" endTimeVariable="t">
  <MiningSchema><MiningField name="surv" usageType="target"/>
    <MiningField name="age"/><MiningField name="t"/></MiningSchema>
  <ParameterList><Parameter name="p1"/></ParameterList>
  <CovariateList><Predictor name="age"/></CovariateList>
  <PPMatrix>
    <PPCell value="1" predictorName="age" parameterName="p1"/>
  </PPMatrix>
  <ParamMatrix><PCell parameterName="p1" beta="0.03"/></ParamMatrix>
  <BaseCumHazardTables maxTime="10">
    <BaselineCell time="1" cumHazard="0.05"/>
    <BaselineCell time="3" cumHazard="0.12"/>
    <BaselineCell time="7" cumHazard="0.30"/>
  </BaseCumHazardTables>
  </GeneralRegressionModel></PMML>"""


class TestCoxRegression:
    def test_survival_parity(self):
        import math

        from flink_jpmml_tpu.pmml import parse_pmml

        doc = parse_pmml(COX)
        cm = compile_pmml(doc)
        h0 = {0.5: 0.0, 1.0: 0.05, 2.9: 0.05, 3.0: 0.12, 6.0: 0.12,
              7.5: 0.30, 10.0: 0.30}
        for t, h in h0.items():
            for age in (30.0, 55.0):
                rec = {"age": age, "t": t}
                hand = math.exp(-h * math.exp(0.03 * age))
                o = evaluate(doc, rec)
                p = cm.score_records([rec])[0]
                assert o.value == pytest.approx(hand, rel=1e-12), (t, age)
                assert p.score.value == pytest.approx(hand, rel=1e-5), (t, age)

    def test_missing_and_rejections(self):
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.utils.exceptions import ModelLoadingException

        doc = parse_pmml(COX)
        cm = compile_pmml(doc)
        assert cm.score_records([{"age": 40.0, "t": None}])[0].is_empty
        assert evaluate(doc, {"age": 40.0, "t": None}).value is None
        # beyond maxTime the baseline is undefined: empty, no extrapolation
        assert cm.score_records([{"age": 40.0, "t": 10.5}])[0].is_empty
        assert evaluate(doc, {"age": 40.0, "t": 10.5}).value is None
        with pytest.raises(ModelLoadingException, match="strat"):
            parse_pmml(COX.replace(
                'endTimeVariable="t"',
                'endTimeVariable="t" baselineStrataVariable="s"',
            ))
        with pytest.raises(ModelLoadingException, match="BaselineCell"):
            parse_pmml(COX.replace(
                '<BaselineCell time="1" cumHazard="0.05"/>', ""
            ).replace(
                '<BaselineCell time="3" cumHazard="0.12"/>', ""
            ).replace(
                '<BaselineCell time="7" cumHazard="0.30"/>', ""
            ))
