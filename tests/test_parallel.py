"""Sharding tests on the 8-device virtual CPU mesh (SURVEY.md §5 tier 2:
the MiniCluster equivalent — real Mesh/shard_map code paths, no TPU)."""

import jax
import numpy as np
import pytest

from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.parallel import (
    HashPartitioner,
    TpLinearScorer,
    dp_sharded,
    make_mesh,
    stable_hash,
)
from flink_jpmml_tpu.pmml import parse_pmml_file
from flink_jpmml_tpu.utils.config import MeshConfig
from flink_jpmml_tpu.utils.exceptions import (
    FlinkJpmmlTpuError,
    InputValidationException,
)


class TestMesh:
    def test_all_dp_default(self):
        mesh = make_mesh()
        assert mesh.shape["data"] == 8
        assert mesh.shape["model"] == 1

    def test_2d(self):
        mesh = make_mesh(MeshConfig(data=4, model=2))
        assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2

    def test_too_many(self):
        with pytest.raises(FlinkJpmmlTpuError, match="devices"):
            make_mesh(MeshConfig(data=16, model=2))


class TestDpSharded:
    def test_gbm_dp_matches_single_device(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "gbm_small.pmml"))
        cm = compile_pmml(doc)
        mesh = make_mesh(MeshConfig(data=8, model=1))
        sm = dp_sharded(cm, mesh)
        rng = np.random.default_rng(0)
        X = rng.normal(0, 1, size=(64, 8)).astype(np.float32)
        M = np.zeros((64, 8), bool)
        ref = np.asarray(cm.predict(X, M).value)
        out = sm.predict(X, M)
        got = np.asarray(out.value)
        # GSPMD partitioning may re-associate the tree-sum reduction, so
        # parity holds at f32 noise tolerance (same bound the rest of the
        # sharded suite uses), not bit-exactly
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        # output really is sharded over the data axis
        assert len(out.value.sharding.device_set) == 8

    def test_classification_dp(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "iris_lr.pmml"))
        cm = compile_pmml(doc)
        mesh = make_mesh(MeshConfig(data=8, model=1))
        sm = dp_sharded(cm, mesh)
        rng = np.random.default_rng(1)
        X = rng.normal(3, 2, size=(32, 4)).astype(np.float32)
        M = np.zeros((32, 4), bool)
        ref = cm.decode(cm.predict(X, M), 32)
        got = sm.decode(sm.predict(X, M), 32)
        assert [p.target.label for p in got] == [p.target.label for p in ref]

    def test_indivisible_batch_rejected(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "iris_lr.pmml"))
        cm = compile_pmml(doc)
        sm = dp_sharded(cm, make_mesh(MeshConfig(data=8, model=1)))
        with pytest.raises(InputValidationException, match="divide"):
            sm.predict(np.zeros((30, 4), np.float32), np.zeros((30, 4), bool))


class TestTpLinear:
    def test_feature_sharded_matches_dense(self):
        mesh = make_mesh(MeshConfig(data=4, model=2))
        rng = np.random.default_rng(2)
        F, C, B = 1024, 3, 16
        W = rng.normal(0, 0.1, size=(F, C)).astype(np.float32)
        b = rng.normal(0, 0.1, size=(C,)).astype(np.float32)
        X = rng.normal(0, 1, size=(B, F)).astype(np.float32)
        scorer = TpLinearScorer(mesh=mesh, W=W, b=b, link="logit")
        got = np.asarray(scorer.predict(X))
        ref = 1.0 / (1.0 + np.exp(-(X @ W + b)))
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=1e-6)

    def test_psum_collective_present(self):
        # the compiled HLO really contains a cross-device reduction
        mesh = make_mesh(MeshConfig(data=4, model=2))
        from flink_jpmml_tpu.parallel.sharding import tp_linear

        fn = tp_linear(mesh, 64, 2)
        import jax.numpy as jnp

        W = jnp.zeros((64, 2))
        b = jnp.zeros((2,))
        X = jnp.zeros((8, 64))
        hlo = jax.jit(fn).lower(W, b, X).compile().as_text()
        assert "all-reduce" in hlo or "all_reduce" in hlo

    def test_indivisible_features_rejected(self):
        mesh = make_mesh(MeshConfig(data=4, model=2))
        with pytest.raises(InputValidationException, match="divide"):
            TpLinearScorer(
                mesh=mesh,
                W=np.zeros((63, 2), np.float32),
                b=np.zeros(2, np.float32),
            )


class TestPartitioner:
    def test_stable_across_runs(self):
        # pinned values: the hash must never change across versions, or
        # resumed keyed streams would re-route mid-flight
        assert stable_hash("model-a") == stable_hash("model-a")
        assert stable_hash(("m", 1)) == stable_hash(("m", 1))
        assert stable_hash("model-a") != stable_hash("model-b")

    def test_partition_deterministic_and_complete(self):
        p = HashPartitioner(4, key_fn=lambda r: r["k"])
        records = [{"k": f"key{i}", "v": i} for i in range(100)]
        lanes = p.partition(records)
        assert lanes == p.partition(records)
        assert set(lanes) <= set(range(4))
        split = p.split(records)
        assert sum(len(l) for l in split) == 100
        # same key → same lane
        assert len({p.lane({"k": "key7"}) for _ in range(5)}) == 1

    def test_reasonable_balance(self):
        p = HashPartitioner(8)
        split = p.split([f"user-{i}" for i in range(8000)])
        sizes = [len(l) for l in split]
        assert min(sizes) > 700  # no dead lanes, no 2x skew
        assert max(sizes) < 1400


class TestGraftEntry:
    def test_dryrun_multichip_8(self):
        import __graft_entry__

        __graft_entry__.dryrun_multichip(8)

    def test_entry_is_jittable(self):
        import jax

        import __graft_entry__

        fn, args = __graft_entry__.entry()
        out = jax.jit(fn)(*args)
        assert out.value.shape == (1024,)


class TestShardedPipeline:
    def test_streaming_pipeline_over_dp_sharded_model(self, tmp_path):
        """SURVEY.md §3 P1: the streaming engine scores through a batch-
        sharded model on the virtual 8-device mesh — per-worker ingestion
        feeding device-sharded micro-batches."""
        import numpy as np

        from assets.generate import gen_iris_lr
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.parallel.mesh import make_mesh
        from flink_jpmml_tpu.parallel.sharding import dp_sharded
        from flink_jpmml_tpu.pmml import parse_pmml_file
        from flink_jpmml_tpu.runtime.engine import Pipeline, StaticScorer
        from flink_jpmml_tpu.runtime.sinks import CollectSink
        from flink_jpmml_tpu.runtime.sources import InMemorySource
        from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig

        doc = parse_pmml_file(gen_iris_lr(str(tmp_path)))
        cm = compile_pmml(doc, batch_size=64)
        sharded = dp_sharded(cm, make_mesh())

        rng = np.random.default_rng(0)
        records = [
            {f: float(v) for f, v in zip(cm.active_fields, row)}
            for row in rng.normal(3.0, 2.0, size=(300, 4))
        ]
        sink = CollectSink()
        pipe = Pipeline(
            InMemorySource(records),
            StaticScorer(sharded),
            sink,
            RuntimeConfig(batch=BatchConfig(size=64, deadline_us=1000)),
        )
        pipe.run_until_exhausted(timeout=60.0)
        assert len(sink.items) == 300
        # parity with the unsharded model
        ref = StaticScorer(cm, use_quantized=False)
        exp = ref.finish(ref.submit(records[:10]))
        for a, b in zip(sink.items[:10], exp):
            assert a.target.label == b.target.label


class TestNewFamiliesSharded:
    """P1 breadth: every round-3 model family scores identically under
    the 8-device data-parallel mesh (batch axis sharded, params
    replicated)."""

    def _check(self, doc, arity, seed=0, B=64):
        from flink_jpmml_tpu.pmml import parse_pmml

        cm = compile_pmml(doc if not isinstance(doc, str) else parse_pmml(doc))
        mesh = make_mesh(MeshConfig(data=8, model=1))
        sm = dp_sharded(cm, mesh)
        rng = np.random.default_rng(seed)
        X = rng.normal(0.5, 1.2, size=(B, arity)).astype(np.float32)
        M = np.zeros((B, arity), bool)
        ref = cm.predict(X, M)
        out = sm.predict(X, M)
        np.testing.assert_allclose(
            np.asarray(out.value), np.asarray(ref.value),
            rtol=1e-5, atol=1e-6,
        )
        if ref.label_idx is not None:
            np.testing.assert_array_equal(
                np.asarray(out.label_idx), np.asarray(ref.label_idx)
            )
        assert len(out.value.sharding.device_set) == 8

    def test_scorecard_sharded(self):
        from tests.test_scorecard_ruleset import SCORECARD

        self._check(SCORECARD, 2)

    def test_ruleset_sharded(self):
        from tests.test_scorecard_ruleset import RULESET

        self._check(RULESET.format(criterion="weightedSum"), 2)

    def test_glm_multinomial_sharded(self):
        from tests.test_glm_bayes import MULTINOMIAL

        self._check(MULTINOMIAL, 1)

    def test_naive_bayes_sharded(self):
        from tests.test_glm_bayes import NAIVE_BAYES

        self._check(NAIVE_BAYES, 2)

    def test_svm_sharded(self):
        from tests.test_svm import _svm_xml, _PAIR_MACHINES, KERNELS

        self._check(_svm_xml(KERNELS["radialBasis"][0], _PAIR_MACHINES), 2)

    def test_knn_sharded(self):
        from tests.test_knn import _knn_xml

        self._check(_knn_xml(), 2)

    def test_anomaly_sharded(self):
        from tests.test_anomaly import _iforest_xml

        self._check(_iforest_xml(), 1)

    def test_gp_sharded(self):
        from tests.test_gp_baseline_assoc import GP

        self._check(GP.format(
            kernel='<RadialBasisKernel gamma="1.5" noiseVariance="0.1" '
                   'lambda="1.1"/>'
        ), 2)

    def test_baseline_sharded(self):
        from tests.test_gp_baseline_assoc import BASELINE

        self._check(BASELINE.format(
            dist='<GaussianDistribution mean="2.0" variance="9.0"/>'
        ), 1)

    def test_association_sharded(self):
        from tests.test_gp_baseline_assoc import ASSOC

        # integer-ish basket indicators: >0.5 ⇔ in basket
        self._check(ASSOC, 4, seed=5)

    def test_timeseries_sharded(self):
        from tests.test_timeseries import TS, TREND_DAMPED, SEASONAL_MUL

        self._check(TS.format(trend=TREND_DAMPED, seasonal=SEASONAL_MUL), 1)

    def test_textmodel_sharded(self):
        from tests.test_textmodel import _xml

        self._check(_xml("logarithmic", "inverseDocumentFrequency",
                         "cosine", "cosine"), 4)


class TestModelParallelGp:
    def test_instance_sharded_gp_matches_single_device(self):
        """mp_gp: training instances sharded over the model axis, one
        psum combines the partial kernel dots — parity vs the
        single-device compiled GP on an 8-device mesh."""
        from tests.test_gp_baseline_assoc import GP
        from flink_jpmml_tpu.parallel.sharding import mp_gp
        from flink_jpmml_tpu.pmml import parse_pmml

        doc = parse_pmml(GP.format(
            kernel='<ARDSquaredExponentialKernel gamma="1.4" '
                   'noiseVariance="0.15"><Lambda>'
                   '<Array n="2" type="real">0.9 1.7</Array></Lambda>'
                   "</ARDSquaredExponentialKernel>"
        ))
        cm = compile_pmml(doc)
        mesh = make_mesh(MeshConfig(data=4, model=2))
        fn = mp_gp(mesh, doc.model)
        rng = np.random.default_rng(7)
        X = rng.normal(0, 1, size=(32, 2)).astype(np.float32)
        got = np.asarray(fn(X))
        ref = cm.predict(X, np.zeros_like(X, bool))
        np.testing.assert_allclose(
            got, np.asarray(ref.value), rtol=2e-5, atol=1e-6
        )
        # the 4 training rows pad to 2 shards of 2+pad — sharding is real
        assert mesh.shape["model"] == 2

    def test_non_sq_kernel_rejected(self):
        from tests.test_gp_baseline_assoc import GP
        from flink_jpmml_tpu.parallel.sharding import mp_gp
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.utils.exceptions import (
            ModelCompilationException,
        )

        doc = parse_pmml(GP.format(
            kernel='<AbsoluteExponentialKernel gamma="1.0" '
                   'noiseVariance="0.1"/>'
        ))
        with pytest.raises(ModelCompilationException, match="squared"):
            mp_gp(make_mesh(MeshConfig(data=4, model=2)), doc.model)

    def test_indivisible_batch_rejected(self):
        from tests.test_gp_baseline_assoc import GP
        from flink_jpmml_tpu.parallel.sharding import mp_gp
        from flink_jpmml_tpu.pmml import parse_pmml

        doc = parse_pmml(GP.format(
            kernel='<RadialBasisKernel gamma="1.0" noiseVariance="0.1" '
                   'lambda="1.0"/>'
        ))
        fn = mp_gp(make_mesh(MeshConfig(data=4, model=2)), doc.model)
        with pytest.raises(InputValidationException, match="divide"):
            fn(np.zeros((30, 2), np.float32))
