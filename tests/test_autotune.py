"""Autotune cache + warmup sweep (compile/autotune.py).

Pins the satellite/acceptance behaviors of ISSUE 2: the sweep measures
fused-vs-host encode (and Pallas tile shapes) and applies the winner;
the winning config round-trips through the on-disk JSON cache and is
consulted by ``build_quantized_scorer`` on the next compile; a corrupt
cache file reads as empty (silent re-tune, never a crash); stale
configs the current build can't honour degrade to defaults."""

import json

import numpy as np
import pytest

from assets.generate import gen_gbm
from flink_jpmml_tpu.compile import autotune
from flink_jpmml_tpu.compile.qtrees import build_quantized_scorer
from flink_jpmml_tpu.pmml import parse_pmml_file


@pytest.fixture
def doc(tmp_path):
    return parse_pmml_file(
        gen_gbm(str(tmp_path), n_trees=10, depth=3, n_features=4)
    )


def _X(n=64, f=4, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(0.0, 1.5, size=(n, f)).astype(np.float32)


class TestSweep:
    def test_sweep_measures_both_encodes(self, doc):
        q = build_quantized_scorer(doc, batch_size=64)
        cfg = autotune.sweep(q, _X(), repeats=1)
        assert cfg.source == "sweep"
        assert {"encode_host", "encode_fused"} <= set(cfg.rates)
        assert cfg.encode in ("host", "fused")
        assert q.encode_mode == cfg.encode
        assert q.tuned is cfg

    def test_pallas_tile_sweep_keeps_parity(self, doc):
        qp = build_quantized_scorer(
            doc, batch_size=64, backend="pallas", pallas_interpret=True
        )
        qx = build_quantized_scorer(doc, batch_size=64, backend="xla")
        cfg = autotune.sweep(qp, _X(), repeats=1)
        assert any(k.startswith("pallas_b") for k in cfg.rates)
        # whatever tile won, scoring is still byte-exact vs the XLA path
        X = _X(128, seed=1)
        Xq = qp.wire.encode(X)
        np.testing.assert_allclose(
            np.asarray(qp.predict_wire(Xq), np.float32),
            np.asarray(qx.predict_wire(Xq), np.float32),
            rtol=1e-5, atol=1e-6,
        )

    def test_sample_tiled_to_batch(self, doc):
        # a sample smaller than the compile batch must not crash the
        # sweep (it is tiled up to one full dispatch)
        q = build_quantized_scorer(doc, batch_size=64)
        cfg = autotune.sweep(q, _X(10), repeats=1)
        assert cfg.rec_s and cfg.rec_s > 0


class TestCacheRoundTrip:
    def test_ensure_tuned_persists_and_next_build_consults(self, doc):
        q = build_quantized_scorer(doc, batch_size=64)
        cfg = autotune.ensure_tuned(q, _X(), repeats=1)
        path = autotune.cache_path()
        data = json.load(open(path))
        assert data["version"] == 1 and data["entries"]
        # a fresh compile of the same model picks the config up from
        # disk (source "cache") without re-sweeping
        q2 = build_quantized_scorer(doc, batch_size=64)
        assert q2.tuned is not None and q2.tuned.source == "cache"
        assert q2.encode_mode == cfg.encode

    def test_cache_hit_applies_without_sweep(self, doc):
        q = build_quantized_scorer(doc, batch_size=64)
        autotune.store(
            q.model_hash, autotune.backend_key(q),
            autotune.TunedConfig(encode="fused", source="sweep"),
        )
        cfg = autotune.ensure_tuned(q, _X(), repeats=1)
        assert cfg.source == "cache"
        assert q.encode_mode == "fused"

    def test_disable_env_bypasses_cache(self, doc, monkeypatch):
        # the bench's --no-autotune ablation: a cached config must NOT
        # be applied at compile when FJT_AUTOTUNE_DISABLE is set
        q = build_quantized_scorer(doc, batch_size=64)
        autotune.store(
            q.model_hash, autotune.backend_key(q),
            autotune.TunedConfig(encode="fused", source="sweep"),
        )
        monkeypatch.setenv("FJT_AUTOTUNE_DISABLE", "1")
        q2 = build_quantized_scorer(doc, batch_size=64)
        assert q2.tuned is None and q2.encode_mode == "host"

    def test_apply_releases_rebuild_hook(self, doc):
        # tuned once: the pallas rebuild closure (pinning host packing
        # tables) must be released after the config is applied
        qp = build_quantized_scorer(
            doc, batch_size=64, backend="pallas", pallas_interpret=True
        )
        assert qp._pallas_rebuild is not None
        autotune.apply(qp, autotune.TunedConfig(encode="host"))
        assert qp._pallas_rebuild is None

    def test_distinct_backend_keys_do_not_collide(self, doc):
        q = build_quantized_scorer(doc, batch_size=64)
        autotune.store(
            q.model_hash, "tpu:v5_lite:pallas",
            autotune.TunedConfig(encode="fused", source="sweep"),
        )
        # same model, DIFFERENT backend key: no entry for this one
        assert autotune.lookup(q.model_hash, autotune.backend_key(q)) is None

    def test_pallas_tile_config_rebuilds_from_cache(self, doc):
        qp = build_quantized_scorer(
            doc, batch_size=64, backend="pallas", pallas_interpret=True
        )
        autotune.store(
            qp.model_hash, autotune.backend_key(qp),
            autotune.TunedConfig(
                encode="host", block_b=32, gt=2, source="sweep"
            ),
        )
        qp2 = build_quantized_scorer(
            doc, batch_size=64, backend="pallas", pallas_interpret=True
        )
        assert qp2.tuned is not None and qp2.tuned.block_b == 32
        qx = build_quantized_scorer(doc, batch_size=64, backend="xla")
        X = _X(seed=2)
        Xq = qp2.wire.encode(X)
        np.testing.assert_allclose(
            np.asarray(qp2.predict_wire(Xq), np.float32),
            np.asarray(qx.predict_wire(Xq), np.float32),
            rtol=1e-5, atol=1e-6,
        )


class TestCorruptCache:
    def test_corrupt_file_reads_empty_and_retunes(self, doc):
        path = autotune.cache_path()
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text("{definitely not json]]")
        q = build_quantized_scorer(doc, batch_size=64)  # no crash
        assert q.tuned is None
        assert autotune.lookup(q.model_hash, autotune.backend_key(q)) is None
        cfg = autotune.ensure_tuned(q, _X(), repeats=1)
        assert cfg.source == "sweep"  # silently re-tuned
        # and the rewrite left a valid file behind
        assert json.load(open(path))["entries"]

    def test_wrong_schema_reads_empty(self, doc):
        path = autotune.cache_path()
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps({"version": 1, "entries": [1, 2, 3]}))
        q = build_quantized_scorer(doc, batch_size=64)
        assert autotune.lookup(q.model_hash, autotune.backend_key(q)) is None

    def test_garbage_entry_values_tolerated(self, doc):
        q = build_quantized_scorer(doc, batch_size=64)
        path = autotune.cache_path()
        path.parent.mkdir(parents=True, exist_ok=True)
        key = f"{q.model_hash}|{autotune.backend_key(q)}"
        path.write_text(json.dumps({
            "version": 1,
            "entries": {key: {"encode": 7, "block_b": "wat", "gt": None}},
        }))
        # a malformed entry must not break the compile-time consult
        q2 = build_quantized_scorer(doc, batch_size=64)
        assert q2.encode_mode in ("host", "fused")


class TestApply:
    def test_stale_fused_degrades_to_host(self, doc):
        q = build_quantized_scorer(doc, batch_size=64)
        q._fused_inner = None  # model without device tables
        autotune.apply(q, autotune.TunedConfig(encode="fused"))
        assert q.encode_mode == "host"

    def test_clear_scoped_and_full(self, doc):
        q = build_quantized_scorer(doc, batch_size=64)
        key = autotune.backend_key(q)
        autotune.store(q.model_hash, key, autotune.TunedConfig())
        autotune.store("deadbeef", key, autotune.TunedConfig())
        autotune.clear(q.model_hash)
        assert autotune.lookup(q.model_hash, key) is None
        assert autotune.lookup("deadbeef", key) is not None
        autotune.clear()
        assert autotune.lookup("deadbeef", key) is None
