"""Parser + IR tests (reference parity: ModelReaderSpec, SURVEY.md §5)."""

import numpy as np
import pytest

from flink_jpmml_tpu.pmml import ir, parse_pmml, parse_pmml_file
from flink_jpmml_tpu.pmml.parser import _parse_string_array
from flink_jpmml_tpu.utils.exceptions import (
    ModelLoadingException,
    UnsupportedPmmlVersionException,
)


class TestVersionGate:
    def test_unsupported_version_rejected(self, assets_dir):
        with pytest.raises(UnsupportedPmmlVersionException, match="3.2"):
            parse_pmml_file(str(assets_dir / "unsupported_version.pmml"))

    def test_malformed_rejected(self, assets_dir):
        with pytest.raises(ModelLoadingException, match="malformed"):
            parse_pmml_file(str(assets_dir / "malformed.pmml"))

    def test_no_model_rejected(self, assets_dir):
        with pytest.raises(ModelLoadingException, match="no supported model"):
            parse_pmml_file(str(assets_dir / "no_model.pmml"))

    def test_missing_file(self):
        with pytest.raises(ModelLoadingException, match="cannot read"):
            parse_pmml_file("/nonexistent/model.pmml")

    @pytest.mark.parametrize("version", ["4.0", "4.1", "4.2", "4.3", "4.4"])
    def test_supported_versions(self, version):
        doc = parse_pmml(
            f'<PMML version="{version}"><DataDictionary>'
            '<DataField name="x" optype="continuous" dataType="double"/>'
            "</DataDictionary>"
            '<RegressionModel functionName="regression">'
            '<MiningSchema><MiningField name="x"/></MiningSchema>'
            '<RegressionTable intercept="1.0"/>'
            "</RegressionModel></PMML>"
        )
        assert doc.version == version


class TestIrisLr:
    def test_structure(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "iris_lr.pmml"))
        m = doc.model
        assert isinstance(m, ir.RegressionModelIR)
        assert m.function_name == "classification"
        assert m.normalization_method == "softmax"
        assert len(m.tables) == 3
        assert doc.active_fields == (
            "sepal_length",
            "sepal_width",
            "petal_length",
            "petal_width",
        )
        assert doc.target_field == "species"
        assert doc.data_dictionary.field("species").values == (
            "setosa",
            "versicolor",
            "virginica",
        )


class TestGbm:
    def test_structure(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "gbm_small.pmml"))
        m = doc.model
        assert isinstance(m, ir.MiningModelIR)
        assert m.segmentation.multiple_model_method == "sum"
        assert len(m.segmentation.segments) == 16
        tree = m.segmentation.segments[0].model
        assert isinstance(tree, ir.TreeModelIR)
        assert tree.missing_value_strategy == "defaultChild"
        # root is a True-predicate node with two predicate children
        assert isinstance(tree.root.predicate, ir.TruePredicate)
        assert len(tree.root.children) == 2
        assert tree.root.default_child is not None
        # targets rescale (base score)
        assert doc.targets and doc.targets[0].rescale_constant == 0.5


class TestMlp:
    def test_structure(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "mlp_small.pmml"))
        m = doc.model
        assert isinstance(m, ir.NeuralNetworkIR)
        assert len(m.inputs) == 8
        assert [len(l.neurons) for l in m.layers] == [16, 3]
        assert m.layers[-1].activation == "identity"
        assert m.normalization_method == "softmax"
        assert len(m.outputs) == 3
        assert isinstance(m.outputs[0].derived_field.expression, ir.NormDiscrete)


class TestKmeans:
    def test_structure(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "kmeans.pmml"))
        m = doc.model
        assert isinstance(m, ir.ClusteringModelIR)
        assert m.measure.metric == "squaredEuclidean"
        assert len(m.clusters) == 5
        assert all(len(c.center) == 4 for c in m.clusters)


class TestStacked:
    def test_structure(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "stacked.pmml"))
        m = doc.model
        assert isinstance(m, ir.MiningModelIR)
        assert m.segmentation.multiple_model_method == "modelChain"
        inner = m.segmentation.segments[0]
        assert isinstance(inner.model, ir.MiningModelIR)
        assert inner.output_fields[0].name == "gbm_score"
        calib = m.segmentation.segments[1].model
        assert isinstance(calib, ir.RegressionModelIR)
        assert calib.normalization_method == "logit"
        assert calib.mining_schema.active_fields == ("gbm_score",)


class TestArrayParsing:
    def test_plain_tokens(self):
        class Fake:
            text = "a b 3.5"

        assert _parse_string_array(Fake()) == ["a", "b", "3.5"]

    def test_quoted_tokens_with_spaces(self):
        class Fake:
            text = '"hello world" plain "with \\" quote"'

        assert _parse_string_array(Fake()) == [
            "hello world",
            "plain",
            'with " quote',
        ]


class TestDeterminism:
    def test_regeneration_is_byte_identical(self, assets_dir, tmp_path):
        from assets.generate import gen_iris_lr

        p2 = gen_iris_lr(str(tmp_path))
        a = (assets_dir / "iris_lr.pmml").read_bytes()
        b = open(p2, "rb").read()
        assert a == b
