"""bench.py parent orchestration: the live stderr watch, the init
sub-timeout kill, and the headline policies — tested against FAKE
children (shell scripts standing in for the measurement child), so the
attempt schedule's behavior is pinned without touching jax or a device."""

import json
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow  # process-spawning drill (-m 'not slow' = fast inner loop)

from flink_jpmml_tpu import bench


def _args(**over):
    import argparse

    ns = argparse.Namespace(
        trees=500, depth=6, features=32, batch=262144, chunk=16384,
        window=2, seconds=4.0, f32_wire=False, init_timeout=2.0,
        probe_interval=0.2, probe_timeout=2.0, total_budget=60.0,
        skip_interp=False, skip_kafka=False,
        skip_latency=False, latency=False, latency_batch=4096,
        latency_deadline_us=2000, latency_offered=100000.0,
        no_autotune=False, kernel_search=False, no_kernel_search=False,
        no_prefetch=False,
        load_shape="steady",
        in_child=False, force_cpu=False, block_pipeline=False,
    )
    for k, v in over.items():
        setattr(ns, k, v)
    return ns


def _fake_child(tmp_path, monkeypatch, body):
    """Route _child_cmd at a scripted stand-in for the measurement
    child."""
    script = tmp_path / "fake_child.py"
    script.write_text(textwrap.dedent(body))
    monkeypatch.setattr(
        bench, "_child_cmd",
        lambda args, force_cpu: [sys.executable, str(script)],
    )


class TestChildCmd:
    """The parent→child flag plumbing: knobs must actually reach the
    measurement child (the --latency-batch knob is reported back in the
    latency_mode JSON as "batch")."""

    def test_latency_batch_knob_flows_to_child(self):
        cmd = bench._child_cmd(_args(latency_batch=512), force_cpu=False)
        i = cmd.index("--latency-batch")
        assert cmd[i + 1] == "512"

    def test_no_autotune_flag_passthrough(self):
        assert "--no-autotune" not in bench._child_cmd(_args(), False)
        assert "--no-autotune" in bench._child_cmd(
            _args(no_autotune=True), False
        )

    def test_no_prefetch_flag_passthrough(self):
        # the serial-ingest ablation must reach the measurement child,
        # or --no-prefetch silently measures the pipelined path
        assert "--no-prefetch" not in bench._child_cmd(_args(), False)
        assert "--no-prefetch" in bench._child_cmd(
            _args(no_prefetch=True), False
        )

    def test_kernel_search_flags_passthrough(self):
        base = bench._child_cmd(_args(), False)
        assert "--kernel-search" not in base
        assert "--no-kernel-search" not in base
        assert "--kernel-search" in bench._child_cmd(
            _args(kernel_search=True), False
        )
        assert "--no-kernel-search" in bench._child_cmd(
            _args(no_kernel_search=True), False
        )


class TestRunChild:
    def test_healthy_child_line_parsed(self, tmp_path, monkeypatch):
        _fake_child(tmp_path, monkeypatch, """
            import json, sys
            print("[bench +0.1s] backend resolved: tpu", file=sys.stderr)
            print(json.dumps({"metric": "m", "value": 1.0,
                              "backend": "tpu"}))
        """)
        line, err, wedged = bench._run_child(
            _args(), force_cpu=False, init_timeout_s=30.0,
            total_timeout_s=30.0,
        )
        assert err is None and not wedged
        assert line["backend"] == "tpu"

    def test_init_wedge_killed_at_sub_timeout(self, tmp_path, monkeypatch):
        _fake_child(tmp_path, monkeypatch, """
            import sys, time
            print("[bench +0.0s] importing jax", file=sys.stderr, flush=True)
            time.sleep(600)  # wedged: never prints the resolved stamp
        """)
        import time

        t0 = time.monotonic()
        line, err, wedged = bench._run_child(
            _args(), force_cpu=False, init_timeout_s=2.0,
            total_timeout_s=60.0,
        )
        elapsed = time.monotonic() - t0
        assert line is None and wedged
        assert "backend init exceeded" in err
        assert elapsed < 30.0  # killed at the sub-timeout, not the budget

    def test_stamp_found_beyond_tail_window(self, tmp_path, monkeypatch):
        # regression: the stamp must be found even when later stderr
        # (e.g. FJT_BENCH_TRACE faulthandler dumps) pushes it far back
        _fake_child(tmp_path, monkeypatch, """
            import json, sys
            print("[bench +0.1s] backend resolved: tpu", file=sys.stderr,
                  flush=True)
            print("x" * 100000, file=sys.stderr, flush=True)
            print(json.dumps({"metric": "m", "value": 2.0,
                              "backend": "tpu"}))
        """)
        line, err, wedged = bench._run_child(
            _args(), force_cpu=False, init_timeout_s=30.0,
            total_timeout_s=30.0,
        )
        assert err is None and line["value"] == 2.0

    def test_post_init_overrun_killed_at_budget(self, tmp_path, monkeypatch):
        _fake_child(tmp_path, monkeypatch, """
            import sys, time
            print("backend resolved: tpu", file=sys.stderr, flush=True)
            time.sleep(600)  # hangs mid-measurement
        """)
        line, err, wedged = bench._run_child(
            _args(), force_cpu=False, init_timeout_s=30.0,
            total_timeout_s=4.0,
        )
        assert line is None and not wedged
        assert "measurement exceeded" in err

    def test_force_cpu_child_skips_stamp_wait(self, tmp_path, monkeypatch):
        _fake_child(tmp_path, monkeypatch, """
            import json
            print(json.dumps({"metric": "m", "value": 3.0,
                              "backend": "cpu"}))
        """)
        line, err, _ = bench._run_child(
            _args(), force_cpu=True, init_timeout_s=2.0,
            total_timeout_s=30.0,
        )
        assert err is None and line["backend"] == "cpu"


class TestProbePoll:
    """_orchestrate probe-poll (r4 VERDICT #1): cheap probes across the
    whole budget; the measurement child launches only on a healthy
    probe; budget expiry → labelled CPU fallback."""

    def _capture_line(self, capsys):
        out = capsys.readouterr().out.strip().splitlines()
        return json.loads(out[-1])

    def test_measures_on_first_healthy_probe(
        self, tmp_path, monkeypatch, capsys
    ):
        seq = [(None, "probe wedged"), (None, "probe wedged"),
               ("tpu", None)]
        probed = []

        def fake_probe(t):
            probed.append(1)
            # repeat the last value rather than StopIteration if the
            # loop probes more than scripted (a failure should assert,
            # not crash)
            return seq[min(len(probed), len(seq)) - 1]

        monkeypatch.setattr(bench, "_probe_backend", fake_probe)
        _fake_child(tmp_path, monkeypatch, """
            import json, sys
            print("backend resolved: tpu", file=sys.stderr, flush=True)
            print(json.dumps({"metric": "m", "value": 5.0,
                              "backend": "tpu"}))
        """)
        # budget must clear the CPU fallback reserve or the poll loop
        # never starts (the reserve is ~180s + 4x --seconds); generous
        # init_timeout — a loaded host can take seconds just to start
        # the fake child's interpreter
        bench._orchestrate(_args(total_budget=400.0, init_timeout=30.0))
        line = self._capture_line(capsys)
        assert line["backend"] == "tpu" and line["value"] == 5.0
        assert line["probes"] == 3 and line["attempts"] == 1
        assert len(probed) == 3  # two wedged probes did NOT spawn children

    def test_budget_expiry_falls_back_to_labelled_cpu(
        self, tmp_path, monkeypatch, capsys
    ):
        monkeypatch.setattr(
            bench, "_probe_backend", lambda t: (None, "probe wedged")
        )
        _fake_child(tmp_path, monkeypatch, """
            import json
            print(json.dumps({"metric": "m", "value": 7.0,
                              "backend": "cpu"}))
        """)
        # budget only big enough for a few probes + the cpu reserve
        bench._orchestrate(_args(total_budget=190.0, seconds=0.5))
        line = self._capture_line(capsys)
        assert line["backend"] == "cpu-fallback"
        assert "probe wedged" in line["error"]

    def test_cpu_resolution_twice_concedes_early(
        self, tmp_path, monkeypatch, capsys
    ):
        calls = []
        monkeypatch.setattr(
            bench, "_probe_backend",
            lambda t: calls.append(1) or ("cpu", None),
        )
        _fake_child(tmp_path, monkeypatch, """
            import json
            print(json.dumps({"metric": "m", "value": 9.0,
                              "backend": "cpu"}))
        """)
        import time

        t0 = time.monotonic()
        bench._orchestrate(_args(total_budget=600.0, seconds=0.5))
        assert time.monotonic() - t0 < 30.0  # did not poll out 600s
        line = self._capture_line(capsys)
        assert line["backend"] == "cpu-fallback"
        assert len(calls) == 2


class TestLatencyHeadline:
    def test_swaps_to_latency_metric(self):
        line = {
            "metric": "gbm500_records_per_sec_per_chip",
            "value": 900000.0,
            "latency_mode": {"p50_ms": 4.2, "p99_ms": 9.1},
        }
        out = bench._latency_headline(line, 500, "tpu")
        assert out["metric"] == "gbm500_record_latency_p50_ms"
        assert out["value"] == 4.2
        assert out["throughput_rec_s"] == 900000.0

    def test_missing_latency_mode_keeps_line(self):
        line = {"metric": "m", "value": 1.0, "latency_mode": None}
        assert bench._latency_headline(line, 500, "tpu") is line
