"""Real multi-process jax.distributed smoke tests (SURVEY.md §3 row D1).

The in-process tests exercise sharding on a virtual 8-device mesh; these
spawn actual OS processes that join one process group over a local
coordinator, contribute process-local batch slices via
``jax.make_array_from_process_local_data``, and run a psum-backed global
computation — the CPU stand-in for the multi-host ICI/DCN path the
reference delegates to Flink's Akka/Netty runtime. The e2e scoring test
runs at n=2 AND n=4 (VERDICT r3 #9: the 4-way split catches axis
arithmetic a 2-way split can't).
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow  # process-spawning drill (-m 'not slow' = fast inner loop)

_WORKER = textwrap.dedent(
    """
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, {repo!r})
    import numpy as np
    from flink_jpmml_tpu.parallel.distributed import (
        global_batch, init_distributed,
    )
    from flink_jpmml_tpu.parallel.mesh import make_mesh
    from flink_jpmml_tpu.utils.config import MeshConfig

    pid = int(sys.argv[1])
    nproc = int(sys.argv[3])
    ok = init_distributed(
        coordinator_address=sys.argv[2], num_processes=nproc, process_id=pid
    )
    assert ok, "init_distributed returned False"
    assert jax.process_count() == nproc
    mesh = make_mesh(MeshConfig(data=jax.device_count(), model=1))

    # each process contributes 4 rows; the global batch is 4*nproc rows
    X_local = np.full((4, 3), float(pid + 1), np.float32)
    M_local = np.zeros((4, 3), bool)
    Xg, Mg = global_batch(mesh, X_local, M_local)
    assert Xg.shape == (4 * nproc, 3)

    total = float(jax.jit(lambda x: x.sum())(Xg))
    expect = 4.0 * 3.0 * sum(range(1, nproc + 1))
    assert total == expect, (total, expect)
    print(f"proc {{pid}} OK total={{total}}")
    """
)


def _run_procs(tmp_path, script_body, nproc, extra_args=()):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    script = tmp_path / "worker.py"
    script.write_text(script_body.format(repo=repo))

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # one device per process, no virtual mesh
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), coord, str(nproc),
             *extra_args],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(nproc)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=150)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert f"proc {i} OK" in out
    return outs


def test_two_process_group_global_batch(tmp_path):
    _run_procs(tmp_path, _WORKER, nproc=2)


# End-to-end (VERDICT r1 #5, r3 #9): each process ingests the stream, keeps
# its hash partition, contributes its slice of the global batch, and the GBM
# is scored ONCE across the n-process mesh via dp_sharded — then every
# global lane is asserted against the single-process f32 reference.
_E2E_WORKER = textwrap.dedent(
    """
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, {repo!r})
    import numpy as np
    from flink_jpmml_tpu.compile import compile_pmml
    from flink_jpmml_tpu.parallel.distributed import (
        global_batch, init_distributed,
    )
    from flink_jpmml_tpu.parallel.mesh import make_mesh
    from flink_jpmml_tpu.parallel.partitioner import HashPartitioner
    from flink_jpmml_tpu.parallel.sharding import dp_sharded
    from flink_jpmml_tpu.pmml import parse_pmml_file
    from flink_jpmml_tpu.utils.config import MeshConfig

    pid = int(sys.argv[1])
    nproc = int(sys.argv[3])
    pmml_path = sys.argv[4]
    assert init_distributed(
        coordinator_address=sys.argv[2], num_processes=nproc, process_id=pid
    )
    mesh = make_mesh(MeshConfig(data=jax.device_count(), model=1))

    doc = parse_pmml_file(pmml_path)
    cm = compile_pmml(doc)

    # the full stream is deterministic, so every process derives the same
    # partition map; each keeps only its own hash lane (Flink keyBy parity)
    N, F = 256, 6
    rng = np.random.default_rng(0)
    X_full = rng.normal(0.0, 1.5, size=(N, F)).astype(np.float32)
    M_full = rng.random(size=(N, F)) < 0.1
    X_full[M_full] = 0.0

    part = HashPartitioner(nproc, key_fn=lambda i: i)
    lanes = [[i for i in range(N) if part.lane(i) == p]
             for p in range(nproc)]
    # identical on every process (deterministic stream + hash), so the
    # per-process slice size agrees without any coordination
    LOCAL = max(len(rows) for rows in lanes)
    mine = lanes[pid]

    X_local = np.zeros((LOCAL, F), np.float32)
    M_local = np.zeros((LOCAL, F), bool)
    X_local[: len(mine)] = X_full[mine]
    M_local[: len(mine)] = M_full[mine]

    # global row → original record index (−1 = padding)
    gmap = []
    for rows in lanes:
        gmap.extend(rows + [-1] * (LOCAL - len(rows)))

    sm = dp_sharded(cm, mesh)
    Xg, Mg = global_batch(mesh, X_local, M_local)
    out = sm.predict(Xg, Mg)

    # single-process reference, computed locally on this host's device
    ref = np.asarray(cm.predict(X_full, M_full).value, np.float32)

    checked = 0
    for shard in out.value.addressable_shards:
        sl = shard.index[0]
        vals = np.asarray(shard.data, np.float32)
        for j, g in enumerate(range(sl.start, sl.stop)):
            orig = gmap[g]
            if orig >= 0:
                assert abs(vals[j] - ref[orig]) < 1e-4, (g, orig)
                checked += 1
    assert checked > 0, "no real lanes on this process's shards"
    print(f"proc {{pid}} OK checked={{checked}}")
    """
)


@pytest.mark.parametrize("nproc", [2, 4])
def test_multi_process_end_to_end_gbm_scoring(tmp_path, nproc):
    from assets.generate import gen_gbm

    pmml = gen_gbm(str(tmp_path), n_trees=12, depth=3, n_features=6)
    outs = _run_procs(tmp_path, _E2E_WORKER, nproc, extra_args=(pmml,))
    # every process verified a non-trivial share of the global batch
    for out in outs:
        assert "checked=" in out
