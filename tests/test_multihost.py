"""Real 2-process jax.distributed smoke test (SURVEY.md §3 row D1).

The in-process tests exercise sharding on a virtual 8-device mesh; this one
spawns two actual OS processes that join one process group over a local
coordinator, contribute process-local batch slices via
``jax.make_array_from_process_local_data``, and run a psum-backed global
computation — the CPU stand-in for the multi-host ICI/DCN path the
reference delegates to Flink's Akka/Netty runtime.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent(
    """
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, {repo!r})
    import numpy as np
    from flink_jpmml_tpu.parallel.distributed import (
        global_batch, init_distributed,
    )
    from flink_jpmml_tpu.parallel.mesh import make_mesh
    from flink_jpmml_tpu.utils.config import MeshConfig

    pid = int(sys.argv[1])
    ok = init_distributed(
        coordinator_address=sys.argv[2], num_processes=2, process_id=pid
    )
    assert ok, "init_distributed returned False in a 2-process job"
    assert jax.process_count() == 2
    mesh = make_mesh(MeshConfig(data=jax.device_count(), model=1))

    # each process contributes 4 rows; global batch is 8 rows
    X_local = np.full((4, 3), float(pid + 1), np.float32)
    M_local = np.zeros((4, 3), bool)
    Xg, Mg = global_batch(mesh, X_local, M_local)
    assert Xg.shape == (8, 3)

    total = float(jax.jit(lambda x: x.sum())(Xg))
    # 4*3 ones + 4*3 twos = 36, same answer on every process
    assert total == 36.0, total
    print(f"proc {{pid}} OK total={{total}}")
    """
)


def test_two_process_group_global_batch(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    script = tmp_path / "worker.py"
    script.write_text(_WORKER.format(repo=repo))

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # one device per process, no virtual mesh
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), coord],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=110)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert f"proc {i} OK" in out
