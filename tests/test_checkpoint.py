"""runtime/checkpoint.py direct unit coverage: atomic save, retention,
corrupt-latest fallback (what retention exists for), total corruption."""

import json
import pathlib
import time

import pytest

from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
from flink_jpmml_tpu.utils.exceptions import CheckpointException


class TestCheckpointManager:
    def test_save_load_roundtrip(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save({"source_offset": 42, "served": {"m_1": "/p"}})
        assert mgr.load_latest() == {
            "source_offset": 42, "served": {"m_1": "/p"},
        }

    def test_empty_dir_is_none(self, tmp_path):
        assert CheckpointManager(str(tmp_path)).load_latest() is None

    def test_retention_keeps_last_k(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep=3)
        for i in range(6):
            mgr.save({"source_offset": i})
            time.sleep(0.002)  # distinct microsecond stamps
        files = sorted(tmp_path.glob("ckpt-*.json"))
        assert len(files) == 3
        assert mgr.load_latest() == {"source_offset": 5}

    def test_corrupt_latest_falls_back_with_warning(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep=3)
        mgr.save({"source_offset": 1})
        time.sleep(0.002)
        mgr.save({"source_offset": 2})
        time.sleep(0.002)
        mgr.save({"source_offset": 3})
        newest = sorted(tmp_path.glob("ckpt-*.json"))[-1]
        newest.write_text("{truncated")
        with pytest.warns(RuntimeWarning, match="corrupt checkpoint"):
            state = mgr.load_latest()
        assert state == {"source_offset": 2}  # older offset: replay, not loss

    def test_invalid_utf8_latest_falls_back_with_warning(self, tmp_path):
        # bit-rot can turn the newest snapshot into NON-UTF-8 bytes: the
        # decode error is deterministic corruption (UnicodeDecodeError,
        # a ValueError), so restore must fall back to an older retained
        # snapshot exactly like malformed JSON — not crash the resume
        mgr = CheckpointManager(str(tmp_path), keep=3)
        mgr.save({"source_offset": 7})
        time.sleep(0.002)
        mgr.save({"source_offset": 8})
        newest = sorted(tmp_path.glob("ckpt-*.json"))[-1]
        newest.write_bytes(b'{"state": \xff\xfe\x80 torn}')
        with pytest.warns(RuntimeWarning, match="corrupt checkpoint"):
            state = mgr.load_latest()
        assert state == {"source_offset": 7}

    def test_truncated_latest_falls_back_with_warning(self, tmp_path):
        # a truncated-to-empty newest file is the classic torn-disk shape
        mgr = CheckpointManager(str(tmp_path), keep=3)
        mgr.save({"source_offset": 4})
        time.sleep(0.002)
        mgr.save({"source_offset": 5})
        newest = sorted(tmp_path.glob("ckpt-*.json"))[-1]
        newest.write_bytes(b"")
        with pytest.warns(RuntimeWarning, match="corrupt checkpoint"):
            assert mgr.load_latest() == {"source_offset": 4}

    def test_two_corrupt_newest_walks_back_to_third(self, tmp_path):
        # the walk-back must traverse ALL retained snapshots, not fall
        # back exactly one: correlated damage (a dying disk, a torn
        # rsync) routinely takes the two newest together, and retention
        # exists precisely so the third can still resume the job
        mgr = CheckpointManager(str(tmp_path), keep=4)
        for off in (1, 2, 3, 4):
            mgr.save({"source_offset": off})
            time.sleep(0.002)
        snaps = sorted(tmp_path.glob("ckpt-*.json"))
        snaps[-1].write_text("{torn")
        snaps[-2].write_bytes(b"\xff\xfe not json either")
        with pytest.warns(RuntimeWarning, match="corrupt checkpoint"):
            state = mgr.load_latest()
        assert state == {"source_offset": 2}

    def test_all_corrupt_is_typed_error(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep=2)
        mgr.save({"source_offset": 1})
        time.sleep(0.002)
        mgr.save({"source_offset": 2})
        for p in tmp_path.glob("ckpt-*.json"):
            p.write_text("not json at all")
        with pytest.raises(CheckpointException, match="no readable"):
            mgr.load_latest()

    def test_non_dict_json_is_corrupt(self, tmp_path):
        # valid JSON that isn't the payload shape (e.g. null) must take
        # the fallback path, not crash with TypeError
        mgr = CheckpointManager(str(tmp_path))
        mgr.save({"source_offset": 5})
        time.sleep(0.002)
        bad = pathlib.Path(mgr.save({"source_offset": 6}))
        bad.write_text("null")
        with pytest.warns(RuntimeWarning, match="corrupt"):
            assert mgr.load_latest() == {"source_offset": 5}

    def test_missing_state_key_is_corrupt(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save({"source_offset": 7})
        time.sleep(0.002)
        bad = pathlib.Path(
            mgr.save({"source_offset": 8})
        )
        bad.write_text(json.dumps({"timestamp": 0}))  # no "state"
        with pytest.warns(RuntimeWarning, match="corrupt"):
            assert mgr.load_latest() == {"source_offset": 7}

    def test_transient_oserror_retries_once(self, tmp_path, monkeypatch):
        # an EMFILE-style hiccup on the newest snapshot must not roll
        # the job back a retention window: one retry, then success
        mgr = CheckpointManager(str(tmp_path))
        mgr.save({"source_offset": 1})
        time.sleep(0.002)
        mgr.save({"source_offset": 2})
        real_open = open
        fails = {"n": 1}

        def flaky_open(path, *a, **kw):
            if "ckpt-" in str(path) and fails["n"] > 0:
                fails["n"] -= 1
                raise OSError(24, "Too many open files")
            return real_open(path, *a, **kw)

        monkeypatch.setattr("builtins.open", flaky_open)
        assert mgr.load_latest() == {"source_offset": 2}

    def test_persistent_oserror_raises_not_falls_back(
        self, tmp_path, monkeypatch
    ):
        # a persistent I/O failure on an intact newest snapshot raises
        # (operator-visible) instead of silently resuming from an older
        # offset — corruption falls back, transport failure does not
        mgr = CheckpointManager(str(tmp_path))
        mgr.save({"source_offset": 1})
        time.sleep(0.002)
        newest = mgr.save({"source_offset": 2})
        real_open = open

        def broken_open(path, *a, **kw):
            if str(path) == newest:
                raise OSError(13, "Permission denied")
            return real_open(path, *a, **kw)

        monkeypatch.setattr("builtins.open", broken_open)
        with pytest.raises(CheckpointException, match="transient I/O"):
            mgr.load_latest()

    def test_vanished_file_falls_back(self, tmp_path, monkeypatch):
        # FileNotFoundError = a concurrent GC removed it between listing
        # and opening: fall back past it (no intact snapshot is skipped)
        mgr = CheckpointManager(str(tmp_path))
        mgr.save({"source_offset": 1})
        time.sleep(0.002)
        newest = mgr.save({"source_offset": 2})
        real_open = open

        def racing_open(path, *a, **kw):
            if str(path) == newest:
                raise FileNotFoundError(2, "No such file", str(path))
            return real_open(path, *a, **kw)

        monkeypatch.setattr("builtins.open", racing_open)
        with pytest.warns(RuntimeWarning):
            assert mgr.load_latest() == {"source_offset": 1}


class TestCrashSafeWrites:
    """PR 8 satellite: the newest snapshot itself must be crash-safe —
    temp-file + fsync + os.replace + directory fsync means a SIGKILL at
    ANY instant leaves every retained ``ckpt-*.json`` parseable."""

    _CHILD = r"""
import sys, time
sys.path.insert(0, sys.argv[2])
from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
mgr = CheckpointManager(sys.argv[1], keep=4)
# a chunky state widens the mid-write window the kill must land in
state = {"source_offset": 0, "pad": "x" * 200_000}
i = 0
print("ready", flush=True)
while True:
    state["source_offset"] = i
    mgr.save(state)
    i += 1
"""

    def test_kill_mid_write_leaves_parseable_snapshots(self, tmp_path):
        import os
        import signal
        import subprocess
        import sys

        repo = str(pathlib.Path(__file__).resolve().parent.parent)
        for round_i in range(2):
            ckpt_dir = tmp_path / f"r{round_i}"
            ckpt_dir.mkdir()
            proc = subprocess.Popen(
                [sys.executable, "-c", self._CHILD,
                 str(ckpt_dir), repo],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True,
            )
            try:
                assert proc.stdout.readline().strip() == "ready"
                # let a few writes land, then kill mid-flight
                time.sleep(0.25 + 0.2 * round_i)
                os.kill(proc.pid, signal.SIGKILL)
            finally:
                proc.wait(timeout=10)
            snaps = sorted(ckpt_dir.glob("ckpt-*.json"))
            assert snaps, "child never completed a checkpoint"
            # EVERY retained snapshot parses — the atomic-replace
            # protocol admits no torn ckpt-*.json at any kill instant
            for p in snaps:
                payload = json.loads(p.read_text())
                assert "state" in payload and isinstance(
                    payload["state"]["source_offset"], int
                )
            restored = CheckpointManager(str(ckpt_dir)).load_latest()
            assert restored is not None
            assert restored["source_offset"] >= 0

    def test_transient_write_failure_retries(self, tmp_path, monkeypatch):
        # the shared backoff helper turns one flaky fsync into a retry,
        # not a lost snapshot (runtime/faults.py checkpoint_fail rides
        # the same path — see tests/test_faults.py)
        monkeypatch.setenv("FJT_RETRY_BASE_S", "0.001")
        from flink_jpmml_tpu.runtime import faults

        faults.clear()
        faults.inject("checkpoint_fail", n=2)
        try:
            mgr = CheckpointManager(str(tmp_path))
            mgr.save({"source_offset": 7})
        finally:
            faults.clear()
        assert mgr.load_latest() == {"source_offset": 7}
        assert not list(tmp_path.glob(".tmp-*")), (
            "failed attempts littered temp files"
        )
