"""fjt-score CLI (flink_jpmml_tpu/cli.py): CSV + JSONL in, JSONL
predictions out, parity with score_records, stdin/stdout plumbing."""

import json
import pathlib

import numpy as np
import pytest

from flink_jpmml_tpu.api import ModelReader
from flink_jpmml_tpu.assets_gen import gen_iris_lr
from flink_jpmml_tpu.cli import score_main


@pytest.fixture()
def iris(tmp_path):
    return gen_iris_lr(str(tmp_path))


def _write_inputs(tmp_path, fields, rows):
    csv_p = pathlib.Path(tmp_path, "in.csv")
    lines = [",".join(fields)]
    for row in rows:
        lines.append(",".join("" if v is None else str(v) for v in row))
    csv_p.write_text("\n".join(lines) + "\n")
    jsonl_p = pathlib.Path(tmp_path, "in.jsonl")
    jsonl_p.write_text(
        "\n".join(
            json.dumps({f: v for f, v in zip(fields, row) if v is not None})
            for row in rows
        )
        + "\n"
    )
    return str(csv_p), str(jsonl_p)


class TestScoreCli:
    def test_csv_and_jsonl_match_api(self, tmp_path, iris):
        cm = ModelReader(iris).load()
        fields = list(cm.field_space.fields)
        rng = np.random.default_rng(3)
        rows = [
            [round(float(v), 4) for v in rng.normal(3, 2, len(fields))]
            for _ in range(20)
        ]
        rows[5] = [None] * len(fields)  # all-missing record → empty lane
        csv_p, jsonl_p = _write_inputs(tmp_path, fields, rows)

        recs = [
            {f: v for f, v in zip(fields, row) if v is not None}
            for row in rows
        ]
        ref = cm.score_records(recs)

        for inp in (csv_p, jsonl_p):
            out_p = str(pathlib.Path(tmp_path, "out.jsonl"))
            rc = score_main([iris, inp, "-o", out_p, "--platform", "cpu"])
            assert rc == 0
            got = [
                json.loads(ln)
                for ln in pathlib.Path(out_p).read_text().splitlines()
            ]
            assert len(got) == len(ref)
            for g, r in zip(got, ref):
                if r.is_empty:
                    assert g == {"empty": True}
                else:
                    assert g["value"] == pytest.approx(
                        r.score.value, rel=1e-6
                    )
                    assert g["label"] == r.target.label
                    assert g["probs"][r.target.label] == pytest.approx(
                        r.target.probabilities[r.target.label], abs=2e-6
                    )

    def test_replace_nan_fills_numeric_fields(self, tmp_path, iris):
        cm = ModelReader(iris).load()
        fields = list(cm.field_space.fields)
        rows = [[None] * len(fields), [1.0] + [None] * (len(fields) - 1)]
        csv_p, _ = _write_inputs(tmp_path, fields, rows)
        out_p = str(pathlib.Path(tmp_path, "out.jsonl"))
        assert score_main(
            [iris, csv_p, "-o", out_p, "--replace-nan", "0.0",
             "--platform", "cpu"]
        ) == 0
        got = [
            json.loads(ln)
            for ln in pathlib.Path(out_p).read_text().splitlines()
        ]
        # with replacement nothing is empty, and row 0 == all-zeros record
        assert all("empty" not in g for g in got)
        ref = cm.score_records([{f: 0.0 for f in fields}])[0]
        assert got[0]["value"] == pytest.approx(ref.score.value, rel=1e-6)

    def test_stdin_jsonl(self, tmp_path, iris, monkeypatch, capsys):
        import io
        import sys

        cm = ModelReader(iris).load()
        fields = list(cm.field_space.fields)
        rec = {f: 2.0 for f in fields}
        monkeypatch.setattr(
            sys, "stdin", io.StringIO(json.dumps(rec) + "\n")
        )
        assert score_main([iris, "-", "--platform", "cpu"]) == 0
        out = capsys.readouterr().out.strip().splitlines()
        assert len(out) == 1
        ref = cm.score_records([rec])[0]
        assert json.loads(out[0])["value"] == pytest.approx(
            ref.score.value, rel=1e-6
        )

    def test_invalid_jsonl_is_typed_exit(self, tmp_path, iris):
        bad = pathlib.Path(tmp_path, "bad.jsonl")
        bad.write_text("{not json}\n")
        with pytest.raises(SystemExit, match="invalid JSON"):
            score_main([iris, str(bad), "--platform", "cpu"])

    def test_missing_files_are_typed_exits(self, tmp_path, iris):
        with pytest.raises(SystemExit, match="cannot read"):
            score_main([iris, str(tmp_path / "nope.csv"),
                        "--platform", "cpu"])
        good = pathlib.Path(tmp_path, "ok.jsonl")
        good.write_text("{}\n")
        with pytest.raises(SystemExit, match="cannot write"):
            score_main([iris, str(good), "-o",
                        str(tmp_path / "no" / "dir" / "out.jsonl"),
                        "--platform", "cpu"])

    def test_csv_numeric_looking_categoricals_ride_the_codec(self, tmp_path):
        # a CSV cell "2" for a string-categorical field must stay a
        # string: float-parsing it would bypass the codec and alias onto
        # the wrong category code
        xml = """<PMML version="4.3"><DataDictionary>
          <DataField name="c" optype="categorical" dataType="string">
            <Value value="1"/><Value value="2"/><Value value="3"/>
          </DataField>
          <DataField name="y" optype="continuous" dataType="double"/>
          </DataDictionary>
          <RegressionModel functionName="regression">
          <MiningSchema><MiningField name="y" usageType="target"/>
            <MiningField name="c"/></MiningSchema>
          <RegressionTable intercept="0.0">
            <CategoricalPredictor name="c" value="1" coefficient="10"/>
            <CategoricalPredictor name="c" value="2" coefficient="20"/>
            <CategoricalPredictor name="c" value="3" coefficient="30"/>
          </RegressionTable></RegressionModel></PMML>"""
        model = pathlib.Path(tmp_path, "cat.pmml")
        model.write_text(xml)
        csv_p = pathlib.Path(tmp_path, "in.csv")
        csv_p.write_text("c\n2\n3\n")
        out_p = str(pathlib.Path(tmp_path, "out.jsonl"))
        assert score_main(
            [str(model), str(csv_p), "-o", out_p, "--platform", "cpu"]
        ) == 0
        got = [
            json.loads(ln)
            for ln in pathlib.Path(out_p).read_text().splitlines()
        ]
        assert [g["value"] for g in got] == [
            pytest.approx(20.0), pytest.approx(30.0)
        ]
