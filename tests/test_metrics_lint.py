"""Tier-1 guard: metric names emitted in code and the operator
catalogue (docs/operations.md) cannot silently drift — dashboards and
alert rules key on these names (tools/metrics_lint.py)."""

import pathlib
import subprocess
import sys

_LINT = (
    pathlib.Path(__file__).resolve().parent.parent
    / "tools" / "metrics_lint.py"
)


def test_metric_names_match_catalogue():
    proc = subprocess.run(
        [sys.executable, str(_LINT)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, (
        f"metrics lint rc={proc.returncode}\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    assert "in sync" in proc.stdout
