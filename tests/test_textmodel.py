"""TextModel: weighted document similarity, compiled vs oracle vs hand
math across local/global weights, normalization and similarity types."""

import math

import numpy as np
import pytest

from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml
from flink_jpmml_tpu.pmml.interp import evaluate
from flink_jpmml_tpu.utils.exceptions import ModelLoadingException

TEXT = """<PMML version="4.2"><DataDictionary>
  <DataField name="ball" optype="continuous" dataType="double"/>
  <DataField name="goal" optype="continuous" dataType="double"/>
  <DataField name="oven" optype="continuous" dataType="double"/>
  <DataField name="salt" optype="continuous" dataType="double"/>
  </DataDictionary>
  <TextModel functionName="classification" numberOfTerms="4"
      numberOfDocuments="3">
  <MiningSchema>
    <MiningField name="ball"/><MiningField name="goal"/>
    <MiningField name="oven"/><MiningField name="salt"/>
  </MiningSchema>
  <TextDictionary><Array n="4" type="string">ball goal oven salt</Array>
  </TextDictionary>
  <TextCorpus>
    <TextDocument id="sports"/><TextDocument id="cooking"/>
    <TextDocument id="mixed"/>
  </TextCorpus>
  <DocumentTermMatrix><Matrix>
    <Array n="4" type="real">5 3 0 0</Array>
    <Array n="4" type="real">0 0 4 6</Array>
    <Array n="4" type="real">2 1 1 2</Array>
  </Matrix></DocumentTermMatrix>
  {normalization}
  {similarity}
  </TextModel></PMML>"""

DTM = np.array([[5, 3, 0, 0], [0, 0, 4, 6], [2, 1, 1, 2]], float)
DOCS = ["sports", "cooking", "mixed"]


def _hand_scores(q, local="termFrequency", glob="none", doc_norm="none",
                 sim="cosine"):
    def lw(v):
        v = np.maximum(np.asarray(v, float), 0.0)
        if local == "binary":
            return (v > 0).astype(float)
        if local == "logarithmic":
            return np.log10(1.0 + v)
        if local == "augmentedNormalizedTermFrequency":
            m = v.max()
            return np.where((v > 0) & (m > 0), 0.5 + 0.5 * v / max(m, 1e-30), 0.0)
        return v

    if glob == "inverseDocumentFrequency":
        dj = (DTM > 0).sum(axis=0)
        idf = np.where(dj > 0, np.log10(len(DOCS) / np.maximum(dj, 1)), 0.0)
    else:
        idf = np.ones(4)

    def w(v):
        x = lw(v) * idf
        if doc_norm == "cosine":
            n = np.linalg.norm(x)
            if n > 0:
                x = x / n
        return x

    qw = w(q)
    out = {}
    for did, row in zip(DOCS, DTM):
        dw = w(row)
        if sim == "cosine":
            nq, nd = np.linalg.norm(qw), np.linalg.norm(dw)
            out[did] = float(qw @ dw / (nq * nd)) if nq > 0 and nd > 0 else 0.0
        else:
            out[did] = float(np.linalg.norm(qw - dw))
    return out


def _xml(local=None, glob=None, doc_norm=None, sim=None):
    norm = ""
    if local or glob or doc_norm:
        norm = (
            f'<TextModelNormalization '
            f'localTermWeights="{local or "termFrequency"}" '
            f'globalTermWeights="{glob or "none"}" '
            f'documentNormalization="{doc_norm or "none"}"/>'
        )
    s = f'<TextModelSimilarity similarityType="{sim}"/>' if sim else ""
    return TEXT.format(normalization=norm, similarity=s)


class TestTextModel:
    @pytest.mark.parametrize(
        "local,glob,doc_norm,sim",
        [
            (None, None, None, None),  # all defaults: tf / none / cosine
            ("binary", None, None, "cosine"),
            ("logarithmic", "inverseDocumentFrequency", None, "cosine"),
            ("augmentedNormalizedTermFrequency", None, "cosine", "cosine"),
            ("termFrequency", "inverseDocumentFrequency", "cosine",
             "euclidean"),
        ],
    )
    def test_similarity_parity(self, local, glob, doc_norm, sim):
        doc = parse_pmml(_xml(local, glob, doc_norm, sim))
        cm = compile_pmml(doc)
        rng = np.random.default_rng(5)
        queries = [rng.integers(0, 6, size=4).astype(float) for _ in range(20)]
        queries.append(np.array([3.0, 2.0, 0.0, 0.0]))  # clearly sports
        recs = [
            dict(zip(("ball", "goal", "oven", "salt"), q.tolist()))
            for q in queries
        ]
        preds = cm.score_records(recs)
        for q, rec, p in zip(queries, recs, preds):
            hand = _hand_scores(
                q, local or "termFrequency", glob or "none",
                doc_norm or "none", sim or "cosine",
            )
            o = evaluate(doc, rec)
            for did in DOCS:
                assert o.probabilities[did] == pytest.approx(
                    hand[did], abs=1e-9
                )
                assert p.target.probabilities[did] == pytest.approx(
                    hand[did], abs=1e-4
                )
            assert p.target.label == o.label

    def test_sports_query_wins(self):
        doc = parse_pmml(_xml())
        cm = compile_pmml(doc)
        p = cm.score_records([{"ball": 4, "goal": 2, "oven": 0, "salt": 0}])[0]
        assert p.target.label == "sports"
        assert evaluate(
            doc, {"ball": 4, "goal": 2, "oven": 0, "salt": 0}
        ).label == "sports"

    def test_missing_counts_read_zero(self):
        doc = parse_pmml(_xml())
        cm = compile_pmml(doc)
        rec = {"ball": 4.0, "goal": None, "oven": None, "salt": None}
        p = cm.score_records([rec])[0]
        o = evaluate(doc, rec)
        assert not p.is_empty and p.target.label == o.label

    def test_rejections(self):
        with pytest.raises(ModelLoadingException, match="shape"):
            parse_pmml(_xml().replace(
                '<Array n="4" type="real">2 1 1 2</Array>', ""
            ))
        with pytest.raises(ModelLoadingException, match="active MiningField"):
            parse_pmml(_xml().replace('<MiningField name="salt"/>', ""))
        with pytest.raises(ModelLoadingException, match="localTermWeights"):
            parse_pmml(_xml(local="squareRoot"))
        with pytest.raises(ModelLoadingException, match="duplicate"):
            parse_pmml(_xml().replace(
                '<TextDocument id="cooking"/>', '<TextDocument id="sports"/>'
            ))
