"""Tier-1 guard for the overlapped-dispatch concurrency code: run the
tools/perf_smoke.py check in a subprocess (its watchdog converts a
shutdown hang into a non-zero exit instead of a wedged test session).
Deliberately NOT marked slow — this is the fast-loop tripwire for
ordering and shutdown regressions in runtime/pipeline.py."""

import os
import pathlib
import subprocess
import sys

_SMOKE = (
    pathlib.Path(__file__).resolve().parent.parent / "tools" / "perf_smoke.py"
)


def test_perf_smoke_passes():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # +30s over the pre-device-fault budget: the device-fault check
    # paces a ~12k-record stream through a breaker lifecycle (~3-6s)
    # plus one extra GBM compile; +30s more for the history check's
    # 1s armed-budget window, GBM compile, and live /history reconcile;
    # +60s more for the keyed-state check: one extra GBM compile, two
    # state-entry jit compiles, 120 timed dispatches, and a replay-
    # parity pass over 80 more
    env["FJT_SMOKE_WATCHDOG_S"] = "330"
    env.pop("FJT_FAULTS", None)  # the no-op check requires a clean env
    env.pop("FJT_RESTART_STREAK", None)
    env.pop("FJT_JOURNEY_DIR", None)  # the journey gate check likewise
    env.pop("FJT_FAILOVER", None)  # the fail-fast default likewise
    env.pop("FJT_HISTORY_DIR", None)  # the unarmed-gate check likewise
    env.pop("FJT_METRICS_MAX_SERIES", None)  # reconcile needs raw series
    env.pop("FJT_STATE_CAPACITY", None)  # keyed-state check sizes its own
    env.pop("FJT_STATE_PROBE", None)
    env.pop("FJT_STATE_DECAY", None)
    env.pop("FJT_STATE_STRIDE", None)
    proc = subprocess.run(
        [sys.executable, str(_SMOKE)],
        capture_output=True, text=True, timeout=420, env=env,
    )
    assert proc.returncode == 0, (
        f"perf smoke rc={proc.returncode}\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-2000:]}"
    )
    assert "dispatcher ordering OK" in proc.stdout
    assert "block pipeline drain/ordering OK" in proc.stdout
    assert "kafka pipeline OK" in proc.stdout
    assert "fused encode parity OK" in proc.stdout
    assert "autotune cache roundtrip OK" in proc.stdout
    assert "kernel search OK" in proc.stdout
    assert "obs /metrics scrape OK" in proc.stdout
    assert "attribution overhead OK" in proc.stdout
    assert "rollout drill OK" in proc.stdout
    assert "freshness burst drill OK" in proc.stdout
    assert "overload drill OK" in proc.stdout
    assert "journey trace OK" in proc.stdout
    assert "recovery drill OK" in proc.stdout
    assert "device fault plane OK" in proc.stdout
    assert "fault hooks no-op OK" in proc.stdout
    assert "mesh gate no-op OK" in proc.stdout
    assert "history OK" in proc.stdout
    assert "keyed state OK" in proc.stdout
