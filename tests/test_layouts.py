"""Layout-catalogue byte-parity suite (compile/layouts.py, ISSUE 11).

Every catalogue variant — breadth-first SoA split order, uint8/uint16
threshold-rank wire packing, the Pallas multi-tree megakernel — must
score BYTE-IDENTICALLY to the reference packing across NaN, explicit
missing masks, ±inf cells, and mining-schema
``missingValueReplacement`` inputs (the test_fused_encode.py pattern),
including interpret-mode Pallas. The variants change memory layout,
never math: BFS permutes the reduced S axis (integer/exact-f32 sums),
the wire pack round-trips ranks exactly, the megakernel accumulates
groups in the same ascending order as the grid."""

import numpy as np
import pytest

from assets.generate import gen_gbm
from flink_jpmml_tpu.compile import layouts
from flink_jpmml_tpu.compile.gtrees import pack_general
from flink_jpmml_tpu.compile.qtrees import QuantizedWire, build_quantized_scorer
from flink_jpmml_tpu.pmml import parse_pmml, parse_pmml_file

from test_qtrees import _forest_xml


def _doc(tmp_path, **kw):
    return parse_pmml_file(gen_gbm(str(tmp_path), **kw))


def _adversarial_X(rng, n, f, missing_rate=0.25):
    """The satellite's input grid: NaN, ±inf, and ordinary values."""
    X = rng.normal(0.0, 1.5, size=(n, f)).astype(np.float32)
    X[rng.random(size=X.shape) < missing_rate] = np.nan
    X[0, 0] = np.inf
    X[1, f - 1] = -np.inf
    return X


def stump_forest_xml(n_a=300, n_b=5):
    """A sum forest of depth-1 stumps with skewed cut cardinality:
    feature ``a`` carries ``n_a`` distinct thresholds (>254 → uint16
    wire), feature ``b`` only ``n_b`` — the mixed-width shape the wire
    pack exists for."""
    segs = []
    i = 0
    for field, n in (("a", n_a), ("b", n_b)):
        for k in range(n):
            thr = round(-3.0 + 6.0 * (k + 1) / (n + 1), 6)
            i += 1
            segs.append(f"""
      <Segment><True/>
        <TreeModel functionName="regression"
                   missingValueStrategy="defaultChild"
                   splitCharacteristic="binarySplit">
          <MiningSchema><MiningField name="y" usageType="target"/>
            <MiningField name="a"/><MiningField name="b"/></MiningSchema>
          <Node id="r" defaultChild="l"><True/>
            <Node id="l" score="{0.01 * i}">
              <SimplePredicate field="{field}" operator="lessOrEqual"
                               value="{thr}"/></Node>
            <Node id="g" score="{-0.01 * i}">
              <SimplePredicate field="{field}" operator="greaterThan"
                               value="{thr}"/></Node>
          </Node>
        </TreeModel>
      </Segment>""")
    return f"""<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
  <Header/>
  <DataDictionary numberOfFields="3">
    <DataField name="a" optype="continuous" dataType="double"/>
    <DataField name="b" optype="continuous" dataType="double"/>
    <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <MiningModel functionName="regression">
    <MiningSchema><MiningField name="y" usageType="target"/>
      <MiningField name="a"/><MiningField name="b"/></MiningSchema>
    <Segmentation multipleModelMethod="sum">{''.join(segs)}
    </Segmentation>
  </MiningModel></PMML>"""


_REPL_XML = """<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
  <Header/>
  <DataDictionary numberOfFields="3">
    <DataField name="a" optype="continuous" dataType="double"/>
    <DataField name="b" optype="continuous" dataType="double"/>
    <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <TreeModel functionName="regression" missingValueStrategy="defaultChild"
             splitCharacteristic="binarySplit">
    <MiningSchema>
      <MiningField name="y" usageType="target"/>
      <MiningField name="a" missingValueReplacement="0.25"/>
      <MiningField name="b"/>
    </MiningSchema>
    <Node id="0" defaultChild="1"><True/>
      <Node id="1" defaultChild="3">
        <SimplePredicate field="a" operator="lessThan" value="0.1"/>
        <Node id="3" score="1.5">
          <SimplePredicate field="b" operator="lessOrEqual" value="-0.2"/>
        </Node>
        <Node id="4" score="-2.0">
          <SimplePredicate field="b" operator="greaterThan" value="-0.2"/>
        </Node>
      </Node>
      <Node id="2" score="3.0">
        <SimplePredicate field="a" operator="greaterOrEqual" value="0.1"/>
      </Node>
    </Node>
  </TreeModel></PMML>"""


class TestBfsSplitOrder:
    def test_order_is_descending_reach(self):
        # a 3-split depth-2 tree: root reaches 4 leaves, each child 2
        P = np.zeros((1, 3, 4), np.int8)
        P[0, 2] = [1, 1, -1, -1]     # root (slot 2 on purpose)
        P[0, 0] = [1, -1, 0, 0]      # left child
        P[0, 1] = [0, 0, 1, -1]      # right child
        perm = layouts.bfs_split_order(P)
        assert perm[0].tolist() == [2, 0, 1]

    def test_xla_bfs_bit_exact(self, tmp_path):
        doc = _doc(tmp_path, n_trees=15, depth=4, n_features=6)
        q_ref = build_quantized_scorer(doc, batch_size=64, backend="xla")
        q = build_quantized_scorer(doc, batch_size=64, backend="xla")
        built = q.build_variant("bfs")
        assert built is not None
        q.adopt_variant(built, "bfs")
        assert q.layout == "bfs"
        rng = np.random.default_rng(0)
        for n in (64, 64 - 9, 2 * 64 + 7):
            X = _adversarial_X(rng, n, 6)
            ref = np.asarray(
                q_ref.predict_wire(q_ref.wire.encode(X)), np.float32
            )
            got = np.asarray(q.predict_wire(q.wire.encode(X)), np.float32)
            np.testing.assert_array_equal(got, ref)

    def test_missing_value_replacement_bit_exact(self):
        doc = parse_pmml(_REPL_XML)
        q_ref = build_quantized_scorer(doc, batch_size=8)
        q = build_quantized_scorer(doc, batch_size=8)
        q.adopt_variant(q.build_variant("bfs"), "bfs")
        X = np.array(
            [[np.nan, -0.5], [np.nan, 0.5], [0.0, np.nan], [2.0, -1.0]],
            np.float32,
        )
        np.testing.assert_array_equal(
            np.asarray(q.predict_wire(q.wire.encode(X)), np.float32),
            np.asarray(q_ref.predict_wire(q_ref.wire.encode(X)), np.float32),
        )


class TestWirePack:
    def test_plan_none_for_uint8_wire(self, tmp_path):
        doc = _doc(tmp_path, n_trees=10, depth=3, n_features=4)
        q = build_quantized_scorer(doc, batch_size=32)
        assert q.wire.dtype is np.uint8
        assert layouts.plan_wire_pack(q.wire) is None

    def test_plan_none_when_nothing_fits_uint8(self):
        cuts = tuple(
            np.linspace(-1, 1, 300).astype(np.float32) for _ in range(2)
        )
        wire = QuantizedWire(
            fields=("a", "b"), cuts=cuts, dtype=np.uint16, sentinel=65535,
            repl=np.zeros((2,), np.float32),
            has_repl=np.zeros((2,), bool),
        )
        assert layouts.plan_wire_pack(wire) is None

    def test_pack_roundtrip_exact(self):
        cuts = (
            np.linspace(-1, 1, 300).astype(np.float32),
            np.linspace(-1, 1, 5).astype(np.float32),
        )
        wire = QuantizedWire(
            fields=("a", "b"), cuts=cuts, dtype=np.uint16, sentinel=65535,
            repl=np.zeros((2,), np.float32),
            has_repl=np.zeros((2,), bool),
        )
        wp = layouts.plan_wire_pack(wire)
        assert wp is not None and wp.width == 3  # 2 + 1 bytes
        rng = np.random.default_rng(1)
        codes = np.stack(
            [
                rng.integers(0, 301, size=64).astype(np.uint16),
                rng.integers(0, 6, size=64).astype(np.uint16),
            ],
            axis=1,
        )
        codes[0] = [65535, 65535]  # the sentinel survives both widths
        codes[1, 0] = 255  # a rank that collides with uint8's marker
        np.testing.assert_array_equal(
            wp.unpack_host(wp.pack(codes)), codes.astype(np.int64)
        )

    def test_scoring_bit_exact_and_fewer_bytes(self):
        doc = parse_pmml(stump_forest_xml())
        q_ref = build_quantized_scorer(doc, batch_size=32, backend="xla")
        assert q_ref.wire.dtype is np.uint16
        rng = np.random.default_rng(2)
        X = _adversarial_X(rng, 32, 2)
        ref = np.asarray(q_ref.predict_wire(q_ref.wire.encode(X)), np.float32)
        for lay in ("wirepack", "bfs_wirepack"):
            q = build_quantized_scorer(doc, batch_size=32, backend="xla")
            built = q.build_variant(lay)
            assert built is not None, lay
            q.adopt_variant(built, lay)
            got = np.asarray(q.predict_wire(q.wire.encode(X)), np.float32)
            np.testing.assert_array_equal(got, ref)
            # the point of the layout: fewer staged bytes than the
            # all-uint16 wire (3 vs 4 here)
            assert q.staged_bytes_per_record < q_ref.staged_bytes_per_record

    def test_odd_batches_through_pad_wire(self):
        doc = parse_pmml(stump_forest_xml())
        q_ref = build_quantized_scorer(doc, batch_size=32, backend="xla")
        q = build_quantized_scorer(doc, batch_size=32, backend="xla")
        q.adopt_variant(q.build_variant("wirepack"), "wirepack")
        rng = np.random.default_rng(3)
        for n in (20, 32, 77):
            X = _adversarial_X(rng, n, 2)
            ref = [p.score.value for p in q_ref.score(X)]
            got = [p.score.value for p in q.score(X)]
            assert got == ref

    def test_dispatch_helper_accounts_packed_bytes(self):
        from flink_jpmml_tpu.runtime.pipeline import dispatch_quantized
        from flink_jpmml_tpu.utils.metrics import MetricsRegistry

        doc = parse_pmml(stump_forest_xml())
        q = build_quantized_scorer(doc, batch_size=32, backend="xla")
        q.adopt_variant(q.build_variant("wirepack"), "wirepack")
        rng = np.random.default_rng(4)
        X = rng.normal(size=(32, 2)).astype(np.float32)
        m = MetricsRegistry()
        dispatch_quantized(q, X, metrics=m)
        # 3 packed bytes per record, not 4 uint16 wire bytes
        assert m.counter("h2d_bytes").get() == 32 * 3


class TestPallasMegakernel:
    def _pallas(self, doc, batch, **kw):
        q = build_quantized_scorer(
            doc, batch_size=batch, backend="pallas", pallas_interpret=True,
            **kw,
        )
        assert q is not None and q.backend == "pallas"
        return q

    @pytest.mark.parametrize("lay", ["mega", "bfs", "mega_bfs"])
    def test_regression_bit_exact(self, tmp_path, lay):
        doc = _doc(tmp_path, n_trees=13, depth=3, n_features=4)
        B = 32
        q_ref = self._pallas(doc, B)
        q = self._pallas(doc, B)
        built = q.build_variant(lay)
        assert built is not None, lay
        q.adopt_variant(built, lay)
        rng = np.random.default_rng(5)
        for n in (B, 2 * B):  # 2*B exercises the scan (K > 1) path too
            X = _adversarial_X(rng, n, 4, missing_rate=0.2)
            Xq = q.wire.encode(X)
            np.testing.assert_array_equal(
                np.asarray(q.predict_wire(Xq), np.float32),
                np.asarray(q_ref.predict_wire(Xq), np.float32),
            )

    @pytest.mark.parametrize(
        "method,weighted,n_trees",
        [
            ("majorityVote", False, 8),
            # non-integer vote tables: f32 sums are NOT association-
            # free here, so this pins the megakernel's accumulation
            # order against the grid kernel (caught live: acc+hi+lo
            # drifted 1 ULP from acc+(hi+lo))
            ("weightedMajorityVote", True, 48),
        ],
    )
    def test_classification_votes_bit_exact(self, method, weighted, n_trees):
        doc = parse_pmml(
            _forest_xml(method, weighted=weighted, n_trees=n_trees)
        )
        B = 32
        q_ref = self._pallas(doc, B)
        assert q_ref.is_classification
        q = self._pallas(doc, B)
        q.adopt_variant(q.build_variant("mega"), "mega")
        rng = np.random.default_rng(6)
        X = _adversarial_X(rng, B, 4, missing_rate=0.2)
        Xq = q.wire.encode(X)
        rv, rp, rl = q_ref.predict_wire(Xq)
        mv, mp, ml = q.predict_wire(Xq)
        np.testing.assert_array_equal(np.asarray(ml), np.asarray(rl))
        np.testing.assert_array_equal(np.asarray(mp), np.asarray(rp))
        np.testing.assert_array_equal(np.asarray(mv), np.asarray(rv))

    def test_fused_encode_composes_with_mega(self, tmp_path):
        # the fused featurize stage rides the variant's params: one
        # dispatch covers encode+pad+score through the megakernel too
        doc = _doc(tmp_path, n_trees=13, depth=3, n_features=4)
        B = 32
        q = self._pallas(doc, B)
        q.adopt_variant(q.build_variant("mega"), "mega")
        assert q.supports_fused
        rng = np.random.default_rng(7)
        X = _adversarial_X(rng, B, 4, missing_rate=0.15)
        host = np.asarray(q.predict_wire(q.wire.encode(X)), np.float32)
        fused = np.asarray(q.predict_fused(X), np.float32)
        np.testing.assert_array_equal(fused, host)

    def test_wirepack_ineligible_on_pallas(self, tmp_path):
        doc = _doc(tmp_path, n_trees=10, depth=3, n_features=4)
        q = self._pallas(doc, 32)
        assert q.build_variant("wirepack") is None


class TestGtreesBfsLayout:
    def test_bfs_order_levels(self):
        # pre-order rows of a depth-2 binary tree: 0,(1,(2,3)),(4,(5,6))
        children = [[1, 4], [2, 3], [], [], [5, 6], [], []]
        assert layouts.bfs_order(children) == [0, 1, 4, 2, 3, 5, 6]

    def test_pack_general_rows_are_breadth_first(self):
        from flink_jpmml_tpu.compile.common import LowerCtx, build_codecs
        from flink_jpmml_tpu.utils.config import CompileConfig

        doc = parse_pmml(_REPL_XML)
        model = doc.model
        ctx = LowerCtx(
            field_index={f: i for i, f in enumerate(doc.active_fields)},
            codecs=build_codecs(doc.data_dictionary),
            config=CompileConfig(),
        )
        params, meta = pack_general([model], ctx)
        # root at 0; every parent index precedes its children (BFS)
        child_idx = params["child_idx"][0]
        is_leaf = params["is_leaf"][0]
        for ni in range(meta["N"]):
            if is_leaf[ni]:
                continue
            for c in child_idx[ni]:
                if c != ni:  # self-loops pad empty child slots
                    assert c > ni
