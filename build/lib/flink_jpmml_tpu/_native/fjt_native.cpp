// fjt_native: host-side data plane for the streaming runtime.
//
// Replaces the per-record Python queue on the hot ingest path (the
// reference's data plane was Flink's Netty stack with credit-based
// backpressure; SURVEY.md §3 row D1). This is a bounded MPSC ring of
// fixed-arity float32 records guarded by a mutex + condvars:
//
//  - producers push single records or contiguous blocks (blocking with
//    backpressure or non-blocking);
//  - the consumer drains fill-or-deadline micro-batches *directly into a
//    caller-provided contiguous buffer* that numpy wraps zero-copy, so no
//    Python object per record ever exists on this path;
//  - close() wakes everyone; drains return what remains.
//
// Build: g++ -O3 -march=native -shared -fPIC -o libfjt_native.so fjt_native.cpp -lpthread
// Bound via ctypes (flink_jpmml_tpu/runtime/native.py) — no pybind11 in the
// image, and the ABI below is deliberately C-plain for that reason.

#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

using namespace std::chrono;

namespace {

struct Ring {
    uint32_t capacity;   // records
    uint32_t arity;      // floats per record
    float*   data;       // capacity * arity floats
    uint64_t* offsets;   // per-record source offset (resume bookkeeping)
    uint32_t head = 0;   // next slot to pop
    uint32_t count = 0;  // records in the ring
    bool     closed = false;
    std::mutex mu;
    std::condition_variable not_full;
    std::condition_variable not_empty;
};

inline uint32_t slot(const Ring* r, uint32_t logical) {
    uint32_t s = r->head + logical;
    if (s >= r->capacity) s -= r->capacity;
    return s;
}

}  // namespace

extern "C" {

Ring* fjt_ring_create(uint32_t capacity, uint32_t arity) {
    if (capacity == 0 || arity == 0) return nullptr;
    Ring* r = new (std::nothrow) Ring();
    if (!r) return nullptr;
    r->capacity = capacity;
    r->arity = arity;
    r->data = new (std::nothrow) float[(size_t)capacity * arity];
    r->offsets = new (std::nothrow) uint64_t[capacity];
    if (!r->data || !r->offsets) {
        delete[] r->data;
        delete[] r->offsets;
        delete r;
        return nullptr;
    }
    return r;
}

void fjt_ring_destroy(Ring* r) {
    if (!r) return;
    delete[] r->data;
    delete[] r->offsets;
    delete r;
}

void fjt_ring_close(Ring* r) {
    std::lock_guard<std::mutex> lk(r->mu);
    r->closed = true;
    r->not_empty.notify_all();
    r->not_full.notify_all();
}

uint32_t fjt_ring_size(Ring* r) {
    std::lock_guard<std::mutex> lk(r->mu);
    return r->count;
}

int fjt_ring_closed(Ring* r) {
    std::lock_guard<std::mutex> lk(r->mu);
    return r->closed ? 1 : 0;
}

// Push a contiguous block of n records (n*arity floats) with consecutive
// source offsets starting at first_offset. Blocks until all records are in
// (backpressure) or timeout_us elapses. Returns the number of records
// pushed; -1 (as UINT32_MAX) never — closed ring returns what fit.
uint32_t fjt_ring_push_block(Ring* r, const float* recs, uint64_t first_offset,
                             uint32_t n, int64_t timeout_us) {
    uint32_t pushed = 0;
    auto deadline = steady_clock::now() + microseconds(timeout_us);
    std::unique_lock<std::mutex> lk(r->mu);
    while (pushed < n) {
        while (r->count == r->capacity && !r->closed) {
            if (timeout_us >= 0) {
                if (r->not_full.wait_until(lk, deadline) == std::cv_status::timeout)
                    return pushed;
            } else {
                r->not_full.wait(lk);
            }
        }
        if (r->closed) return pushed;
        uint32_t room = r->capacity - r->count;
        uint32_t take = n - pushed < room ? n - pushed : room;
        for (uint32_t i = 0; i < take; ++i) {
            uint32_t s = slot(r, r->count + i);
            std::memcpy(r->data + (size_t)s * r->arity,
                        recs + (size_t)(pushed + i) * r->arity,
                        r->arity * sizeof(float));
            r->offsets[s] = first_offset + pushed + i;
        }
        r->count += take;
        pushed += take;
        r->not_empty.notify_one();
    }
    return pushed;
}

// Fill-or-deadline drain into out (max_n*arity floats) + out_offsets
// (max_n u64). Blocks until >=1 record (or closed) — bounded by
// idle_timeout_us when >= 0 (0 records returned on expiry: lets a
// consumer with control-plane work, e.g. the dynamic serving pipeline's
// Add/Del polling, wake up on an idle stream; -1 waits indefinitely).
// Once records flow, keeps taking until max_n or deadline_us after the
// first take. Returns records drained (0 => closed-and-empty or idle
// bound expired).
uint32_t fjt_ring_drain(Ring* r, float* out, uint64_t* out_offsets,
                        uint32_t max_n, int64_t deadline_us,
                        int64_t idle_timeout_us) {
    std::unique_lock<std::mutex> lk(r->mu);
    auto idle_deadline = steady_clock::now() + microseconds(idle_timeout_us);
    while (r->count == 0) {
        if (r->closed) return 0;
        if (idle_timeout_us >= 0) {
            if (r->not_empty.wait_until(lk, idle_deadline) ==
                    std::cv_status::timeout ||
                (r->count == 0 && steady_clock::now() >= idle_deadline))
                if (r->count == 0) return 0;
        } else {
            r->not_empty.wait_for(lk, milliseconds(100));
        }
    }
    uint32_t drained = 0;
    auto deadline = steady_clock::now() + microseconds(deadline_us);
    for (;;) {
        uint32_t take = r->count < max_n - drained ? r->count : max_n - drained;
        for (uint32_t i = 0; i < take; ++i) {
            uint32_t s = slot(r, i);
            std::memcpy(out + (size_t)(drained + i) * r->arity,
                        r->data + (size_t)s * r->arity,
                        r->arity * sizeof(float));
            out_offsets[drained + i] = r->offsets[s];
        }
        r->head = slot(r, take);
        r->count -= take;
        drained += take;
        if (take) r->not_full.notify_all();
        if (drained >= max_n) break;
        if (r->count == 0) {
            if (r->closed) break;
            if (r->not_empty.wait_until(lk, deadline) == std::cv_status::timeout)
                break;
            if (r->count == 0 && r->closed) break;
            if (steady_clock::now() >= deadline) break;
        }
    }
    return drained;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Rank-wire bucketizer (compile/qtrees.py QuantizedWire.encode fast path).
//
// Maps each f32 feature value to its rank among that feature's model split
// cuts — rank = #{c in cuts[j] : c < x} — producing the uint8/uint16 codes
// the quantized TPU kernel compares against. This is host featurization
// (the reference does the analogous prepare/coerce per record in
// JPMML-Evaluator's FieldValue prep; SURVEY.md §4.1), multithreaded so the
// host keeps ahead of the device at >1M records/s.
//
//   X        [n, f] row-major f32
//   cuts     two layouts, one per entry-point family:
//            fjt_bucketize_*      — ragged: concatenated per-feature sorted
//                                   tables + offs[f+1] int32 offsets
//            fjt_bucketize_pow2_* — [f, L] rows, +inf-padded to a shared
//                                   power-of-two length L (no offs)
//   repl     [f] f32 missing-value replacement (used where has_repl)
//   has_repl [f] u8
//   mask     [n, f] u8 missing mask, may be null (NaN always = missing)
//   out      [n, f] codes; sentinel = max value of the code type
// ---------------------------------------------------------------------------

namespace {

// Shared row-range fan-out: clamp thread count (spawn/join costs ~100us a
// thread — keep >=4096 rows each) and run `rows` over [0, n) partitions.
template <typename RowsFn>
void fan_out_rows(uint64_t n, uint32_t n_threads, const RowsFn& rows) {
    if (n_threads == 0) {
        unsigned hw = std::thread::hardware_concurrency();
        n_threads = hw ? hw : 4;
    }
    uint64_t max_useful = (n + 4095) / 4096;
    if (n_threads > max_useful) n_threads = static_cast<uint32_t>(max_useful);
    if (n_threads == 0) n_threads = 1;
    if (n_threads <= 1) {
        rows(uint64_t(0), n);
        return;
    }
    std::vector<std::thread> ts;
    ts.reserve(n_threads);
    uint64_t per = (n + n_threads - 1) / n_threads;
    for (uint32_t t = 0; t < n_threads; ++t) {
        uint64_t b = t * per, e = b + per < n ? b + per : n;
        if (b >= e) break;
        ts.emplace_back(rows, b, e);
    }
    for (auto& t : ts) t.join();
}

template <typename Code>
void bucketize_rows(const float* X, uint64_t row_begin, uint64_t row_end,
                    uint32_t f, const float* cuts, const int32_t* offs,
                    const float* repl, const uint8_t* has_repl,
                    const uint8_t* mask, Code* out) {
    const Code sentinel = static_cast<Code>(~Code(0));
    for (uint64_t i = row_begin; i < row_end; ++i) {
        const float* row = X + i * f;
        const uint8_t* mrow = mask ? mask + i * f : nullptr;
        Code* orow = out + i * f;
        for (uint32_t j = 0; j < f; ++j) {
            float x = row[j];
            bool miss = (x != x) || (mrow && mrow[j]);
            if (miss) {
                if (has_repl[j]) {
                    x = repl[j];
                } else {
                    orow[j] = sentinel;
                    continue;
                }
            }
            // branchless lower_bound: rank = #{c < x}. The `* half` form
            // compiles to cmov — no data-dependent branches, which is worth
            // ~5x on random inputs (every branch would mispredict).
            const float* start = cuts + offs[j];
            const float* lo = start;
            uint32_t len = static_cast<uint32_t>(offs[j + 1] - offs[j]);
            while (len > 1) {
                uint32_t half = len / 2;
                lo += (lo[half - 1] < x) * half;
                len -= half;
            }
            orow[j] = static_cast<Code>((lo - start) + (len && lo[0] < x));
        }
    }
}

template <typename Code>
void bucketize_impl(const float* X, uint64_t n, uint32_t f, const float* cuts,
                    const int32_t* offs, const float* repl,
                    const uint8_t* has_repl, const uint8_t* mask, Code* out,
                    uint32_t n_threads) {
    fan_out_rows(n, n_threads, [&](uint64_t b, uint64_t e) {
        bucketize_rows<Code>(X, b, e, f, cuts, offs, repl, has_repl, mask,
                             out);
    });
}

// Lockstep variant over power-of-two padded tables (cuts[j*L .. j*L+L),
// padded with +inf which never counts toward a rank). The per-feature
// binary searches form f independent load-compare chains; executed
// feature-after-feature each chain's ~log2(L) dependent loads serialize,
// but interleaving them level-by-level keeps ~f independent loads in
// flight per round, which on a single host core (the deployment reality
// behind the tunneled-TPU bench) is worth ~1.3-2x.
template <typename Code>
void bucketize_rows_pow2(const float* X, uint64_t row_begin, uint64_t row_end,
                         uint32_t f, const float* cuts, uint32_t L,
                         const float* repl, const uint8_t* has_repl,
                         const uint8_t* mask, Code* out) {
    const Code sentinel = static_cast<Code>(~Code(0));
    std::vector<uint32_t> pos(f);
    std::vector<float> xv(f);
    std::vector<uint8_t> miss(f);
    for (uint64_t i = row_begin; i < row_end; ++i) {
        const float* row = X + i * f;
        const uint8_t* mrow = mask ? mask + i * f : nullptr;
        Code* orow = out + i * f;
        for (uint32_t j = 0; j < f; ++j) {
            float x = row[j];
            bool m = (x != x) || (mrow && mrow[j]);
            if (m && has_repl[j]) {
                x = repl[j];
                m = false;
            }
            // NaN compares false against every cut, so a missing lane
            // rides the rounds harmlessly and is overwritten at the end
            miss[j] = m;
            xv[j] = x;
            pos[j] = 0;
        }
        for (uint32_t half = L >> 1; half >= 1; half >>= 1) {
            for (uint32_t j = 0; j < f; ++j) {
                const float* t = cuts + static_cast<uint64_t>(j) * L;
                pos[j] += (t[pos[j] + half - 1] < xv[j]) * half;
            }
        }
        for (uint32_t j = 0; j < f; ++j) {
            const float* t = cuts + static_cast<uint64_t>(j) * L;
            uint32_t r = pos[j] + (t[pos[j]] < xv[j]);
            orow[j] = miss[j] ? sentinel : static_cast<Code>(r);
        }
    }
}

template <typename Code>
void bucketize_pow2_impl(const float* X, uint64_t n, uint32_t f,
                         const float* cuts, uint32_t L, const float* repl,
                         const uint8_t* has_repl, const uint8_t* mask,
                         Code* out, uint32_t n_threads) {
    fan_out_rows(n, n_threads, [&](uint64_t b, uint64_t e) {
        bucketize_rows_pow2<Code>(X, b, e, f, cuts, L, repl, has_repl, mask,
                                  out);
    });
}

}  // namespace

extern "C" {

void fjt_bucketize_pow2_u8(const float* X, uint64_t n, uint32_t f,
                           const float* cuts, uint32_t L, const float* repl,
                           const uint8_t* has_repl, const uint8_t* mask,
                           uint8_t* out, uint32_t n_threads) {
    bucketize_pow2_impl<uint8_t>(X, n, f, cuts, L, repl, has_repl, mask, out,
                                 n_threads);
}

void fjt_bucketize_pow2_u16(const float* X, uint64_t n, uint32_t f,
                            const float* cuts, uint32_t L, const float* repl,
                            const uint8_t* has_repl, const uint8_t* mask,
                            uint16_t* out, uint32_t n_threads) {
    bucketize_pow2_impl<uint16_t>(X, n, f, cuts, L, repl, has_repl, mask, out,
                                  n_threads);
}

void fjt_bucketize_u8(const float* X, uint64_t n, uint32_t f,
                      const float* cuts, const int32_t* offs,
                      const float* repl, const uint8_t* has_repl,
                      const uint8_t* mask, uint8_t* out, uint32_t n_threads) {
    bucketize_impl<uint8_t>(X, n, f, cuts, offs, repl, has_repl, mask, out,
                            n_threads);
}

void fjt_bucketize_u16(const float* X, uint64_t n, uint32_t f,
                       const float* cuts, const int32_t* offs,
                       const float* repl, const uint8_t* has_repl,
                       const uint8_t* mask, uint16_t* out,
                       uint32_t n_threads) {
    bucketize_impl<uint16_t>(X, n, f, cuts, offs, repl, has_repl, mask, out,
                             n_threads);
}

}  // extern "C"
