"""C++ sources for the native data plane (built on import, cached)."""
