"""Domain ADTs: predictions, control-stream messages, model identity.

Mirrors the reference's ``…/models/`` package (SURVEY.md §3 rows B4, C2 —
expected upstream ``flink-jpmml-scala/src/main/scala/io/radicalbit/flink/pmml/
scala/models/`` [UNVERIFIED]); re-designed as frozen dataclasses instead of
Scala sealed ADTs.
"""
