"""Control-stream protocol for dynamic model serving (capability C6).

Reference parity: ``ServingMessage`` / ``AddMessage`` / ``DelMessage`` in the
reference's ``…/models/control/`` (SURVEY.md §3 row C2, §4.3 [UNVERIFIED]).
A control stream of these messages is joined with the event stream; the
registry applies them in timestamp order (see
:mod:`flink_jpmml_tpu.serving.managers`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Union

from flink_jpmml_tpu.models.core import ModelId


@dataclass(frozen=True)
class AddMessage:
    """Start serving ``(name, version)`` from the PMML document at ``path``."""

    name: str
    version: int
    path: str
    timestamp: float

    def __post_init__(self) -> None:
        # Validate eagerly so a bad message fails at the producer, not later
        # inside the registry apply step.
        ModelId(self.name, self.version)

    @property
    def model_id(self) -> ModelId:
        return ModelId(self.name, self.version)


@dataclass(frozen=True)
class DelMessage:
    """Stop serving ``(name, version)``."""

    name: str
    version: int
    timestamp: float

    def __post_init__(self) -> None:
        ModelId(self.name, self.version)

    @property
    def model_id(self) -> ModelId:
        return ModelId(self.name, self.version)


ServingMessage = Union[AddMessage, DelMessage]
