"""Prediction ADT: the total (exception-free) scoring output type.

Reference parity: ``Prediction`` / sealed ``Score`` / ``EmptyScore`` in the
reference's ``…/models/prediction.scala`` (SURVEY.md §3 row B4 [UNVERIFIED]).
The reference wraps every evaluation in a ``Try`` and collapses failures into
``Prediction(EmptyScore)`` so dirty data never kills the stream (capability
C5). Here the same totality is achieved *as data*: the compiled JAX model
emits a per-record validity mask alongside scores, and the host-side decode
step materialises invalid lanes as ``EmptyScore``. No exception ever crosses
the device boundary.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Mapping, Optional, Sequence, Union


@dataclass(frozen=True)
class Score:
    """A successful scoring result: a concrete target value."""

    value: float

    def is_empty(self) -> bool:
        return False

    def get_or_else(self, default: float) -> float:
        return self.value


@dataclass(frozen=True)
class EmptyScore:
    """A failed scoring result (invalid input, preparation error, …).

    Singleton-ish by convention: compare with ``is_empty()`` rather than
    identity.
    """

    def is_empty(self) -> bool:
        return True

    def get_or_else(self, default: float) -> float:
        return default


ScoreLike = Union[Score, EmptyScore]


@dataclass(frozen=True)
class Target:
    """Decoded target for classification-style models.

    ``label`` is the predicted category (as a string, matching PMML
    DataDictionary values); ``probabilities`` optionally maps every class
    label to its probability.
    """

    label: Optional[str] = None
    probabilities: Mapping[str, float] = field(default_factory=dict)


@dataclass(frozen=True)
class Prediction:
    """The unit of output of every evaluation.

    ``score`` is total: either a :class:`Score` or :class:`EmptyScore`.
    ``target`` carries the decoded class label / per-class probabilities for
    classification models (``None`` for pure regression / clustering outputs
    where ``score`` already says everything). ``outputs`` carries the
    document's top-level <Output> field values when it declares any
    (pmml/outputs.py), ``None`` otherwise.
    """

    score: ScoreLike
    target: Optional[Target] = None
    outputs: Optional[Mapping[str, Any]] = None

    @property
    def is_empty(self) -> bool:
        return self.score.is_empty()

    @staticmethod
    def empty() -> "Prediction":
        return Prediction(score=EmptyScore())

    @staticmethod
    def of(value: float) -> "Prediction":
        """Lift a raw value; NaN collapses to :class:`EmptyScore` (totality)."""
        if value is None or _is_nan(value):
            return Prediction.empty()
        return Prediction(score=Score(float(value)))


def _is_nan(v: Any) -> bool:
    # math.isnan accepts any real number (incl. numpy scalars off the device);
    # non-numeric values are not NaN.
    try:
        return math.isnan(v)
    except TypeError:
        return False


def decode_batch(
    values: Sequence[float],
    valid: Sequence[bool],
    labels: Optional[Sequence[Optional[str]]] = None,
    probabilities: Optional[Sequence[Mapping[str, float]]] = None,
) -> list[Prediction]:
    """Materialise device output lanes into :class:`Prediction` objects.

    ``values``/``valid`` come straight off the device (host-transferred);
    invalid lanes become ``Prediction(EmptyScore)`` — the masked-lane
    equivalent of the reference's ``Try``→``EmptyScore`` collapse.
    """
    n = len(values)
    if len(valid) != n:
        raise ValueError(f"values/valid length mismatch: {n} vs {len(valid)}")
    for opt, tag in ((labels, "labels"), (probabilities, "probabilities")):
        if opt is not None and len(opt) != n:
            raise ValueError(f"{tag} length mismatch: {n} vs {len(opt)}")
    out: list[Prediction] = []
    for i in range(n):
        v, ok = values[i], valid[i]
        if not ok or _is_nan(v):
            out.append(Prediction.empty())
            continue
        target: Optional[Target] = None
        if labels is not None and labels[i] is not None:
            probs = probabilities[i] if probabilities is not None else None
            target = Target(
                label=labels[i],
                probabilities=dict(probs) if probs else {},
            )
        out.append(Prediction(score=Score(float(v)), target=target))
    return out
