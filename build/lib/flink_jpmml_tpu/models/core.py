"""Model identity: name + version, and served-model metadata.

Reference parity: ``ModelId`` / ``ModelInfo`` in the reference's
``…/models/core/`` (SURVEY.md §3 row C2 [UNVERIFIED]). ``ModelId`` is the key
of the dynamic-serving registry; ``ModelInfo`` records where the model's PMML
lives (the *path*, never the document itself — capability C2: only paths
travel through the system).
"""

from __future__ import annotations

from dataclasses import dataclass

_SEP = "_"


@dataclass(frozen=True, order=True)
class ModelId:
    name: str
    version: int

    def __post_init__(self) -> None:
        if not self.name or _SEP in self.name:
            raise ValueError(
                f"model name must be non-empty and must not contain {_SEP!r}: "
                f"{self.name!r}"
            )
        if self.version < 0:
            raise ValueError(f"model version must be >= 0: {self.version}")

    def key(self) -> str:
        return f"{self.name}{_SEP}{self.version}"

    @staticmethod
    def from_key(key: str) -> "ModelId":
        name, _, version = key.rpartition(_SEP)
        return ModelId(name=name, version=int(version))


@dataclass(frozen=True)
class ModelInfo:
    """Registry value: the filesystem path of a served model's PMML document."""

    path: str
