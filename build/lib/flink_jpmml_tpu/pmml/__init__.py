"""PMML 4.x ingestion: XML parsing, typed IR, reference interpreter.

This package replaces the reference's EXT-B substrate (``jpmml-model`` JAXB
tree + part of JPMML-Evaluator; SURVEY.md §2 layer EXT-B) with an in-tree
parser producing a typed IR that the :mod:`flink_jpmml_tpu.compile` package
lowers to JAX. The :mod:`flink_jpmml_tpu.pmml.interp` module is a slow,
per-record reference interpreter used as the semantic oracle in golden tests
(standing in for JPMML-Evaluator, which is JVM-only).
"""

from flink_jpmml_tpu.pmml.parser import parse_pmml, parse_pmml_file  # noqa: F401
from flink_jpmml_tpu.pmml.ir import PmmlDocument  # noqa: F401
