"""Expression lowering: PMML DerivedField expressions → (value, missing) lanes.

Used by NeuralNetwork inputs and (later) TransformationDictionary-derived
features. Mirrors :func:`flink_jpmml_tpu.pmml.interp.eval_expression`
semantics: every expression yields a value lane f32[B] plus a missing lane
bool[B]; ``mapMissingTo`` substitutes a constant where the input is missing.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax.numpy as jnp
import numpy as np

from flink_jpmml_tpu.compile.common import LowerCtx
from flink_jpmml_tpu.pmml import ir
from flink_jpmml_tpu.utils.exceptions import ModelCompilationException

ExprFn = Callable[[jnp.ndarray, jnp.ndarray], Tuple[jnp.ndarray, jnp.ndarray]]


def lower_expression(expr: ir.Expression, ctx: LowerCtx) -> ExprFn:
    if isinstance(expr, ir.Constant):
        v = np.float32(expr.value)

        def cfn(X, M):
            B = X.shape[0]
            return jnp.full((B,), v), jnp.zeros((B,), bool)

        return cfn

    if isinstance(expr, ir.FieldRef):
        col = ctx.column(expr.field)

        def ffn(X, M):
            return X[:, col], M[:, col]

        return ffn

    if isinstance(expr, ir.NormContinuous):
        col = ctx.column(expr.field)
        origs = np.asarray([n.orig for n in expr.norms], np.float32)
        norms = np.asarray([n.norm for n in expr.norms], np.float32)
        outliers = expr.outliers
        mm = expr.map_missing_to

        def nfn(X, M):
            x = X[:, col]
            miss = M[:, col]
            # asIs extrapolates; asExtremeValues/asMissingValues clamp (the
            # latter then masks out-of-range lanes as missing)
            y = _piecewise(x, origs, norms, extrapolate=(outliers == "asIs"))
            if outliers == "asMissingValues":
                miss = miss | (x < origs[0]) | (x > origs[-1])
            return _with_map_missing(y, miss, mm)

        return nfn

    if isinstance(expr, ir.NormDiscrete):
        col = ctx.column(expr.field)
        code = np.float32(ctx.encode(expr.field, expr.value))
        mm = expr.map_missing_to

        def dfn(X, M):
            ind = (X[:, col] == code).astype(jnp.float32)
            return _with_map_missing(ind, M[:, col], mm)

        return dfn

    if isinstance(expr, ir.Apply):
        arg_fns = [lower_expression(a, ctx) for a in expr.args]
        fn_name = expr.function
        mm = expr.map_missing_to

        def afn(X, M):
            vals, misses = zip(*(f(X, M) for f in arg_fns))
            miss = jnp.zeros_like(misses[0]) if not misses else misses[0]
            for m2 in misses[1:]:
                miss = miss | m2
            y, extra_missing = _apply(fn_name, vals)
            return _with_map_missing(y, miss | extra_missing, mm)

        return afn

    raise ModelCompilationException(
        f"unsupported expression {type(expr).__name__}"
    )


def _with_map_missing(y, miss, map_missing_to):
    if map_missing_to is not None:
        y = jnp.where(miss, jnp.float32(map_missing_to), y)
        miss = jnp.zeros_like(miss)
    return y, miss


def _piecewise(x, origs, norms, extrapolate: bool):
    """Piecewise-linear map through (origs → norms) control points.

    ``extrapolate=True`` extends the outermost segments (PMML outliers=asIs);
    otherwise values clamp to the boundary norms (asExtremeValues).
    """
    if len(origs) == 2 and extrapolate:
        slope = (norms[1] - norms[0]) / (origs[1] - origs[0])
        return norms[0] + (x - origs[0]) * slope
    y = jnp.interp(x, origs, norms)  # clamps outside the range
    if extrapolate:
        lo_slope = (norms[1] - norms[0]) / (origs[1] - origs[0])
        hi_slope = (norms[-1] - norms[-2]) / (origs[-1] - origs[-2])
        y = jnp.where(x < origs[0], norms[0] + (x - origs[0]) * lo_slope, y)
        y = jnp.where(x > origs[-1], norms[-1] + (x - origs[-1]) * hi_slope, y)
    return y


def _apply(fn: str, vals):
    """→ (value, extra_missing) for the supported built-in functions."""
    zero_false = jnp.zeros_like(vals[0], dtype=bool)
    if fn == "+":
        return vals[0] + vals[1], zero_false
    if fn == "-":
        return vals[0] - vals[1], zero_false
    if fn == "*":
        return vals[0] * vals[1], zero_false
    if fn == "/":
        return jnp.where(vals[1] == 0, 0.0, vals[0] / vals[1]), vals[1] == 0
    if fn == "min":
        return jnp.min(jnp.stack(vals), axis=0), zero_false
    if fn == "max":
        return jnp.max(jnp.stack(vals), axis=0), zero_false
    if fn == "pow":
        return vals[0] ** vals[1], zero_false
    if fn == "exp":
        return jnp.exp(vals[0]), zero_false
    if fn == "ln":
        return jnp.where(vals[0] > 0, jnp.log(jnp.maximum(vals[0], 1e-38)), 0.0), \
            vals[0] <= 0
    if fn == "sqrt":
        return jnp.sqrt(jnp.maximum(vals[0], 0.0)), vals[0] < 0
    if fn == "abs":
        return jnp.abs(vals[0]), zero_false
    if fn == "floor":
        return jnp.floor(vals[0]), zero_false
    if fn == "ceil":
        return jnp.ceil(vals[0]), zero_false
    if fn == "threshold":
        return (vals[0] > vals[1]).astype(jnp.float32), zero_false
    if fn == "if":
        cond = vals[0] != 0.0
        if len(vals) > 2:
            return jnp.where(cond, vals[1], vals[2]), zero_false
        return jnp.where(cond, vals[1], 0.0), ~cond
    raise ModelCompilationException(f"unsupported Apply function {fn!r}")
