"""NeuralNetwork → JAX: a dense matmul chain (the MXU path, BASELINE config 3).

PMML expresses networks as per-neuron ``<Con>`` lists; we reassemble them
into layer weight matrices ``W[in, out]`` + bias ``b[out]`` so the whole
layer is one matmul. Connections must be strictly layered (every ``Con``
references the immediately previous layer) — the shape every mainstream MLP
exporter emits; skip connections raise at compile time.

Missing semantics (matching the oracle): any missing network input makes the
whole record's result missing.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from flink_jpmml_tpu.compile.common import HIGHEST, Lowered, LowerCtx, ModelOutput
from flink_jpmml_tpu.compile.exprs import lower_expression
from flink_jpmml_tpu.compile.regression import softmax
from flink_jpmml_tpu.pmml import ir
from flink_jpmml_tpu.utils.exceptions import ModelCompilationException

_ACTIVATIONS = {
    "logistic": lambda z: 1.0 / (1.0 + jnp.exp(-z)),
    "tanh": jnp.tanh,
    "identity": lambda z: z,
    "rectifier": lambda z: jnp.maximum(z, 0.0),
    # PMML 4.x defines arctan as 2*arctan(Z)/pi (range (-1, 1))
    "arctan": lambda z: 2.0 * jnp.arctan(z) / jnp.pi,
    "cosine": jnp.cos,
    "sine": jnp.sin,
    "square": lambda z: z * z,
    "Gauss": lambda z: jnp.exp(-(z * z)),
    "reciprocal": lambda z: 1.0 / z,
    "exponential": jnp.exp,
    "Elliott": lambda z: z / (1.0 + jnp.abs(z)),
    "elliott": lambda z: z / (1.0 + jnp.abs(z)),  # lenient-case alias
}


def lower_neural_network(model: ir.NeuralNetworkIR, ctx: LowerCtx) -> Lowered:
    input_fns = [lower_expression(ni.derived_field.expression, ctx)
                 for ni in model.inputs]
    prev_ids = [ni.neuron_id for ni in model.inputs]

    layer_weights = []
    layer_acts = []
    layer_norms = []
    all_ids_per_layer = []
    for li, layer in enumerate(model.layers):
        index = {nid: i for i, nid in enumerate(prev_ids)}
        W = np.zeros((len(prev_ids), len(layer.neurons)), np.float32)
        b = np.zeros((len(layer.neurons),), np.float32)
        for j, neuron in enumerate(layer.neurons):
            b[j] = neuron.bias
            for src, w in neuron.weights:
                if src not in index:
                    raise ModelCompilationException(
                        f"neuron {neuron.neuron_id!r} in layer {li} references "
                        f"{src!r} which is not in the previous layer — only "
                        "strictly layered networks lower to the matmul chain"
                    )
                W[index[src], j] = w
        act_name = layer.activation or model.activation_function
        act_spec: dict = {"kind": "plain", "name": act_name}
        if act_name == "threshold":
            # out = 1 if z > threshold else 0 (cut from layer, else model)
            thr = (
                layer.threshold
                if layer.threshold is not None
                else model.threshold
            )
            act_spec = {"kind": "threshold", "thr": float(thr)}
        elif act_name == "radialBasis":
            # RBF neuron: the Con weights are the center; per the spec
            #   z_j = Σ_i (w_ij − x_i)²
            #   out = exp(fanIn_j · ln(altitude_j) − z_j / (2·width_j²))
            # width resolves Neuron → Layer → Network (required), altitude
            # likewise (default 1.0); bias is unused.
            widths = np.zeros((len(layer.neurons),), np.float32)
            alts = np.zeros((len(layer.neurons),), np.float32)
            fanin = np.zeros((len(layer.neurons),), np.float32)
            conn = np.zeros((len(prev_ids), len(layer.neurons)), np.float32)
            index2 = {nid: i for i, nid in enumerate(prev_ids)}
            for j, neuron in enumerate(layer.neurons):
                w = (
                    neuron.width
                    if neuron.width is not None
                    else (
                        layer.width
                        if layer.width is not None
                        else model.width
                    )
                )
                if w is None or w <= 0:
                    raise ModelCompilationException(
                        f"radialBasis neuron {neuron.neuron_id!r} has no "
                        "positive width (Neuron/NeuralLayer/NeuralNetwork)"
                    )
                widths[j] = w
                a = (
                    neuron.altitude
                    if neuron.altitude is not None
                    else (
                        layer.altitude
                        if layer.altitude is not None
                        else model.altitude
                    )
                )
                if a <= 0:
                    raise ModelCompilationException(
                        f"radialBasis neuron {neuron.neuron_id!r} has "
                        f"non-positive altitude {a}"
                    )
                alts[j] = a
                fanin[j] = len(neuron.weights)
                for src, _w in neuron.weights:
                    conn[index2[src], j] = 1.0
            act_spec = {
                "kind": "rbf",
                "widths": widths,
                "log_alt": np.log(alts).astype(np.float32),
                "fanin": fanin,
                "conn": conn,
            }
        elif act_name not in _ACTIVATIONS:
            raise ModelCompilationException(
                f"unsupported activation {act_name!r}"
            )
        is_last = li == len(model.layers) - 1
        norm = layer.normalization or (
            model.normalization_method if is_last else "none"
        )
        if norm not in ("none", "softmax", "simplemax"):
            raise ModelCompilationException(
                f"unsupported layer normalization {norm!r}"
            )
        layer_weights.append((W, b))
        layer_acts.append(act_spec)
        layer_norms.append(norm)
        prev_ids = [n.neuron_id for n in layer.neurons]
        all_ids_per_layer.append(prev_ids)

    out_index = {nid: i for i, nid in enumerate(prev_ids)}
    params = {
        f"l{i}": {"W": W, "b": b} for i, (W, b) in enumerate(layer_weights)
    }

    def run_network(p, X, M) -> Tuple[jnp.ndarray, jnp.ndarray]:
        vals, misses = zip(*(f(X, M) for f in input_fns))
        h = jnp.stack(vals, axis=1)  # [B, I]
        missing = misses[0]
        for m2 in misses[1:]:
            missing = missing | m2
        for i, spec in enumerate(layer_acts):
            lp = p[f"l{i}"]
            if spec["kind"] == "rbf":
                # z_j = Σ_i conn_ij (w_ij − h_i)², expanded so the MXU
                # carries it: colsum(conn·W²) − 2 h@(conn·W) + h²@conn
                W_, conn = lp["W"], spec["conn"]
                cw = conn * W_
                z = (
                    jnp.sum(cw * W_, axis=0)[None, :]
                    - 2.0 * jnp.dot(h, cw, precision=HIGHEST)
                    + jnp.dot(h * h, conn, precision=HIGHEST)
                )
                h = jnp.exp(
                    spec["fanin"] * spec["log_alt"]
                    - z / (2.0 * spec["widths"] * spec["widths"])
                )
            else:
                z = jnp.dot(h, lp["W"], precision=HIGHEST) + lp["b"]
                if spec["kind"] == "threshold":
                    h = (z > spec["thr"]).astype(jnp.float32)
                else:
                    h = _ACTIVATIONS[spec["name"]](z)
            if layer_norms[i] == "softmax":
                h = softmax(h)
            elif layer_norms[i] == "simplemax":
                s = jnp.sum(h, axis=1, keepdims=True)
                h = jnp.where(s == 0, h, h / s)
        return h, missing

    if model.function_name == "classification":
        labels = []
        out_cols = []
        for no in model.outputs:
            expr = no.derived_field.expression
            if not isinstance(expr, ir.NormDiscrete):
                raise ModelCompilationException(
                    "classification NeuralOutput must map via NormDiscrete"
                )
            labels.append(expr.value)
            if no.output_neuron not in out_index:
                raise ModelCompilationException(
                    f"NeuralOutput references unknown neuron "
                    f"{no.output_neuron!r}"
                )
            out_cols.append(out_index[no.output_neuron])
        out_cols = np.asarray(out_cols, np.int32)

        def cfn(p, X, M):
            h, missing = run_network(p, X, M)
            probs = h[:, out_cols]
            label_idx = jnp.argmax(probs, axis=1).astype(jnp.int32)
            value = jnp.take_along_axis(probs, label_idx[:, None], axis=1)[:, 0]
            return ModelOutput(
                value=value, valid=~missing, probs=probs, label_idx=label_idx
            )

        return Lowered(fn=cfn, params=params, labels=tuple(labels))

    if not model.outputs:
        raise ModelCompilationException("regression NeuralNetwork has no outputs")
    no = model.outputs[0]
    if no.output_neuron not in out_index:
        raise ModelCompilationException(
            f"NeuralOutput references unknown neuron {no.output_neuron!r}"
        )
    out_col = out_index[no.output_neuron]
    expr = no.derived_field.expression
    if isinstance(expr, ir.NormContinuous):
        if len(expr.norms) != 2:
            raise ModelCompilationException(
                "regression NeuralOutput NormContinuous supports exactly two "
                "LinearNorm points in the lowering (n-point: oracle only)"
            )
        a, b2 = expr.norms
        denorm_slope = np.float32((b2.orig - a.orig) / (b2.norm - a.norm))
        denorm = (np.float32(a.orig), np.float32(a.norm), denorm_slope)
    elif isinstance(expr, ir.FieldRef):
        denorm = None
    else:
        raise ModelCompilationException(
            f"unsupported NeuralOutput expression {type(expr).__name__}"
        )

    def rfn(p, X, M):
        h, missing = run_network(p, X, M)
        y = h[:, out_col]
        if denorm is not None:
            orig0, norm0, slope = denorm
            y = orig0 + (y - norm0) * slope
        return ModelOutput(value=y, valid=~missing)

    return Lowered(fn=rfn, params=params)
