"""BaselineModel → JAX: per-record z-value against a parametric baseline.

Reference parity: JPMML-Evaluator scores BaselineModel documents
(SURVEY.md §1 C1). The ``zValue`` test statistic is stateless per record:

    z = (x − μ₀) / σ₀

with (μ₀, σ₀²) from the declared baseline distribution — Gaussian
(mean, variance), Poisson (σ₀² = μ₀), or Uniform (μ₀ = (l+u)/2,
σ₀² = (u−l)²/12). Windowed statistics (CUSUM, chi-square families) are
multi-record and rejected at parse time (pmml/parser.py), keeping the
per-record streaming contract honest. A missing test field scores as an
empty lane.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from flink_jpmml_tpu.compile.common import Lowered, LowerCtx, ModelOutput
from flink_jpmml_tpu.pmml import ir


def lower_baseline(model: ir.BaselineIR, ctx: LowerCtx) -> Lowered:
    col = ctx.column(model.field)
    mean = float(model.baseline.mean)
    inv_sd = 1.0 / math.sqrt(model.baseline.variance)
    params = {
        "mean": np.float32(mean),
        "inv_sd": np.float32(inv_sd),
    }

    def fn(p, X, M):
        x = X[:, col]
        return ModelOutput(
            value=((x - p["mean"]) * p["inv_sd"]).astype(jnp.float32),
            valid=~M[:, col],
        )

    return Lowered(fn=fn, params=params)
