"""BayesianNetworkModel (discrete) → JAX: CPT-row matmuls in log space.

Reference parity: PMML 4.3 declares BayesianNetworkModel (SURVEY.md §1
C1 model-class coverage). Under the streaming contract every non-target
node is an observed active field (enforced at parse), so the target
posterior is closed form over its Markov blanket:

    P(t = s | e) ∝ P(t = s | pa(t)) · Π_{c : t ∈ pa(c)} P(c_obs | pa(c), t = s)

Lowering: each factor becomes a CPT-row *match matmul*. For a factor
with rows r over observed parent configs, ``A[B, r] = Π_j [x_{p_j} =
config_{r,j}]`` is a product of equality indicators; the log-probability
contribution is ``(A * logP) @ onehot(rows → target states)`` — three
small einsums per factor, no gathers over dynamic shapes. Lanes where
any observation is missing/unknown, or where the matched rows don't
uniquely cover every state, come out invalid (C5).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from flink_jpmml_tpu.compile.common import (
    HIGHEST,
    Lowered,
    LowerCtx,
    ModelOutput,
)
from flink_jpmml_tpu.pmml import ir
from flink_jpmml_tpu.utils.exceptions import ModelCompilationException

_TINY = 1e-30  # log(0) guard: exp(log(_TINY)) underflows to ~0 after norm


def lower_bayesian_network(
    model: ir.BayesianNetworkIR, ctx: LowerCtx
) -> Lowered:
    by_name = {n.name: n for n in model.nodes}
    tnode = by_name[model.target]
    S = len(tnode.values)
    tpos = {v: i for i, v in enumerate(tnode.values)}

    def code(field: str, value: str) -> float:
        return ctx.encode(field, value)

    params: dict = {}
    factors = []  # (kind, names) closures assembled below

    # -- target's own CPT ---------------------------------------------------
    R = len(tnode.cpt)
    t_cols = np.asarray(
        [ctx.column(p) for p in tnode.parents], np.int32
    )
    t_cfg = np.zeros((R, max(len(tnode.parents), 1)), np.float32)
    t_logp = np.zeros((R, S), np.float32)
    t_pos = np.zeros((R, S), np.float32)
    for r, (config, probs) in enumerate(tnode.cpt):
        for j, v in enumerate(config):
            t_cfg[r, j] = code(tnode.parents[j], v)
        t_logp[r] = np.log(np.maximum(np.asarray(probs), _TINY))
        t_pos[r] = (np.asarray(probs) > 0).astype(np.float32)
    params["t_cfg"] = t_cfg
    params["t_logp"] = t_logp
    # exact positivity alongside the clamped logs: a state whose TRUE
    # probability is zero must decode to exactly 0 (and all-zero lanes
    # to invalid), matching the oracle — the log(_TINY) clamp alone
    # cancels in the softmax and would fake a posterior
    params["t_pos"] = t_pos

    # -- children of the target --------------------------------------------
    children = []
    for child in model.nodes:
        if child.name == model.target or model.target not in child.parents:
            continue
        ti = child.parents.index(model.target)
        other = [p for j, p in enumerate(child.parents) if j != ti]
        Rc = len(child.cpt)
        cfg = np.zeros((Rc, max(len(other), 1)), np.float32)
        onehot = np.zeros((Rc, S), np.float32)
        logp = np.zeros((Rc, len(child.values)), np.float32)
        for r, (config, probs) in enumerate(child.cpt):
            tv = config[ti]
            if tv not in tpos:
                raise ModelCompilationException(
                    f"DiscreteNode {child.name!r}: ParentValue {tv!r} is "
                    f"not a state of target {model.target!r}"
                )
            onehot[r, tpos[tv]] = 1.0
            k = 0
            for j, v in enumerate(config):
                if j == ti:
                    continue
                cfg[r, k] = code(child.parents[j], v)
                k += 1
            logp[r] = np.log(np.maximum(np.asarray(probs), _TINY))
        key = f"c{len(children)}"
        params[f"{key}_cfg"] = cfg
        params[f"{key}_onehot"] = onehot
        params[f"{key}_logp"] = logp
        params[f"{key}_pos"] = np.asarray(
            [[pr > 0 for pr in probs] for _, probs in child.cpt], np.float32
        )
        params[f"{key}_vcodes"] = np.asarray(
            [code(child.name, v) for v in child.values], np.float32
        )
        children.append((
            key,
            ctx.column(child.name),
            np.asarray([ctx.column(p) for p in other], np.int32),
        ))

    labels = tnode.values

    def row_match(p_cfg, X, M, cols):
        """[B, R] product of per-parent equality indicators (1 when the
        factor has no observed parents)."""
        if cols.shape[0] == 0:
            return jnp.ones((X.shape[0], p_cfg.shape[0]), jnp.float32)
        xv = X[:, cols]  # [B, P]
        ok = ~M[:, cols]
        eq = (xv[:, None, :] == p_cfg[None, :, : cols.shape[0]]) & ok[
            :, None, :
        ]
        return jnp.all(eq, axis=-1).astype(jnp.float32)

    def fn(p, X, M):
        B = X.shape[0]
        A_t = row_match(p["t_cfg"], X, M, t_cols)  # [B, R]
        valid = jnp.sum(A_t, axis=1) == 1.0
        logp = jnp.matmul(A_t, p["t_logp"], precision=HIGHEST)  # [B, S]
        pos = jnp.matmul(A_t, p["t_pos"], precision=HIGHEST)  # [B, S]
        for key, ccol, ocols in children:
            A = row_match(p[f"{key}_cfg"], X, M, ocols)  # [B, Rc]
            # exactly one matching row per target state
            cover = jnp.matmul(
                A, p[f"{key}_onehot"], precision=HIGHEST
            )  # [B, S]
            valid = valid & jnp.all(cover == 1.0, axis=1)
            # observed child value → per-row log prob
            vcodes = p[f"{key}_vcodes"]
            hit = (X[:, ccol][:, None] == vcodes[None, :]) & ~M[
                :, ccol
            ][:, None]
            valid = valid & jnp.any(hit, axis=1)
            obs = jnp.argmax(hit, axis=1)  # [B]
            lp_rows = p[f"{key}_logp"][:, :]  # [Rc, V]
            lp_obs = jnp.take(lp_rows.T, obs, axis=0)  # [B, Rc]
            logp = logp + jnp.matmul(
                A * lp_obs, p[f"{key}_onehot"], precision=HIGHEST
            )
            pos_obs = jnp.take(p[f"{key}_pos"].T, obs, axis=0)  # [B, Rc]
            pos = pos * jnp.matmul(
                A * pos_obs, p[f"{key}_onehot"], precision=HIGHEST
            )
        m = jnp.max(logp, axis=1, keepdims=True)
        # exact zeros where any factor's true probability was zero — the
        # clamped logs would otherwise cancel in the softmax and fake a
        # posterior for impossible evidence
        e = jnp.exp(logp - m) * pos
        total = jnp.sum(e, axis=1, keepdims=True)
        probs = e / jnp.maximum(total, _TINY)
        valid = valid & (total[:, 0] > 0)
        lab = jnp.argmax(probs, axis=1).astype(jnp.int32)
        value = jnp.take_along_axis(probs, lab[:, None], axis=1)[:, 0]
        return ModelOutput(
            value=value.astype(jnp.float32),
            valid=valid,
            probs=probs.astype(jnp.float32),
            label_idx=lab,
        )

    return Lowered(fn=fn, params=params, labels=labels)
