"""Scorecard → JAX: vectorized first-true attribute scan per characteristic.

Reference parity: the reference scores any JPMML-supported model class
(SURVEY.md §1 C1 "build an evaluator for whatever model class the
document contains"); scorecards are JPMML's bread-and-butter credit-risk
format. Semantics: score = initialScore + Σ over Characteristics of the
partialScore of the first Attribute whose predicate is TRUE (UNKNOWN
doesn't match — scorecard documents bin missing values with explicit
isMissing attributes); a characteristic with no matching attribute makes
the record's result invalid (empty lane, totality C5).

Lowering: every attribute predicate flattens through the general
predicate tables of gtrees.py (Simple/SimpleSet/True/False, single-level
or DNF-expanded nested compounds) into ``[C, A, K]`` arrays; one
evaluation produces the ``[B, C, A]`` truth cube, the first-true scan is
an argmax, and the per-characteristic chosen partials land in
``ModelOutput.probs[:, :C]`` with the chosen attribute index in
``probs[:, C:]`` — the decode side derives ranked reason codes from them
(pointsBelow/pointsAbove) without a second device readback.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from flink_jpmml_tpu.compile.common import Lowered, LowerCtx, ModelOutput
from flink_jpmml_tpu.compile.gtrees import (
    _C_OR,
    _combine,
    _flatten_predicate,
    _P_FALSE,
    _sub_pred_eval,
)
from flink_jpmml_tpu.pmml import ir
from flink_jpmml_tpu.utils.exceptions import ModelCompilationException


class ReasonCodeMeta:
    """Static reason-code data the decode step needs: per-(c, a) codes,
    per-characteristic baselines, and the ranking algorithm."""

    def __init__(self, model: ir.ScorecardIR):
        self.algorithm = model.reason_code_algorithm
        if self.algorithm not in ("pointsBelow", "pointsAbove"):
            raise ModelCompilationException(
                f"unsupported reasonCodeAlgorithm {self.algorithm!r}"
            )
        self.codes = []  # [C][A] strings
        self.baselines = np.zeros((len(model.characteristics),), np.float32)
        for ci, ch in enumerate(model.characteristics):
            bs = (
                ch.baseline_score
                if ch.baseline_score is not None
                else model.baseline_score
            )
            if bs is None:
                raise ModelCompilationException(
                    f"useReasonCodes: characteristic {ch.name!r} has no "
                    "baselineScore (and the Scorecard declares none)"
                )
            self.baselines[ci] = bs
            row = []
            for at in ch.attributes:
                code = at.reason_code or ch.reason_code
                if code is None:
                    raise ModelCompilationException(
                        f"useReasonCodes: characteristic {ch.name!r} has "
                        "an attribute with no reasonCode (attribute or "
                        "characteristic level)"
                    )
                row.append(code)
            self.codes.append(row)

    def rank(self, partials: np.ndarray, attr_idx: np.ndarray) -> list:
        """One record's ([C] partials, [C] chosen attribute) → reason
        codes ranked worst-first per the algorithm (ties: document
        order, np.argsort stable)."""
        diff = (
            self.baselines - partials
            if self.algorithm == "pointsBelow"
            else partials - self.baselines
        )
        order = np.argsort(-diff, kind="stable")
        return [
            self.codes[c][int(attr_idx[c])] for c in order
        ]


def lower_scorecard(model: ir.ScorecardIR, ctx: LowerCtx) -> Lowered:
    C = len(model.characteristics)
    A = max(len(ch.attributes) for ch in model.characteristics)
    flat = [
        [_flatten_predicate(at.predicate, ctx) for at in ch.attributes]
        for ch in model.characteristics
    ]
    K = max(len(subs) for row in flat for _, subs in row)
    KS = max(
        (len(s[3]) for row in flat for _, subs in row for s in subs),
        default=0,
    )

    pcol = np.zeros((C, A, K), np.int32)
    pop = np.full((C, A, K), float(_P_FALSE), np.float32)
    pval = np.zeros((C, A, K), np.float32)
    pact = np.zeros((C, A, K), np.float32)
    pneg = np.zeros((C, A, K), np.float32)
    pterm = np.zeros((C, A, K), np.float32)
    # padded attribute slots (characteristics with fewer than A
    # attributes) must evaluate FALSE: an empty AND is vacuously TRUE in
    # the three-valued combiner, an empty OR is FALSE — pad with OR
    # (same convention as gtrees.pack_general)
    pcomb = np.full((C, A), float(_C_OR), np.float32)
    psets = np.full((C, A, K, KS), np.nan, np.float32) if KS else None
    partial = np.zeros((C, A), np.float32)

    # ComplexPartialScore slots: (ci, ai, lowered expression) — their
    # per-record values overwrite the static partial plane in fn
    expr_slots = []
    for ci, ch in enumerate(model.characteristics):
        for ai, at in enumerate(ch.attributes):
            comb, subs = flat[ci][ai]
            pcomb[ci, ai] = comb
            partial[ci, ai] = at.partial_score
            if at.partial_expr is not None:
                from flink_jpmml_tpu.compile.exprs import lower_expression

                expr_slots.append(
                    (ci, ai, lower_expression(at.partial_expr, ctx))
                )
            for k, (c_, o_, v_, s_, n_, t_) in enumerate(subs):
                pcol[ci, ai, k] = c_
                pop[ci, ai, k] = o_
                pval[ci, ai, k] = v_
                pact[ci, ai, k] = 1.0
                pneg[ci, ai, k] = 1.0 if n_ else 0.0
                pterm[ci, ai, k] = t_
                if s_ and psets is not None:
                    psets[ci, ai, k, : len(s_)] = s_

    params = {
        "pcol": pcol, "pop": pop, "pval": pval, "pact": pact,
        "pneg": pneg, "pterm": pterm, "pcomb": pcomb,
        "partial": partial,
    }
    if psets is not None:
        params["psets"] = psets
    init = float(model.initial_score)

    def fn(p, X, M):
        B = X.shape[0]
        cols = p["pcol"].reshape(-1)  # [C*A*K]
        x = jnp.take(X, cols, axis=1).reshape(B, C, A, K)
        m = jnp.take(M, cols, axis=1).reshape(B, C, A, K)
        member = None
        if "psets" in p:
            member = jnp.any(x[..., None] == p["psets"][None], axis=-1)
        isT, isU = _sub_pred_eval(
            x, m, p["pop"][None], p["pval"][None], member, p["pneg"][None]
        )
        attrT, _attrU = _combine(
            p["pcomb"][None], isT, isU, p["pact"][None], p["pterm"][None]
        )  # [B, C, A]; UNKNOWN attributes simply don't match
        matched = jnp.any(attrT, axis=-1)  # [B, C]
        first = jnp.argmax(attrT, axis=-1)  # first True (argmax on bools)
        partial_dyn = jnp.broadcast_to(p["partial"][None], (B, C, A))
        expr_bad = None  # [B, C, A] chosen-slot poison for failed exprs
        if expr_slots:
            expr_bad = jnp.zeros((B, C, A), bool)
            for ci, ai, efn in expr_slots:
                v, miss = efn(X, M)
                partial_dyn = partial_dyn.at[:, ci, ai].set(
                    jnp.where(miss, 0.0, v.astype(jnp.float32))
                )
                expr_bad = expr_bad.at[:, ci, ai].set(miss)
        chosen = jnp.take_along_axis(
            partial_dyn, first[..., None], axis=-1
        )[..., 0]  # [B, C]
        value = init + jnp.sum(chosen, axis=-1)
        valid = jnp.all(matched, axis=-1)
        if expr_bad is not None:
            # a chosen attribute whose ComplexPartialScore failed to
            # compute empties the lane (oracle parity)
            chosen_bad = jnp.take_along_axis(
                expr_bad, first[..., None], axis=-1
            )[..., 0]
            valid = valid & ~jnp.any(chosen_bad, axis=-1)
        # decode-side payload: per-characteristic partials + chosen
        # attribute index (for attribute-level reason codes)
        probs = jnp.concatenate(
            [chosen, first.astype(jnp.float32)], axis=1
        )  # [B, 2C]
        return ModelOutput(
            value=value.astype(jnp.float32),
            valid=valid,
            probs=probs,
            label_idx=None,
        )

    return Lowered(fn=fn, params=params, labels=())
