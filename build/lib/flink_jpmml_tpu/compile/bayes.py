"""NaiveBayesModel → JAX: summed log-likelihood tables + argmax.

Reference parity: JPMML scores NaiveBayes documents (SURVEY.md §1 C1).
Semantics (PMML 4.x):

    L(t) = log count(t) + Σ_i log P(x_i | t)

- categorical input: P = PairCounts count / BayesOutput target count;
  zero probabilities are replaced by the model ``threshold``;
- continuous input: Gaussian density from TargetValueStats
  (mean/variance per target value);
- a missing input (or an input value with no PairCounts row) simply
  drops its term — records with everything missing score the priors.

The winner is argmax L; per-class probabilities are the normalized
likelihoods (softmax over L). Lowering: each categorical input is one
log-probability table ``[V_i + 1, T]`` (last row = the out-of-table /
missing zero row) gathered per record; continuous inputs are closed-form
log-density lanes; everything sums into one ``[B, T]`` plane.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from flink_jpmml_tpu.compile.common import Lowered, LowerCtx, ModelOutput
from flink_jpmml_tpu.pmml import ir
from flink_jpmml_tpu.utils.exceptions import ModelCompilationException


def lower_naive_bayes(model: ir.NaiveBayesIR, ctx: LowerCtx) -> Lowered:
    labels = tuple(v for v, _ in model.target_counts)
    T = len(labels)
    tpos = {v: i for i, v in enumerate(labels)}
    totals = np.asarray([c for _, c in model.target_counts], np.float64)
    if (totals <= 0).any():
        raise ModelCompilationException(
            "BayesOutput target counts must all be positive"
        )
    thr = model.threshold
    prior = np.log(totals)  # unnormalized: constants cancel in argmax

    cat_tables: list = []  # (col, codes f32[V], logp f32[V+1, T])
    cont_rows: list = []  # (col, mean[T], var[T], active[T])
    for bi in model.inputs:
        col = ctx.column(bi.field)
        if isinstance(bi, ir.BayesCategoricalInput):
            codes = []
            rows = []
            for value, counts in bi.counts:
                codes.append(ctx.encode(bi.field, value))
                row = np.zeros((T,), np.float64)
                for tv, cnt in counts:
                    if tv not in tpos:
                        raise ModelCompilationException(
                            f"BayesInput {bi.field!r}: PairCounts target "
                            f"{tv!r} not in BayesOutput"
                        )
                    row[tpos[tv]] = cnt
                p = row / totals
                if thr <= 0 and (p <= 0).any():
                    raise ModelCompilationException(
                        f"BayesInput {bi.field!r}: zero conditional "
                        "probability with no positive model threshold"
                    )
                # the threshold replaces ZERO probabilities only (spec);
                # a small positive p stays itself even if below threshold
                rows.append(np.log(np.where(p > 0, p, thr)))
            # sentinel last row: out-of-table / missing input drops the
            # term (contributes 0 to every class)
            logp = np.zeros((len(rows) + 1, T), np.float32)
            logp[: len(rows)] = np.asarray(rows, np.float32)
            cat_tables.append(
                (col, np.asarray(codes, np.float32), logp)
            )
        else:
            mean = np.zeros((T,), np.float32)
            var = np.ones((T,), np.float32)
            active = np.zeros((T,), np.float32)
            for tv, m_, v_ in bi.stats:
                if tv not in tpos:
                    raise ModelCompilationException(
                        f"BayesInput {bi.field!r}: stats target {tv!r} "
                        "not in BayesOutput"
                    )
                if v_ <= 0:
                    raise ModelCompilationException(
                        f"BayesInput {bi.field!r}: non-positive variance "
                        f"for target {tv!r}"
                    )
                mean[tpos[tv]] = m_
                var[tpos[tv]] = v_
                active[tpos[tv]] = 1.0
            cont_rows.append((col, mean, var, active))

    params = {
        "prior": prior.astype(np.float32),
        **{
            f"cat{i}_logp": t[2] for i, t in enumerate(cat_tables)
        },
        **{
            f"cat{i}_codes": t[1] for i, t in enumerate(cat_tables)
        },
    }
    for i, (col, mean, var, active) in enumerate(cont_rows):
        params[f"g{i}_mean"] = mean
        params[f"g{i}_var"] = var
        params[f"g{i}_act"] = active
    log2pi = float(math.log(2.0 * math.pi))

    def fn(p, X, M):
        B = X.shape[0]
        L = jnp.broadcast_to(p["prior"][None, :], (B, T))
        for i, (col, _codes, _logp) in enumerate(cat_tables):
            codes = p[f"cat{i}_codes"]
            logp = p[f"cat{i}_logp"]
            V = codes.shape[0]
            x = X[:, col]
            hit = x[:, None] == codes[None, :]  # [B, V]
            idx = jnp.where(
                jnp.any(hit, axis=1) & ~M[:, col],
                jnp.argmax(hit, axis=1),
                V,  # sentinel zero row: missing / unknown value
            )
            L = L + jnp.take(logp, idx, axis=0)
        for i, (col, _m, _v, _a) in enumerate(cont_rows):
            mean = p[f"g{i}_mean"]
            var = p[f"g{i}_var"]
            act = p[f"g{i}_act"]
            x = X[:, col][:, None]
            logpdf = -0.5 * (log2pi + jnp.log(var))[None, :] - (
                (x - mean[None, :]) ** 2 / (2.0 * var)[None, :]
            )
            drop = M[:, col][:, None] | (act[None, :] < 0.5)
            L = L + jnp.where(drop, 0.0, logpdf)
        lab = jnp.argmax(L, axis=1).astype(jnp.int32)
        m = jnp.max(L, axis=1, keepdims=True)
        e = jnp.exp(L - m)
        probs = e / jnp.sum(e, axis=1, keepdims=True)
        value = jnp.take_along_axis(probs, lab[:, None], axis=1)[:, 0]
        return ModelOutput(
            value=value.astype(jnp.float32),
            valid=jnp.ones((B,), bool),
            probs=probs.astype(jnp.float32),
            label_idx=lab,
        )

    return Lowered(fn=fn, params=params, labels=labels)
