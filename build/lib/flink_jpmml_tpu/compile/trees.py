"""TreeModel / tree ensembles → JAX via a path-matrix einsum lowering.

This is the performance-critical lowering (BASELINE config 2: 500-tree GBM at
≥1M rec/s/chip). The reference walks each tree per record on the CPU
(SURVEY.md §4.1 hot loop); a TPU wants matmuls, so we restructure evaluation
as three dense contractions (the "GEMM strategy" family — cf. Hummingbird —
adapted to per-tree block structure so the FLOP count stays linear in
trees × leaves):

1. **Split indicators**: gather each split's feature into ``x[B,T,S]``,
   compare against thresholds → ``go_left[B,T,S]`` (missing values follow the
   split's ``defaultChild`` direction, or poison the lane when the strategy
   demands a null prediction).
2. **Leaf matching**: encode each tree's topology as a path matrix
   ``P[T,S,L] ∈ {+1 (left edge), −1 (right edge), 0 (off-path)}`` with
   per-leaf edge counts ``c[T,L]``. A leaf is reached iff
   ``einsum('bts,tsl->btl', sign(go_left), P) == c`` — an MXU-friendly
   batched matmul. Operands are cast to ``CompileConfig.matmul_dtype``
   (bfloat16 by default): values are in {−1,0,+1} and path sums are bounded
   by tree depth ≤ 255, all exactly representable in bf16 with float32
   accumulation, so the comparison is exact.
3. **Leaf values**: one-hot leaf selection contracts with leaf values
   (float32, to preserve regression exactness) or per-class distributions.

Trees deeper than ``CompileConfig.max_dense_depth`` use an iterative
node-hop traversal (``lax.fori_loop`` + gathers) instead — O(depth) gathers
rather than an O(S·L) matmul.

Supported missing-value strategies: ``defaultChild``, ``none``,
``nullPrediction`` (vectorized as data); ``lastPrediction`` is rejected at
compile time (the oracle supports it; a lowering can follow).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_jpmml_tpu.compile import common
from flink_jpmml_tpu.compile.common import HIGHEST, Lowered, LowerCtx, ModelOutput
from flink_jpmml_tpu.pmml import ir
from flink_jpmml_tpu.utils.exceptions import ModelCompilationException

# opcodes for canonical splits (static per model)
_OPS = {"lessThan": 0, "lessOrEqual": 1, "greaterThan": 2, "greaterOrEqual": 3,
        "equal": 4, "notEqual": 5}
_OP_IN = 6       # SimpleSetPredicate isIn   (categorical splits)
_OP_NOT_IN = 7   # SimpleSetPredicate isNotIn
_COMPLEMENT = {
    "lessThan": "greaterOrEqual",
    "lessOrEqual": "greaterThan",
    "greaterThan": "lessOrEqual",
    "greaterOrEqual": "lessThan",
    "equal": "notEqual",
    "notEqual": "equal",
}


@dataclass
class _CanonLeaf:
    score: Optional[str]
    distribution: Tuple[ir.ScoreDistribution, ...]


@dataclass
class _CanonSplit:
    col: int
    op: int  # opcode (_OPS values, _OP_IN, _OP_NOT_IN)
    value: float  # threshold (comparison splits; 0.0 for set splits)
    default_left: bool
    missing_null: bool  # True → a missing value here nulls the prediction
    left: "_CanonNode"
    right: "_CanonNode"
    set_values: Tuple[float, ...] = ()  # member codes (set splits only)
    # True → a missing value halts traversal and the tree returns the last
    # *scored* node on the path (lastPrediction / returnLastPrediction)
    halt: bool = False
    # this node's own payload (interior nodes may carry scores — they are
    # the candidates the halt path returns)
    node_score: Optional[str] = None
    node_dist: Tuple[ir.ScoreDistribution, ...] = ()


_CanonNode = object  # _CanonSplit | _CanonLeaf


class NonCanonicalTreeError(ModelCompilationException):
    """The forest's *shape* doesn't fit the canonical binary-split form
    (compound predicates, n-ary nodes, non-complementary children,
    non-True roots). Routed to the general scan backend (gtrees.py);
    genuine model errors stay plain ModelCompilationExceptions and
    propagate loudly instead of silently degrading to the slow path."""


def _canonicalize(
    node: ir.TreeNode, model: ir.TreeModelIR, ctx: LowerCtx
) -> _CanonNode:
    """Reduce a PMML tree node to canonical binary form.

    Canonical: every internal node has exactly two children whose predicates
    are (P, complement-of-P) or (P, True) for a simple comparison P. This is
    the shape every mainstream GBM/CART exporter emits. Non-canonical trees
    raise with a clear message rather than silently misevaluating.
    """
    if node.is_leaf:
        return _CanonLeaf(score=node.score, distribution=node.score_distribution)
    if len(node.children) != 2:
        raise NonCanonicalTreeError(
            f"non-binary tree node (id={node.node_id!r}, "
            f"{len(node.children)} children) — only binary-split trees lower "
            "to the dense path"
        )
    c1, c2 = node.children
    p1, p2 = c1.predicate, c2.predicate

    split = _extract_split(p1, p2, ctx, node)
    if split is None:
        # degenerate: first child is catch-all → it always wins (first-match)
        if isinstance(p1, ir.TruePredicate):
            return _canonicalize(c1, model, ctx)
        raise NonCanonicalTreeError(
            f"tree node {node.node_id!r} children predicates "
            f"({type(p1).__name__}, {type(p2).__name__}) are not a canonical "
            "binary split"
        )
    col, op, value, set_values = split
    right_is_catch_all = isinstance(p2, ir.TruePredicate)

    strategy = model.missing_value_strategy
    halt = False
    if strategy == "defaultChild":
        if node.default_child is not None:
            default_left = node.default_child == c1.node_id
            if not default_left and node.default_child != c2.node_id:
                raise ModelCompilationException(
                    f"defaultChild {node.default_child!r} names no child of "
                    f"node {node.node_id!r}"
                )
            missing_null = False
        else:
            # no defaultChild attribute: a missing value nulls the prediction
            default_left, missing_null = True, True
    elif strategy == "lastPrediction":
        # missing → return the last scored node on the path (oracle
        # interp._eval_tree lastPrediction branch)
        default_left, missing_null, halt = True, False, True
    elif strategy == "none" and right_is_catch_all:
        # UNKNOWN left predicate → scan continues → the <True/> child matches
        default_left, missing_null = False, False
    elif strategy in ("none", "nullPrediction"):
        # both children UNKNOWN → no child matches → noTrueChildStrategy
        # decides: returnNullPrediction nulls, returnLastPrediction halts
        if (
            strategy == "none"
            and model.no_true_child_strategy == "returnLastPrediction"
        ):
            default_left, missing_null, halt = True, False, True
        else:
            default_left, missing_null = True, True
    else:
        raise ModelCompilationException(
            f"missingValueStrategy {strategy!r} has no vectorized lowering "
            "(supported: defaultChild, lastPrediction, none, nullPrediction)"
        )

    return _CanonSplit(
        col=col,
        op=op,
        value=value,
        default_left=default_left,
        missing_null=missing_null,
        left=_canonicalize(c1, model, ctx),
        right=_canonicalize(c2, model, ctx),
        set_values=set_values,
        halt=halt,
        node_score=node.score,
        node_dist=node.score_distribution,
    )


def _extract_split(
    p1: ir.Predicate, p2: ir.Predicate, ctx: LowerCtx, node: ir.TreeNode
) -> Optional[Tuple[int, int, float, Tuple[float, ...]]]:
    """(left pred, right pred) → (col, opcode, threshold, set_codes) or None."""
    if isinstance(p1, ir.SimplePredicate) and p1.operator in _OPS:
        col = ctx.column(p1.field)
        value = ctx.encode(p1.field, p1.value)
        if isinstance(p2, ir.TruePredicate):
            return col, _OPS[p1.operator], value, ()
        if (
            isinstance(p2, ir.SimplePredicate)
            and p2.field == p1.field
            and p2.operator == _COMPLEMENT[p1.operator]
            and p2.value == p1.value
        ):
            return col, _OPS[p1.operator], value, ()
    if isinstance(p1, ir.SimpleSetPredicate):
        col = ctx.column(p1.field)
        codes = tuple(ctx.encode(p1.field, v) for v in p1.values)
        op = _OP_IN if p1.boolean_operator == "isIn" else _OP_NOT_IN
        value = 0.0
        if not codes:
            # degenerate empty set: isIn {} ≡ always-false, isNotIn {} ≡
            # always-true — encode as a NaN comparison (x == NaN is never
            # true, x != NaN always is); missing-value handling is unchanged
            op = _OPS["equal"] if op == _OP_IN else _OPS["notEqual"]
            value = float("nan")
        complementary = (
            isinstance(p2, ir.SimpleSetPredicate)
            and p2.field == p1.field
            and frozenset(p2.values) == frozenset(p1.values)
            and p2.boolean_operator != p1.boolean_operator
        )
        if isinstance(p2, ir.TruePredicate) or complementary:
            return col, op, value, codes
    return None


# ---------------------------------------------------------------------------
# Packing: canonical trees → padded dense arrays
# ---------------------------------------------------------------------------


@dataclass
class _FlatTree:
    # per split
    cols: List[int] = dc_field(default_factory=list)
    ops: List[int] = dc_field(default_factory=list)
    values: List[float] = dc_field(default_factory=list)
    dleft: List[bool] = dc_field(default_factory=list)
    mnull: List[bool] = dc_field(default_factory=list)
    sets: List[Tuple[float, ...]] = dc_field(default_factory=list)
    # per leaf
    leaf_scores: List[Optional[str]] = dc_field(default_factory=list)
    leaf_dists: List[Tuple[ir.ScoreDistribution, ...]] = dc_field(
        default_factory=list
    )
    paths: List[List[Tuple[int, int]]] = dc_field(default_factory=list)
    # (split_idx, +1 left / −1 right) per edge on the leaf's path
    depth: int = 0


# -- shared leaf payload rules (both packers MUST agree on these) -----------


def _collect_labels(leaves) -> Tuple[str, ...]:
    """Ordered label space from (score, distribution) leaf pairs."""
    label_set: List[str] = []
    for score, dist in leaves:
        for d in dist:
            if d.value not in label_set:
                label_set.append(d.value)
        if score is not None and score not in label_set:
            label_set.append(score)
    return tuple(label_set)


def _leaf_class_row(
    score: Optional[str],
    dist: Tuple[ir.ScoreDistribution, ...],
    labels: Tuple[str, ...],
    where: str,
) -> Tuple[int, np.ndarray]:
    """→ (label index, dense per-class probability row).

    The label is the leaf's ``score`` attribute when present (PMML allows it
    to disagree with the distribution argmax); probabilities come from
    explicit ``probability`` attributes or record counts; a score-only leaf
    gets probability 1 on its label.
    """
    total = sum(d.record_count for d in dist)
    probs = {}
    for d in dist:
        if d.probability is not None:
            probs[d.value] = d.probability
        elif total > 0:
            probs[d.value] = d.record_count / total
    lab = score if score is not None else (
        max(probs, key=probs.get) if probs else None
    )
    if lab is None:
        raise ModelCompilationException(
            f"classification leaf {where} has neither score nor "
            "ScoreDistribution"
        )
    row = np.zeros((len(labels),), np.float32)
    for lbl, pr in probs.items():
        row[labels.index(lbl)] = pr
    if not probs:
        row[labels.index(lab)] = 1.0
    return labels.index(lab), row


def _leaf_value(score: Optional[str], where: str) -> float:
    if score is None:
        raise ModelCompilationException(f"regression leaf {where} has no score")
    try:
        return float(score)
    except ValueError:
        raise ModelCompilationException(
            f"regression leaf score {score!r} is not numeric"
        ) from None


def _flatten(node: _CanonNode, flat: _FlatTree, path: List[Tuple[int, int]]):
    if isinstance(node, _CanonLeaf):
        flat.leaf_scores.append(node.score)
        flat.leaf_dists.append(node.distribution)
        flat.paths.append(list(path))
        flat.depth = max(flat.depth, len(path))
        return
    s: _CanonSplit = node
    if s.halt:
        raise ModelCompilationException(
            "halting missing-value semantics (lastPrediction / "
            "returnLastPrediction) require the iterative backend"
        )
    idx = len(flat.cols)
    flat.cols.append(s.col)
    flat.ops.append(s.op)
    flat.values.append(s.value)
    flat.dleft.append(s.default_left)
    flat.mnull.append(s.missing_null)
    flat.sets.append(s.set_values)
    _flatten(s.left, flat, path + [(idx, +1)])
    _flatten(s.right, flat, path + [(idx, -1)])


@dataclass
class PackedEnsemble:
    """Padded dense arrays for T trees (static shape metadata + params)."""

    n_trees: int
    n_splits: int  # S (max, padded)
    n_leaves: int  # L (max, padded)
    depth: int
    opcodes: np.ndarray  # i8[T, S] — static (specializes comparisons)
    uniform_op: Optional[int]
    labels: Tuple[str, ...]  # classification class list ((),) for regression
    params: Dict[str, np.ndarray]
    # params: feat i32[T,S], thresh f32[T,S], dleft f32[T,S], mnull f32[T,S],
    #         P f32[T,S,L], count f32[T,L],
    #         leaf_values f32[T,L] (regression) or leaf_probs f32[T,L,C] and
    #         leaf_label i8/i32[T,L] (classification)


def _canonicalize_forest(
    trees: Sequence[ir.TreeModelIR], ctx: LowerCtx
) -> Tuple[List[_CanonNode], bool, int]:
    """Canonicalize + validate an ensemble ONCE → (canons, classification,
    depth). Both packers consume the canonical forest, so the recursive
    canonicalization cost is paid a single time on the 500-tree fast path."""
    classification = trees[0].function_name == "classification"
    canons: List[_CanonNode] = []
    depth = 1
    for t in trees:
        if (t.function_name == "classification") != classification:
            raise ModelCompilationException(
                "mixed regression/classification trees in one ensemble"
            )
        if not isinstance(t.root.predicate, ir.TruePredicate):
            raise NonCanonicalTreeError(
                "tree root predicate must be <True/> for the fused lowering"
            )
        canon = _canonicalize(t.root, t, ctx)
        canons.append(canon)
        depth = max(depth, _canon_depth(canon))
    return canons, classification, depth


def _canon_depth(canon: _CanonNode) -> int:
    if isinstance(canon, _CanonLeaf):
        return 0
    return 1 + max(_canon_depth(canon.left), _canon_depth(canon.right))


def _canon_has_halt(canon: _CanonNode) -> bool:
    if isinstance(canon, _CanonLeaf):
        return False
    return (
        canon.halt or _canon_has_halt(canon.left) or _canon_has_halt(canon.right)
    )


def pack_ensemble(
    canons: Sequence[_CanonNode], classification: bool
) -> PackedEnsemble:
    flats: List[_FlatTree] = []
    for canon in canons:
        flat = _FlatTree()
        _flatten(canon, flat, [])
        if not flat.cols:
            # single-leaf tree: manufacture a no-op split so S ≥ 1
            flat.cols, flat.ops, flat.values = [0], [0], [float("inf")]
            flat.dleft, flat.mnull, flat.sets = [True], [False], [()]
            flat.paths = [[(0, +1)], [(0, -1)]]
            flat.leaf_scores = flat.leaf_scores * 2
            flat.leaf_dists = flat.leaf_dists * 2
            flat.depth = 1
        flats.append(flat)

    T = len(flats)
    S = max(len(f.cols) for f in flats)
    L = max(len(f.leaf_scores) for f in flats)
    depth = max(f.depth for f in flats)

    feat = np.zeros((T, S), np.int32)
    ops = np.zeros((T, S), np.int8)
    thresh = np.zeros((T, S), np.float32)
    dleft = np.zeros((T, S), np.float32)
    mnull = np.zeros((T, S), np.float32)
    P = np.zeros((T, S, L), np.float32)
    count = np.full((T, L), -5.0, np.float32)  # padded leaves can never match
    K = max((len(s) for f in flats for s in f.sets), default=0)
    set_codes = (
        np.full((T, S, K), np.nan, np.float32) if K > 0 else None
    )  # NaN pad: never equal to any input

    labels: Tuple[str, ...] = ()
    if classification:
        labels = _collect_labels(
            (s, d)
            for f in flats
            for s, d in zip(f.leaf_scores, f.leaf_dists)
        )
        C = len(labels)
        leaf_probs = np.zeros((T, L, C), np.float32)
        leaf_label = np.zeros((T, L), np.int32)
    else:
        leaf_values = np.zeros((T, L), np.float32)

    for ti, f in enumerate(flats):
        ns = len(f.cols)
        feat[ti, :ns] = f.cols
        ops[ti, :ns] = f.ops
        thresh[ti, :ns] = f.values
        dleft[ti, :ns] = np.asarray(f.dleft, np.float32)
        mnull[ti, :ns] = np.asarray(f.mnull, np.float32)
        if set_codes is not None:
            for si, s in enumerate(f.sets):
                if s:
                    set_codes[ti, si, : len(s)] = s
        for li, path in enumerate(f.paths):
            count[ti, li] = len(path)
            for s_idx, direction in path:
                P[ti, s_idx, li] = direction
            score = f.leaf_scores[li]
            where = f"{li} in tree {ti}"
            if classification:
                lab_idx, row = _leaf_class_row(
                    score, f.leaf_dists[li], labels, where
                )
                leaf_label[ti, li] = lab_idx
                leaf_probs[ti, li] = row
            else:
                leaf_values[ti, li] = _leaf_value(score, where)

    # uniform-op specialization: padded split slots don't constrain it
    real_ops = {op for f in flats for op in f.ops}
    uniform_op = real_ops.pop() if len(real_ops) == 1 else None
    if uniform_op is not None:
        ops[:] = uniform_op

    params: Dict[str, np.ndarray] = {
        "feat": feat,
        "thresh": thresh,
        "dleft": dleft,
        "mnull": mnull,
        "P": P,
        "count": count,
    }
    if set_codes is not None:
        params["set_codes"] = set_codes
    if classification:
        params["leaf_probs"] = leaf_probs
        params["leaf_label"] = leaf_label.astype(np.float32)
    else:
        params["leaf_values"] = leaf_values

    return PackedEnsemble(
        n_trees=T,
        n_splits=S,
        n_leaves=L,
        depth=depth,
        opcodes=ops,
        uniform_op=int(uniform_op) if uniform_op is not None else None,
        labels=labels,
        params=params,
    )


# ---------------------------------------------------------------------------
# Evaluation
# ---------------------------------------------------------------------------


def _compare(x, t, op_arr, uniform_op, member=None):
    """Split comparison dispatch shared by the dense and iterative paths.

    ``op_arr`` broadcasts against ``x`` (int opcodes); ``member`` is the set
    membership lane for _OP_IN/_OP_NOT_IN splits (None when no set splits).
    """
    if uniform_op is not None:
        op = uniform_op
        if op == _OP_IN:
            return member
        if op == _OP_NOT_IN:
            return ~member
        return (
            x < t if op == 0 else
            x <= t if op == 1 else
            x > t if op == 2 else
            x >= t if op == 3 else
            x == t if op == 4 else
            x != t
        )
    cmp = jnp.where(
        op_arr == 0, x < t,
        jnp.where(op_arr == 1, x <= t,
        jnp.where(op_arr == 2, x > t,
        jnp.where(op_arr == 3, x >= t,
        jnp.where(op_arr == 4, x == t, x != t)))),
    )
    if member is not None:
        cmp = jnp.where(
            op_arr == _OP_IN, member,
            jnp.where(op_arr == _OP_NOT_IN, ~member, cmp),
        )
    return cmp


def _go_left(
    x: jnp.ndarray,  # f32[B, T, S] gathered feature values
    m: jnp.ndarray,  # bool[B, T, S] missing
    p: dict,
    opcodes: np.ndarray,
    uniform_op: Optional[int],
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """→ (go_left bool[B,T,S], nulled bool[B,T,S])."""
    t = p["thresh"][None, :, :]
    member = None
    if "set_codes" in p:
        member = jnp.any(x[..., None] == p["set_codes"][None], axis=-1)
    cmp = _compare(x, t, opcodes[None, :, :], uniform_op, member)
    go = jnp.where(m, p["dleft"][None] > 0.5, cmp)
    nulled = m & (p["mnull"][None] > 0.5)
    return go, nulled


def make_ensemble_eval(packed: PackedEnsemble, ctx: LowerCtx):
    """→ fn(params, X, M) -> (sel bf/f32[B,T,L] one-hot, tree_null bool[B,T]).

    ``sel`` one-hot selects each tree's reached leaf; ``tree_null`` marks
    (record, tree) pairs whose selected path crossed a missing-nulled split.
    """
    # bf16 topology matmuls are exact here (±1/0 operands, depth-bounded
    # sums) and run at full MXU rate on TPU; the CPU backend has no bf16 dot
    # kernel, so fall back to f32 there.
    use_bf16 = (
        ctx.config.matmul_dtype == "bfloat16"
        and not common.backend_is_cpu()
    )
    cdtype = jnp.bfloat16 if use_bf16 else jnp.float32
    opcodes = packed.opcodes
    uniform_op = packed.uniform_op

    def fn(p: dict, X: jnp.ndarray, M: jnp.ndarray):
        feat = p["feat"]  # i32[T, S]
        x = X[:, feat]  # [B, T, S]
        m = M[:, feat]
        go, nulled = _go_left(x, m, p, opcodes, uniform_op)
        sign = (2.0 * go.astype(cdtype) - 1.0)
        Pm = p["P"].astype(cdtype)
        match = jnp.einsum(
            "bts,tsl->btl", sign, Pm, preferred_element_type=jnp.float32
        )
        # sel stays float32: XLA would otherwise fuse a bf16 sel through the
        # downstream value einsums and demote the f32 leaf values to bf16
        sel = (match == p["count"][None]).astype(jnp.float32)  # one-hot [B,T,L]
        # a nulled split on the selected path ⇒ tree result is null
        nullcnt = jnp.einsum(
            "bts,tsl->btl",
            nulled.astype(cdtype),
            jnp.abs(Pm),
            preferred_element_type=jnp.float32,
        )
        on_path_null = jnp.einsum(
            "btl,btl->bt", sel, nullcnt, precision=HIGHEST
        )
        return sel, on_path_null > 0.5

    return fn


# ---------------------------------------------------------------------------
# Iterative node-hop evaluation (deep trees: O(depth) gathers instead of an
# O(S·L) path matrix)
# ---------------------------------------------------------------------------


@dataclass
class PackedNodes:
    """Node-table form: every tree's canonical nodes in one padded [T, N]
    family; leaves self-loop so a fixed ``depth`` iteration count converges."""

    n_trees: int
    n_nodes: int  # N (max, padded)
    depth: int
    uniform_op: Optional[int]
    has_sets: bool
    labels: Tuple[str, ...]
    params: Dict[str, np.ndarray]
    # params: col i32[T,N], op f32[T,N], thresh f32[T,N], dleft f32[T,N],
    #         mnull f32[T,N], left i32[T,N], right i32[T,N], is_leaf f32[T,N],
    #         value f32[T,N] | (probs f32[T,N,C] + label f32[T,N]),
    #         set_codes f32[T,N,K] (when set splits exist)


def _node_flatten(canon: _CanonNode, rows: List[dict]) -> int:
    """Pre-order flatten; returns this node's index."""
    idx = len(rows)
    rows.append({})  # reserve
    if isinstance(canon, _CanonLeaf):
        rows[idx] = {
            "leaf": True,
            "score": canon.score,
            "dist": canon.distribution,
            "left": idx,
            "right": idx,
        }
        return idx
    s: _CanonSplit = canon
    left = _node_flatten(s.left, rows)
    right = _node_flatten(s.right, rows)
    rows[idx] = {
        "leaf": False,
        "col": s.col,
        "op": s.op,
        "thresh": s.value,
        "dleft": s.default_left,
        "mnull": s.missing_null,
        "sets": s.set_values,
        "left": left,
        "right": right,
        "halt": s.halt,
        "score": s.node_score,
        "dist": s.node_dist,
    }
    return idx


def pack_nodes(
    canons: Sequence[_CanonNode], classification: bool, depth: int
) -> PackedNodes:
    per_tree_rows: List[List[dict]] = []
    for canon in canons:
        rows: List[dict] = []
        _node_flatten(canon, rows)
        per_tree_rows.append(rows)

    T = len(per_tree_rows)
    N = max(len(r) for r in per_tree_rows)
    K = max(
        (len(row.get("sets", ())) for rows in per_tree_rows for row in rows),
        default=0,
    )

    col = np.zeros((T, N), np.int32)
    op = np.zeros((T, N), np.float32)
    thresh = np.zeros((T, N), np.float32)
    dleft = np.zeros((T, N), np.float32)
    mnull = np.zeros((T, N), np.float32)
    halt = np.zeros((T, N), np.float32)
    scored = np.zeros((T, N), np.float32)  # node carries a payload
    # padding rows are self-looping leaves; real rows are overwritten below
    left = np.broadcast_to(np.arange(N, dtype=np.int32), (T, N)).copy()
    right = left.copy()
    is_leaf = np.ones((T, N), np.float32)
    set_codes = np.full((T, N, K), np.nan, np.float32) if K else None

    labels: Tuple[str, ...] = ()
    if classification:
        labels = _collect_labels(
            (row["score"], row["dist"])
            for rows in per_tree_rows
            for row in rows
            if row["leaf"] or row["score"] is not None or row["dist"]
        )
        C = len(labels)
        probs = np.zeros((T, N, C), np.float32)
        label = np.zeros((T, N), np.float32)
    else:
        value = np.zeros((T, N), np.float32)
        # dist-only regression interiors count as "scored" for halt
        # tracking (oracle last_scored) but their value is null
        valnull = np.zeros((T, N), np.float32)

    ops_seen = set()
    for ti, rows in enumerate(per_tree_rows):
        for ni, row in enumerate(rows):
            left[ti, ni] = row["left"]
            right[ti, ni] = row["right"]
            has_payload = (
                row["leaf"]
                or row["score"] is not None
                or bool(row["dist"])
            )
            if has_payload:
                scored[ti, ni] = 1.0
                where = f"{ni} in tree {ti}"
                if classification:
                    lab_idx, prow = _leaf_class_row(
                        row["score"], row["dist"], labels, where
                    )
                    label[ti, ni] = lab_idx
                    probs[ti, ni] = prow
                elif row["score"] is None and not row["leaf"]:
                    valnull[ti, ni] = 1.0  # dist-only interior node
                else:
                    value[ti, ni] = _leaf_value(row["score"], where)
            if not row["leaf"]:
                is_leaf[ti, ni] = 0.0
                col[ti, ni] = row["col"]
                op[ti, ni] = row["op"]
                thresh[ti, ni] = row["thresh"]
                dleft[ti, ni] = float(row["dleft"])
                mnull[ti, ni] = float(row["mnull"])
                if row["halt"]:
                    halt[ti, ni] = 1.0
                ops_seen.add(row["op"])
                if set_codes is not None and row["sets"]:
                    set_codes[ti, ni, : len(row["sets"])] = row["sets"]

    uniform_op = ops_seen.pop() if len(ops_seen) == 1 else None
    params: Dict[str, np.ndarray] = {
        "col": col,
        "op": op,
        "thresh": thresh,
        "dleft": dleft,
        "mnull": mnull,
        "left": left,
        "right": right,
        "is_leaf": is_leaf,
        "halt": halt,
        "scored": scored,
    }
    if set_codes is not None:
        params["set_codes"] = set_codes
    if classification:
        params["probs"] = probs
        params["label"] = label
    else:
        params["value"] = value
        params["valnull"] = valnull
    return PackedNodes(
        n_trees=T,
        n_nodes=N,
        depth=depth,
        uniform_op=uniform_op,
        has_sets=set_codes is not None,
        labels=labels,
        params=params,
    )


def make_iterative_eval(packed: PackedNodes):
    """→ tree_eval(params, X, M) -> (final_idx i32[B,T], null bool[B,T]).

    ``lax.fori_loop`` over tree depth; every step gathers the current
    node's attributes per (record, tree) and hops left/right. Leaves
    self-loop, so exactly ``depth`` iterations settle every lane.

    Halting strategies (lastPrediction / noTrueChildStrategy
    returnLastPrediction) latch a ``stopped`` mask and track the node index
    of the last *scored* ancestor (``last``); a stopped lane's final index
    is that ancestor (or null when no ancestor ever carried a score) —
    mirroring the oracle's ``last_scored`` bookkeeping in interp._eval_tree.
    """
    T, N, depth = packed.n_trees, packed.n_nodes, packed.depth
    uniform_op = packed.uniform_op
    has_sets = packed.has_sets
    any_halt = bool(packed.params["halt"].any())

    def fn(p: dict, X: jnp.ndarray, M: jnp.ndarray):
        B = X.shape[0]
        offs = jnp.arange(T, dtype=jnp.int32)[None, :] * N  # [1, T]
        colf = p["col"].reshape(-1)
        opf = p["op"].reshape(-1)
        threshf = p["thresh"].reshape(-1)
        dleftf = p["dleft"].reshape(-1)
        mnullf = p["mnull"].reshape(-1)
        leftf = p["left"].reshape(-1)
        rightf = p["right"].reshape(-1)
        leaff = p["is_leaf"].reshape(-1)
        haltf = p["halt"].reshape(-1)
        scoredf = p["scored"].reshape(-1)
        setf = p["set_codes"].reshape(T * N, -1) if has_sets else None

        def body(_, carry):
            idx, null, stopped, last = carry
            g = offs + idx  # [B, T] flat node ids
            # the current node's own payload counts as "last scored" for a
            # halt at its split (oracle updates last_scored on arrival)
            if any_halt:
                live = ~stopped
                last = jnp.where(
                    live & (jnp.take(scoredf, g) > 0.5), idx, last
                )
            cols = jnp.take(colf, g)
            x = jnp.take_along_axis(X, cols, axis=1)
            m = jnp.take_along_axis(M, cols, axis=1)
            t = jnp.take(threshf, g)
            opg = jnp.take(opf, g)
            member = (
                jnp.any(x[..., None] == jnp.take(setf, g, axis=0), axis=-1)
                if has_sets
                else None
            )
            cmp = _compare(x, t, opg, uniform_op, member)
            go = jnp.where(m, jnp.take(dleftf, g) > 0.5, cmp)
            leaf = jnp.take(leaff, g) > 0.5
            null = null | (m & (jnp.take(mnullf, g) > 0.5) & ~leaf)
            if any_halt:
                stop_now = m & (jnp.take(haltf, g) > 0.5) & ~leaf & ~stopped
                stopped = stopped | stop_now
            nxt = jnp.where(go, jnp.take(leftf, g), jnp.take(rightf, g))
            settled = leaf | stopped if any_halt else leaf
            idx = jnp.where(settled, idx, nxt)
            return idx, null, stopped, last

        idx0 = jnp.zeros((B, T), jnp.int32)
        null0 = jnp.zeros((B, T), bool)
        stopped0 = jnp.zeros((B, T), bool)
        last0 = jnp.full((B, T), -1, jnp.int32)
        idx, null, stopped, last = jax.lax.fori_loop(
            0, depth, body, (idx0, null0, stopped0, last0)
        )
        if any_halt:
            null = null | (stopped & (last < 0))
            idx = jnp.where(stopped & (last >= 0), last, idx)
            if "valnull" in p:
                null = null | (
                    jnp.take(p["valnull"].reshape(-1), offs + idx) > 0.5
                )
        return idx, null

    return fn


def _tree_eval_fns(trees, ctx):
    """Choose the dense (path-matrix einsum) or iterative (node-hop)
    backend and return a uniform per-tree interface:

    regression:      vals(p, X, M)  -> (values f32[B,T], null bool[B,T])
    classification:  cls(p, X, M)   -> (probs f32[B,T,C], label i32[B,T],
                                        null bool[B,T])
    plus (params, labels).
    """
    try:
        canons, classification, depth = _canonicalize_forest(trees, ctx)
    except NonCanonicalTreeError:
        # non-canonical forest (compound predicates, n-ary nodes, non-
        # complementary children, non-True roots, isMissing operators…):
        # the general first-match-scan backend handles it faithfully
        from flink_jpmml_tpu.compile.gtrees import general_tree_eval_fns

        return general_tree_eval_fns(trees, ctx)
    dense = depth <= ctx.config.max_dense_depth and not any(
        _canon_has_halt(c) for c in canons
    )

    if dense:
        packed = pack_ensemble(canons, classification)
        ev = make_ensemble_eval(packed, ctx)
        if not classification:
            def vals(p, X, M):
                sel, null = ev(p, X, M)
                v = jnp.einsum(
                    "btl,tl->bt", sel, p["leaf_values"], precision=HIGHEST
                )
                return v, null
            return vals, packed.params, ()

        def cls(p, X, M):
            sel, null = ev(p, X, M)
            probs = jnp.einsum(
                "btl,tlc->btc", sel, p["leaf_probs"], precision=HIGHEST
            )
            lab = jnp.einsum(
                "btl,tl->bt", sel, p["leaf_label"], precision=HIGHEST
            )
            return probs, jnp.round(lab).astype(jnp.int32), null
        return cls, packed.params, packed.labels

    packed = pack_nodes(canons, classification, depth)
    ev = make_iterative_eval(packed)
    fn = node_payload_fns(ev, packed.n_trees, packed.n_nodes, classification)
    return fn, packed.params, packed.labels


def node_payload_fns(ev, T: int, N: int, classification: bool):
    """Final payload gather shared by every node-table backend (the
    canonical iterative hop and the general scan in gtrees.py): map the
    per-lane final node index to its value / (probs, label)."""
    if not classification:
        def vals(p, X, M):
            idx, null = ev(p, X, M)
            g = jnp.arange(T, dtype=jnp.int32)[None, :] * N + idx
            return jnp.take(p["value"].reshape(-1), g), null
        return vals

    def cls(p, X, M):
        idx, null = ev(p, X, M)
        g = jnp.arange(T, dtype=jnp.int32)[None, :] * N + idx
        C = p["probs"].shape[-1]
        probs = jnp.take(p["probs"].reshape(T * N, C), g, axis=0)
        lab = jnp.round(jnp.take(p["label"].reshape(-1), g)).astype(jnp.int32)
        return probs, lab, null
    return cls


def lower_tree_ensemble(
    trees: Sequence[ir.TreeModelIR],
    weights: Sequence[float],
    method: str,
    ctx: LowerCtx,
) -> Lowered:
    """Fused lowering for an ensemble of canonical trees under one
    segmentation method (the 500-tree-GBM fast path). ``method`` ∈
    {sum, average, weightedAverage, max, median} for regression,
    {majorityVote, weightedMajorityVote} for classification — or 'single'
    for a lone TreeModel. Trees deeper than
    ``CompileConfig.max_dense_depth`` transparently use the iterative
    node-hop backend."""
    w = np.asarray(weights, np.float32)
    classification = trees[0].function_name == "classification"
    eval_fn, params, labels = _tree_eval_fns(trees, ctx)

    if not classification:
        def rfn(p, X, M):
            per_tree, tree_null = eval_fn(p, X, M)
            valid = ~jnp.any(tree_null, axis=1)
            if method in ("sum", "single"):
                value = jnp.sum(per_tree, axis=1)
            elif method == "average":
                value = jnp.mean(per_tree, axis=1)
            elif method == "weightedAverage":
                value = jnp.dot(per_tree, w, precision=HIGHEST) / np.float32(w.sum())
            elif method == "max":
                value = jnp.max(per_tree, axis=1)
            elif method == "median":
                value = jnp.median(per_tree, axis=1)
            else:
                raise ModelCompilationException(
                    f"unsupported regression ensemble method {method!r}"
                )
            return ModelOutput(value=value, valid=valid)

        return Lowered(fn=rfn, params=params)

    C = len(labels)

    if method not in ("single", "majorityVote", "weightedMajorityVote"):
        # sum/average over classification trees aggregate *numeric* winning
        # probabilities in the oracle — not votes; route those through the
        # generic per-segment path (mining._lower_aggregate) instead
        raise ModelCompilationException(
            f"classification ensemble method {method!r} has no fused lowering"
        )

    def cfn(p, X, M):
        tprobs, tlabel, tree_null = eval_fn(p, X, M)
        if method == "single":
            probs = tprobs[:, 0, :]
            valid = ~tree_null[:, 0]
            # the label comes from the leaf's 'score' attribute, NOT argmax
            # of the distribution — PMML allows them to disagree
            label_idx = tlabel[:, 0]
            value = jnp.take_along_axis(probs, label_idx[:, None], axis=1)[:, 0]
            return ModelOutput(
                value=value, valid=valid, probs=probs, label_idx=label_idx
            )
        # each tree votes its leaf's label one-hot (weighted); a tree nulled
        # by a missing value abstains (oracle: excluded from the vote), it
        # does not poison the lane
        votes = jax.nn.one_hot(tlabel, C, dtype=jnp.float32)  # [B, T, C]
        votes = votes * (~tree_null).astype(jnp.float32)[:, :, None]
        if method == "weightedMajorityVote":
            votes = votes * w[None, :, None]
        total = jnp.sum(votes, axis=(1, 2))
        probs = jnp.sum(votes, axis=1) / jnp.maximum(total[:, None], 1e-30)
        valid = total > 0
        label_idx = jnp.argmax(probs, axis=1).astype(jnp.int32)
        value = jnp.take_along_axis(probs, label_idx[:, None], axis=1)[:, 0]
        return ModelOutput(
            value=value, valid=valid, probs=probs, label_idx=label_idx
        )

    return Lowered(fn=cfn, params=params, labels=labels)


def lower_tree(model: ir.TreeModelIR, ctx: LowerCtx) -> Lowered:
    """A standalone TreeModel is an ensemble of one — except the
    fractional-membership strategies, whose weighted-path walk lives in
    wtrees.py (boolean path matrices cannot express them)."""
    if model.missing_value_strategy in (
        "weightedConfidence", "aggregateNodes"
    ):
        from flink_jpmml_tpu.compile.wtrees import lower_weighted_tree

        return lower_weighted_tree(model, ctx)
    return lower_tree_ensemble([model], [1.0], "single", ctx)
