"""AnomalyDetectionModel (PMML 4.4) → JAX: inner model + normalization.

Reference parity: JPMML scores AnomalyDetectionModel documents — the
standard sklearn IsolationForest export (sklearn2pmml wraps the forest
of path-length trees in one). The inner model (any supported family;
iforest uses a MiningModel averaging per-tree path lengths) produces the
raw score s; the wrapper normalizes:

- ``iforest``: score = 2^(−s / c(n)), n = sampleDataSize and
  c(n) = 2·(ln(n−1) + γ) − 2·(n−1)/n (average unsuccessful-search depth
  of a BST; γ the Euler–Mascheroni constant) — higher means more
  anomalous, 0.5 is the "no structure" midpoint.
- ``ocsvm`` / ``other``: the inner value passes through unchanged.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from flink_jpmml_tpu.compile.common import Lowered, LowerCtx
from flink_jpmml_tpu.pmml import ir

_EULER_GAMMA = 0.5772156649015329


def iforest_c(n: int) -> float:
    """Average unsuccessful-search path length of a BST over n samples."""
    return 2.0 * (math.log(n - 1.0) + _EULER_GAMMA) - 2.0 * (n - 1.0) / n


def lower_anomaly(model: ir.AnomalyDetectionIR, ctx: LowerCtx) -> Lowered:
    from flink_jpmml_tpu.compile.compiler import lower_model

    inner = lower_model(model.inner, ctx)
    if model.algorithm_type != "iforest":
        return inner  # ocsvm / other: raw inner value
    c = iforest_c(model.sample_data_size)

    def fn(p, X, M):
        out = inner.fn(p, X, M)
        return out._replace(
            value=jnp.exp2(-out.value / jnp.float32(c)).astype(jnp.float32)
        )

    return Lowered(fn=fn, params=inner.params, labels=inner.labels)
