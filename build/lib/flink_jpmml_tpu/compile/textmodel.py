"""TextModel → JAX: weighted document-similarity as one matmul.

Reference parity: PMML's TextModel class (SURVEY.md §1 C1 model-class
coverage). The corpus DocumentTermMatrix is weighted once at compile
(local × global term weights + optional cosine normalization, float64);
per batch the query rows get the identical weighting in-graph and the
similarity against all documents is a single ``[B, T] @ [T, D]`` matmul
(cosine) or the ‖q−d‖² expansion (euclidean) — MXU-shaped, no per-record
text handling on the device.

Input contract (ir.TextModelIR): one active field per term carrying the
record's term count; missing cells read as 0 (an unobserved term is an
absent term, mirroring the association basket contract), so lanes are
always valid.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from flink_jpmml_tpu.compile.common import (
    HIGHEST,
    Lowered,
    LowerCtx,
    ModelOutput,
)
from flink_jpmml_tpu.pmml import ir


def _weight_np(rows: np.ndarray, kind: str, idf: np.ndarray,
               doc_norm: str) -> np.ndarray:
    if kind == "binary":
        w = (rows > 0).astype(np.float64)
    elif kind == "logarithmic":
        w = np.log10(1.0 + np.maximum(rows, 0.0))
    elif kind == "augmentedNormalizedTermFrequency":
        m = rows.max(axis=1, keepdims=True)
        w = np.where(
            (rows > 0) & (m > 0), 0.5 + 0.5 * rows / np.maximum(m, 1e-30),
            0.0,
        )
    else:  # termFrequency
        w = np.maximum(rows, 0.0)
    w = w * idf[None, :]
    if doc_norm == "cosine":
        n = np.linalg.norm(w, axis=1, keepdims=True)
        w = np.where(n > 0, w / np.maximum(n, 1e-30), 0.0)
    return w


def lower_text_model(model: ir.TextModelIR, ctx: LowerCtx) -> Lowered:
    cols = np.asarray([ctx.column(t) for t in model.terms], np.int32)
    dtm = np.asarray(model.dtm, np.float64)
    D, T = dtm.shape
    if model.global_weight == "inverseDocumentFrequency":
        dj = (dtm > 0).sum(axis=0)
        idf = np.where(dj > 0, np.log10(D / np.maximum(dj, 1)), 0.0)
    else:
        idf = np.ones((T,), np.float64)
    W = _weight_np(dtm, model.local_weight, idf, model.doc_normalization)

    params = {
        "W": W.astype(np.float32),  # [D, T] weighted corpus
        "Wsq": (W ** 2).sum(axis=1).astype(np.float32),  # [D]
        "Wnorm": np.linalg.norm(W, axis=1).astype(np.float32),
        "idf": idf.astype(np.float32),
    }
    local = model.local_weight
    doc_norm = model.doc_normalization
    similarity = model.similarity
    log10 = float(math.log(10.0))

    def fn(p, X, M):
        B = X.shape[0]
        q = jnp.where(M[:, cols], 0.0, jnp.maximum(X[:, cols], 0.0))
        if local == "binary":
            w = (q > 0).astype(jnp.float32)
        elif local == "logarithmic":
            w = jnp.log(1.0 + q) / log10
        elif local == "augmentedNormalizedTermFrequency":
            m = jnp.max(q, axis=1, keepdims=True)
            w = jnp.where(
                (q > 0) & (m > 0), 0.5 + 0.5 * q / jnp.maximum(m, 1e-30),
                0.0,
            )
        else:
            w = q
        w = w * p["idf"][None, :]
        if doc_norm == "cosine":
            n = jnp.linalg.norm(w, axis=1, keepdims=True)
            w = jnp.where(n > 0, w / jnp.maximum(n, 1e-30), 0.0)
        dots = jnp.matmul(w, p["W"].T, precision=HIGHEST)  # [B, D]
        if similarity == "cosine":
            qn = jnp.linalg.norm(w, axis=1, keepdims=True)
            denom = qn * p["Wnorm"][None, :]
            scores = jnp.where(denom > 0, dots / jnp.maximum(denom, 1e-30), 0.0)
            win = jnp.argmax(scores, axis=1).astype(jnp.int32)
        else:  # euclidean: ‖q−d‖² = ‖q‖² + ‖d‖² − 2 q·d
            d2 = (
                jnp.sum(w ** 2, axis=1, keepdims=True)
                + p["Wsq"][None, :]
                - 2.0 * dots
            )
            scores = jnp.sqrt(jnp.maximum(d2, 0.0))
            win = jnp.argmin(scores, axis=1).astype(jnp.int32)
        value = jnp.take_along_axis(scores, win[:, None], axis=1)[:, 0]
        return ModelOutput(
            value=value.astype(jnp.float32),
            valid=jnp.ones((B,), bool),
            probs=scores.astype(jnp.float32),
            label_idx=win,
        )

    return Lowered(fn=fn, params=params, labels=model.doc_ids)
