"""Host-side input preparation: records/vectors → (X, M) batches.

Reference parity (capability C4, SURVEY.md §3 row B2 ``VectorConverter``
[UNVERIFIED]): FlinkML ``DenseVector``s zip positionally with the model's
active fields; ``SparseVector`` gaps become missing values; arity is
validated against the mining schema; ``replaceNan`` optionally substitutes a
default for NaNs *before* missing-value handling.

All of this runs on the host once per micro-batch (cheap, NumPy-vectorized),
so the device graph stays purely numeric.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Mapping, Optional, Sequence, Tuple, Union

import numpy as np

from flink_jpmml_tpu.utils.exceptions import InputValidationException

Value = Union[float, str, None]


@dataclass(frozen=True)
class FieldSpace:
    """The compiled model's input contract: ordered fields + codecs."""

    fields: Tuple[str, ...]
    codecs: Mapping[str, Mapping[str, float]]

    @property
    def arity(self) -> int:
        return len(self.fields)

    def encode_cell(self, field: str, v: Value) -> float:
        """One raw value → float code; NaN encodes 'missing', +inf marks
        an *invalid* (undeclared) category — the compiled sanitize stage
        applies the mining schema's invalidValueTreatment to it
        (compiler.full_fn; spec default returnInvalid)."""
        if v is None:
            return math.nan
        if isinstance(v, str):
            codec = self.codecs.get(field)
            if codec is not None:
                # undeclared category → invalid marker; no numeric
                # fallback (it would alias a numeric-looking string onto
                # a code)
                return codec.get(v, math.inf)
            try:
                return float(v)
            except ValueError:
                return math.nan
        return float(v)


def from_records(
    space: FieldSpace, records: Sequence[Mapping[str, Value]]
) -> Tuple[np.ndarray, np.ndarray]:
    """Dict records → (X, M). Unknown keys are ignored; absent keys are
    missing (mirrors the oracle's ``record.get``)."""
    B, F = len(records), space.arity
    X = np.full((B, F), np.nan, np.float32)
    for b, rec in enumerate(records):
        for j, name in enumerate(space.fields):
            if name in rec:
                X[b, j] = space.encode_cell(name, rec[name])
    M = np.isnan(X)
    return np.where(M, 0.0, X).astype(np.float32), M


def from_dense(
    space: FieldSpace,
    vectors: np.ndarray,
    replace_nan: Optional[float] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Dense vectors [B, F] → (X, M); arity must equal the active fields.

    Reference parity: dense vectors zip with active fields in order; arity
    mismatch is an InputValidationException (→ empty predictions at the API
    layer, SURVEY.md §4.1 validateInput).
    """
    vectors = np.asarray(vectors, np.float32)
    if vectors.ndim != 2:
        raise InputValidationException(
            f"dense batch must be rank-2 [batch, fields], got shape "
            f"{vectors.shape}"
        )
    if vectors.shape[1] != space.arity:
        raise InputValidationException(
            f"input arity {vectors.shape[1]} != model active fields "
            f"{space.arity} ({', '.join(space.fields)})"
        )
    if replace_nan is not None:
        vectors = np.where(np.isnan(vectors), np.float32(replace_nan), vectors)
    M = np.isnan(vectors)
    return np.where(M, 0.0, vectors).astype(np.float32), M


def from_sparse(
    space: FieldSpace,
    indices: Sequence[Sequence[int]],
    values: Sequence[Sequence[float]],
    replace_nan: Optional[float] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Sparse per-record (indices, values) → (X, M); absent indices are
    missing (reference: sparse gaps = missing values)."""
    B, F = len(indices), space.arity
    X = np.full((B, F), np.nan, np.float32)
    for b, (idx, val) in enumerate(zip(indices, values)):
        if len(idx) != len(val):
            raise InputValidationException(
                f"record {b}: {len(idx)} indices but {len(val)} values"
            )
        for i, v in zip(idx, val):
            if not 0 <= i < F:
                raise InputValidationException(
                    f"record {b}: sparse index {i} out of range [0, {F})"
                )
            X[b, i] = v
    if replace_nan is not None:
        X = np.where(np.isnan(X), np.float32(replace_nan), X)
    M = np.isnan(X)
    return np.where(M, 0.0, X).astype(np.float32), M


def pad_batch(
    X: np.ndarray, M: np.ndarray, batch_size: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad a partial batch to the compiled batch shape (static shapes — XLA
    traces once; SURVEY.md §8 step 3 'pad the tail').

    Returns (X_pad, M_pad, lane_mask) where lane_mask marks real records.
    """
    n = X.shape[0]
    if n > batch_size:
        raise InputValidationException(
            f"batch of {n} exceeds compiled batch size {batch_size}"
        )
    lane = np.zeros(batch_size, bool)
    lane[:n] = True
    if n == batch_size:
        return X, M, lane
    Xp = np.zeros((batch_size, X.shape[1]), np.float32)
    Mp = np.ones((batch_size, X.shape[1]), bool)  # padding lanes are missing
    Xp[:n] = X
    Mp[:n] = M
    return Xp, Mp, lane
