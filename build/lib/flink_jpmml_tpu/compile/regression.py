"""RegressionModel → JAX: one matmul + link function (SURVEY.md §8 step 2).

The reference evaluated regression tables per record on the CPU inside
JPMML-Evaluator (SURVEY.md §4.1); here every table is a gathered matmul over
the batch, and the normalization link (logit/softmax/…) is fused elementwise
— exactly the shape XLA tiles onto the MXU/VPU.

Missing semantics (matching the oracle, interp.py): a missing *numeric*
predictor makes that table's value missing (lane invalid); a missing
*categorical* predictor contributes 0.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_jpmml_tpu.compile.common import HIGHEST, Lowered, LowerCtx, ModelOutput
from flink_jpmml_tpu.pmml import ir
from flink_jpmml_tpu.utils.exceptions import ModelCompilationException


def _lower_table(table: ir.RegressionTable, ctx: LowerCtx):
    """One RegressionTable → (params, fn(params, X, M) -> (y, missing))."""
    num_cols = np.asarray(
        [ctx.column(p.name) for p in table.numeric_predictors], np.int32
    )
    num_exps = np.asarray([p.exponent for p in table.numeric_predictors], np.float32)
    all_exp_one = bool(np.all(num_exps == 1.0))
    cat_cols = np.asarray(
        [ctx.column(p.name) for p in table.categorical_predictors], np.int32
    )

    params = {
        "intercept": np.float32(table.intercept),
        "num_coefs": np.asarray(
            [p.coefficient for p in table.numeric_predictors], np.float32
        ),
        "cat_codes": np.asarray(
            [ctx.encode(p.name, p.value) for p in table.categorical_predictors],
            np.float32,
        ),
        "cat_coefs": np.asarray(
            [p.coefficient for p in table.categorical_predictors], np.float32
        ),
    }

    def fn(p: dict, X: jnp.ndarray, M: jnp.ndarray):
        B = X.shape[0]
        y = jnp.broadcast_to(p["intercept"].astype(jnp.float32), (B,))
        missing = jnp.zeros((B,), bool)
        if num_cols.size:
            xs = X[:, num_cols]  # [B, P] static-index gather
            if not all_exp_one:
                xs = xs ** num_exps
            y = y + jnp.dot(xs, p["num_coefs"], precision=HIGHEST)
            missing = missing | jnp.any(M[:, num_cols], axis=1)
        if cat_cols.size:
            xc = X[:, cat_cols]  # [B, Q]
            ind = (xc == p["cat_codes"][None, :]) & ~M[:, cat_cols]
            y = y + jnp.dot(ind.astype(jnp.float32), p["cat_coefs"], precision=HIGHEST)
        return y, missing

    return params, fn


def lower_regression(model: ir.RegressionModelIR, ctx: LowerCtx) -> Lowered:
    nm = model.normalization_method
    lowered_tables = [_lower_table(t, ctx) for t in model.tables]
    params = {f"t{i}": p for i, (p, _) in enumerate(lowered_tables)}
    table_fns = [f for _, f in lowered_tables]

    if model.function_name == "regression":
        if nm not in ("none", "identity", "softmax", "logit", "exp",
                      "cauchit", "cloglog", "loglog", "probit"):
            raise ModelCompilationException(
                f"unsupported regression normalization {nm!r}"
            )
        t0 = table_fns[0]

        def fn(p, X, M):
            y, missing = t0(p["t0"], X, M)
            if nm in ("softmax", "logit"):
                # PMML: for regression, softmax == logit == sigmoid
                y = 1.0 / (1.0 + jnp.exp(-y))
            elif nm == "exp":
                y = jnp.exp(y)
            elif nm == "cauchit":
                y = 0.5 + jnp.arctan(y) / jnp.pi
            elif nm == "cloglog":
                y = 1.0 - jnp.exp(-jnp.exp(y))
            elif nm == "loglog":
                y = jnp.exp(-jnp.exp(-y))
            elif nm == "probit":
                y = 0.5 * (1.0 + jax.scipy.special.erf(y / jnp.sqrt(2.0)))
            return ModelOutput(value=y, valid=~missing)

        return Lowered(fn=fn, params=params)

    if model.function_name != "classification":
        raise ModelCompilationException(
            f"unsupported RegressionModel functionName {model.function_name!r}"
        )

    labels: Tuple[str, ...] = tuple(
        t.target_category or str(i) for i, t in enumerate(model.tables)
    )
    if nm not in ("none", "identity", "softmax", "simplemax", "logit"):
        raise ModelCompilationException(
            f"unsupported classification normalization {nm!r}"
        )
    two_tables = len(table_fns) == 2

    def cfn(p, X, M):
        ys, miss = zip(
            *(f(p[f"t{i}"], X, M) for i, f in enumerate(table_fns))
        )
        Y = jnp.stack(ys, axis=1)  # [B, C]
        missing = jnp.any(jnp.stack(miss, axis=1), axis=1)
        if nm == "softmax":
            probs = softmax(Y)
        elif nm == "simplemax":
            s = jnp.sum(Y, axis=1, keepdims=True)
            probs = jnp.where(s == 0, jnp.nan, Y / s)
        elif nm == "logit":
            if two_tables:
                pr = 1.0 / (1.0 + jnp.exp(-Y[:, 0]))
                probs = jnp.stack([pr, 1.0 - pr], axis=1)
            else:
                probs = 1.0 / (1.0 + jnp.exp(-Y))
        else:
            probs = Y
        label_idx = jnp.argmax(probs, axis=1).astype(jnp.int32)
        value = jnp.take_along_axis(probs, label_idx[:, None], axis=1)[:, 0]
        valid = ~missing & ~jnp.isnan(value)
        return ModelOutput(
            value=value, valid=valid, probs=probs, label_idx=label_idx
        )

    return Lowered(fn=cfn, params=params, labels=labels)


def softmax(Y: jnp.ndarray) -> jnp.ndarray:
    m = jnp.max(Y, axis=1, keepdims=True)
    e = jnp.exp(Y - m)
    return e / jnp.sum(e, axis=1, keepdims=True)
