"""TimeSeriesModel (ExponentialSmoothing) → JAX: closed-form forecasts.

Reference parity: JPMML-Evaluator scores TimeSeriesModel documents'
exponential-smoothing state (SURVEY.md §1 C1). The temporal state is in
the document (final level/trend + one period of seasonal factors); each
record carries the forecast horizon h (first active MiningField, integer
≥ 1, rounded), so scoring stays a pure batched function:

    ŷ(h) = level (+ h·trend | + trend·φ(1−φ^h)/(1−φ) for damped_trend)
                 (+ seasonal[(h−1) mod period]  |  × seasonal[…])

A missing horizon scores as an empty lane. φ^h lowers as exp(h·ln φ)
(φ ∈ (0,1) guaranteed by the parser), keeping the math branch-free.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from flink_jpmml_tpu.compile.common import Lowered, LowerCtx, ModelOutput
from flink_jpmml_tpu.pmml import ir


def lower_time_series(model: ir.TimeSeriesIR, ctx: LowerCtx) -> Lowered:
    col = ctx.column(model.horizon_field)
    s = model.smoothing
    params = {
        "level": np.float32(s.level),
        "trend": np.float32(s.trend),
    }
    if s.seasonal_type != "none":
        params["seasonal"] = np.asarray(s.seasonal, np.float32)
    trend_type = s.trend_type
    seasonal_type = s.seasonal_type
    period = s.period
    log_phi = math.log(s.phi) if trend_type == "damped_trend" else 0.0
    phi_scale = (
        s.phi / (1.0 - s.phi) if trend_type == "damped_trend" else 0.0
    )

    def fn(p, X, M):
        h = jnp.maximum(jnp.round(X[:, col]), 1.0)
        y = jnp.broadcast_to(p["level"], h.shape)
        if trend_type == "additive":
            y = y + h * p["trend"]
        elif trend_type == "damped_trend":
            phi_h = jnp.exp(h * log_phi)
            y = y + p["trend"] * phi_scale * (1.0 - phi_h)
        if seasonal_type != "none":
            idx = jnp.mod(h.astype(jnp.int32) - 1, period)
            factor = jnp.take(p["seasonal"], idx)
            y = y + factor if seasonal_type == "additive" else y * factor
        return ModelOutput(
            value=y.astype(jnp.float32), valid=~M[:, col]
        )

    return Lowered(fn=fn, params=params)
