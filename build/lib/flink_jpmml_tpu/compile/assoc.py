"""AssociationModel → JAX: rule firing as one 0/1 matmul + ranked pick.

Reference parity: JPMML-Evaluator scores AssociationModel documents
(SURVEY.md §1 C1) over transaction baskets. The streaming input contract
here is the fixed-width, TPU-native framing (see ir.AssociationIR): one
active MiningField per declared item, value > 0.5 ⇔ the item is in the
record's basket.

Lowering: with basket matrix Xb ∈ {0,1}^[B, I] and antecedent matrix
A ∈ {0,1}^[R, I], a rule fires iff Xb·Aᵀ equals the antecedent size —
subset testing as a single matmul. The per-criterion winner
(rule / recommendation / exclusiveRecommendation) needs the
consequent∩basket count, a second matmul against the consequent matrix.
Rules are pre-sorted host-side by (confidence desc, support desc,
document order); the device picks the first fired rule in that order
with one argmax. Prediction: value = winning rule's confidence,
label = its consequent (space-joined); no rule fired ⇒ empty lane.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from flink_jpmml_tpu.compile.common import (
    HIGHEST,
    Lowered,
    LowerCtx,
    ModelOutput,
)
from flink_jpmml_tpu.pmml import ir
from flink_jpmml_tpu.utils.exceptions import ModelCompilationException


def lower_association(model: ir.AssociationIR, ctx: LowerCtx) -> Lowered:
    items = model.items
    ipos = {v: i for i, v in enumerate(items)}
    cols = np.asarray([ctx.column(v) for v in items], np.int32)
    R, I = len(model.rules), len(items)

    A = np.zeros((R, I), np.float32)  # antecedent membership
    Cq = np.zeros((R, I), np.float32)  # consequent membership
    conf = np.zeros((R,), np.float32)
    for ri, r in enumerate(model.rules):
        for v in r.antecedent:
            A[ri, ipos[v]] = 1.0
        for v in r.consequent:
            Cq[ri, ipos[v]] = 1.0
        conf[ri] = r.confidence
    ante_n = A.sum(axis=1)
    cons_n = Cq.sum(axis=1)
    if (cons_n == 0).any():
        raise ModelCompilationException(
            "AssociationRule with an empty consequent"
        )

    # host-side ranking: fired rules are picked in this order on-device
    order = sorted(
        range(R),
        key=lambda i: (-model.rules[i].confidence, -model.rules[i].support, i),
    )
    order_a = np.asarray(order, np.int32)
    criterion = model.criterion
    if criterion not in ("rule", "recommendation", "exclusiveRecommendation"):
        raise ModelCompilationException(
            f"unsupported association criterion {criterion!r}"
        )

    params = {
        "A": A, "Cq": Cq,
        "ante_n": ante_n.astype(np.float32),
        "cons_n": cons_n.astype(np.float32),
        "conf": conf,
        "order": order_a,
    }
    labels = tuple(" ".join(r.consequent) for r in model.rules)

    def fn(p, X, M):
        B = X.shape[0]
        # missing item columns read as "not in basket" — a basket field
        # that was never observed cannot assert membership
        Xb = ((X[:, cols] > 0.5) & ~M[:, cols]).astype(jnp.float32)
        in_ante = jnp.matmul(Xb, p["A"].T, precision=HIGHEST)  # [B, R]
        fired = in_ante >= p["ante_n"][None, :] - 0.5
        if criterion != "recommendation":
            # JPMML-parity criteria: "rule" = whole rule in the basket;
            # "recommendation" = antecedent only; "exclusiveRecommendation"
            # (spec default) = antecedent in, consequent NOT fully in yet
            in_cons = jnp.matmul(Xb, p["Cq"].T, precision=HIGHEST)
            cons_in = in_cons >= p["cons_n"][None, :] - 0.5
            fired = fired & (cons_in if criterion == "rule" else ~cons_in)
        fired_sorted = jnp.take(fired, p["order"], axis=1)
        first = jnp.argmax(fired_sorted, axis=1)  # first True in rank order
        rule_idx = jnp.take(p["order"], first)
        valid = jnp.any(fired_sorted, axis=1)
        value = jnp.take(p["conf"], rule_idx)
        return ModelOutput(
            value=value.astype(jnp.float32),
            valid=valid,
            # fired mask in DOCUMENT order: the decode side ranks it with
            # the same static order to serve rank-k ruleValue fields
            probs=fired.astype(jnp.float32),
            label_idx=rule_idx.astype(jnp.int32),
        )

    return Lowered(fn=fn, params=params, labels=labels)
