"""PMML IR -> JAX lowering (SURVEY.md section 8 step 2): the heart of the framework."""

from flink_jpmml_tpu.compile.compiler import CompiledModel, compile_pmml  # noqa: F401
from flink_jpmml_tpu.compile.common import ModelOutput  # noqa: F401
