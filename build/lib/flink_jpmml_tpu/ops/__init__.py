"""TPU compute kernels (Pallas) backing the hot lowering paths."""
