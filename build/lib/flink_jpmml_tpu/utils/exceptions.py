"""Typed failure vocabulary for model load / validate / prepare / extract.

Reference parity: the reference's ``…/exceptions/`` package defines
``ModelLoadingException``, ``InputValidationException``,
``InputPreparationException`` and ``JPMMLExtractionException``
(SURVEY.md §3 row C1 [UNVERIFIED]).

Design difference from the reference: these exceptions are raised only on the
*cold* path (loading, parsing, compiling — where failing loudly is correct).
The *hot* path is total by construction (capability C5): per-record problems
become masked lanes → ``EmptyScore``, never exceptions, because raising from
inside a jitted function is impossible and per-record host checks would
reintroduce the per-record CPU cost the whole design removes.
"""

from __future__ import annotations


class FlinkJpmmlTpuError(Exception):
    """Base class for all framework errors."""


class ModelLoadingException(FlinkJpmmlTpuError):
    """The PMML document could not be read, parsed or version-gated."""


class UnsupportedPmmlVersionException(ModelLoadingException):
    """The document's PMML schema version is outside the supported 4.0–4.4."""


class ModelCompilationException(FlinkJpmmlTpuError):
    """The parsed PMML IR could not be lowered to a JAX computation."""


class InputValidationException(FlinkJpmmlTpuError):
    """Input arity / dtype does not match the model's active fields.

    Raised at *batch-construction* time (host side, cold shape checks only).
    Per-record value problems (NaNs, out-of-range) never raise — they mask.
    """


class InputPreparationException(FlinkJpmmlTpuError):
    """Field preparation (encoding, coercion) failed on the host side."""


class ExtractionException(FlinkJpmmlTpuError):
    """The model's target value could not be decoded from device output."""


class CheckpointException(FlinkJpmmlTpuError):
    """Writing or restoring a runtime checkpoint failed."""


class ModelVerificationException(ModelLoadingException):
    """The document's embedded ModelVerification records disagree with
    the compiled model's output — the model must not serve."""
