"""Cross-cutting utilities: exceptions, config, metrics, logging."""
