"""Metadata managers: pure application of control messages to the served map.

Reference parity: the ``…/api/managers/`` typeclass-style managers
(SURVEY.md §3 row C3 [UNVERIFIED]) — pure functions from (metadata, message)
to metadata, kept separate from the operator so they unit-test in isolation
(reference test strategy, SURVEY.md §5 "manager specs for Add/Del metadata
application").

Semantics:
- ``AddMessage`` is idempotent: re-adding a served (name, version) with the
  same path is a no-op; with a *different* path it is ignored (versions are
  immutable — publish a new version instead).
- ``DelMessage`` for an unknown model is a no-op.
"""

from __future__ import annotations

from typing import Dict, Tuple

from flink_jpmml_tpu.models.control import AddMessage, DelMessage, ServingMessage
from flink_jpmml_tpu.models.core import ModelId, ModelInfo

Metadata = Dict[ModelId, ModelInfo]


def apply_message(meta: Metadata, msg: ServingMessage) -> Tuple[Metadata, bool]:
    """→ (new metadata, changed?). Never mutates the input map."""
    if isinstance(msg, AddMessage):
        return add(meta, msg)
    if isinstance(msg, DelMessage):
        return delete(meta, msg)
    raise TypeError(f"not a serving message: {type(msg).__name__}")


def add(meta: Metadata, msg: AddMessage) -> Tuple[Metadata, bool]:
    mid = msg.model_id
    existing = meta.get(mid)
    if existing is not None:
        return meta, False  # versions are immutable
    out = dict(meta)
    out[mid] = ModelInfo(path=msg.path)
    return out, True


def delete(meta: Metadata, msg: DelMessage) -> Tuple[Metadata, bool]:
    mid = msg.model_id
    if mid not in meta:
        return meta, False
    out = dict(meta)
    del out[mid]
    return out, True


def latest_version(meta: Metadata, name: str) -> int:
    """Highest served version of ``name`` (−1 if none)."""
    versions = [mid.version for mid in meta if mid.name == name]
    return max(versions) if versions else -1
