"""Dynamic model serving (capability C6): registry, managers, control join."""

from flink_jpmml_tpu.serving.block import DynamicBlockPipeline  # noqa: F401
from flink_jpmml_tpu.serving.registry import ModelRegistry  # noqa: F401
from flink_jpmml_tpu.serving.scorer import DynamicScorer, default_route  # noqa: F401
