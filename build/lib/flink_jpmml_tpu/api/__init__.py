"""User-facing API surface (SURVEY.md section 8 step 6)."""

from flink_jpmml_tpu.api.reader import ModelReader, clear_model_cache  # noqa: F401
from flink_jpmml_tpu.api.stream import EvaluatedStream, Stream, StreamEnvironment  # noqa: F401
