"""Device mesh construction (SURVEY.md §8 step 4).

The reference's only parallelism is Flink data parallelism — N operator
subtasks with replicated models (SURVEY.md §3 P1). Our equivalent is a JAX
``Mesh`` over the TPU slice with two named axes:

- ``data``:  batch sharding (DP) — each device scores a slice of the
  micro-batch with replicated params; the padding batcher guarantees the
  batch divides evenly.
- ``model``: feature sharding (1-D TP) for wide linear/NN models
  (BASELINE config 5's 10k-dim sparse scorer) — weight columns split across
  devices, partials combined with ``psum`` over ICI.

``data × model`` must cover the devices exactly; the default is all-DP.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from flink_jpmml_tpu.utils.config import MeshConfig
from flink_jpmml_tpu.utils.exceptions import FlinkJpmmlTpuError

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    allow_subset: bool = False,
) -> Mesh:
    """Build the ``data × model`` mesh.

    ``data * model`` must equal the device count exactly — silently idling
    devices is a throughput bug, not a convenience; pass ``allow_subset=True``
    (or an explicit ``devices`` slice) to opt into a partial mesh.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if config is None:
        # all-DP over every visible device
        config = MeshConfig(data=len(devs), model=1)
    need = config.data * config.model
    if need > len(devs):
        raise FlinkJpmmlTpuError(
            f"mesh {config.data}x{config.model} needs {need} devices, "
            f"only {len(devs)} visible"
        )
    if need < len(devs) and not allow_subset:
        raise FlinkJpmmlTpuError(
            f"mesh {config.data}x{config.model} covers {need} of "
            f"{len(devs)} devices — the rest would sit idle; pass "
            "allow_subset=True (or an explicit devices list) if intentional"
        )
    grid = np.asarray(devs[:need]).reshape(config.data, config.model)
    return Mesh(grid, axis_names=(DATA_AXIS, MODEL_AXIS))
