"""Generated PMML fixtures (reference parity: ``flink-jpmml-assets``,
SURVEY.md §3 row D1). The reference shipped static ``.pmml`` resources; the
mount was empty, so we *generate* deterministic fixtures instead
(SURVEY.md §8 step 7)."""
