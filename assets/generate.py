"""Repo-layout shim: the generators live in the installable package
(flink_jpmml_tpu.assets_gen) so the wheel never ships a top-level
``assets`` module that could shadow another distribution's."""

from flink_jpmml_tpu.assets_gen import (  # noqa: F401
    gen_gbm,
    gen_iris_lr,
    gen_kmeans,
    gen_mlp,
    gen_negative,
    gen_stacked,
    generate_all,
)
