"""Deterministic per-key traffic assignment for canary splits.

The split must satisfy two properties the reference's atomic flip never
needed:

- **per-key stability** — every record with the same routing key takes
  the same side of the split, across processes, restarts, and replays
  (C7: a restored pipeline re-scores its uncommitted tail; those
  records must route exactly as they did the first time). So the
  assignment is a pure function of (name, candidate version, key) via
  :func:`~flink_jpmml_tpu.parallel.partitioner.stable_hash` — the same
  deterministic CRC the keyed-stream partitioner uses, never Python's
  seeded ``hash()``.
- **version-salted** — the hash is salted with the candidate version so
  consecutive rollouts of one name canary *different* key populations;
  a key that straddled the boundary once doesn't straddle it forever.

Records without an explicit key derive one from their content
(:func:`record_key`), which is equally replay-stable because the
replayed record's content is identical.
"""

from __future__ import annotations

from typing import Any

from flink_jpmml_tpu.parallel.partitioner import stable_hash

# granularity of the split: fractions quantize to 0.01% — fine enough
# for the bench drill's ±1% ratio assertion at modest record counts
_BUCKETS = 10_000
_CANARY_SALT = "fjt-canary"
_SHADOW_SALT = "fjt-shadow"


def record_key(payload: Any) -> Any:
    """Replay-stable routing key for an event payload.

    Dict records use their ``"_key"`` field when present (the explicit
    keyed-stream contract); otherwise the key is a canonicalized view of
    the content — sorted items for dicts, a tuple for vectors — so two
    replays of the same record always agree. Callers with real session/
    user keys should pass a ``key_fn`` instead of relying on content
    addressing (two users with identical features would share a lane).
    """
    if isinstance(payload, dict):
        if "_key" in payload:
            return str(payload["_key"])
        return tuple(
            (str(k), _scalar(v)) for k, v in sorted(payload.items())
        )
    if isinstance(payload, (list, tuple)):
        return tuple(_scalar(v) for v in payload)
    tolist = getattr(payload, "tolist", None)
    if tolist is not None:  # numpy vector
        return record_key(tolist())
    return _scalar(payload)


def _scalar(v: Any) -> Any:
    if isinstance(v, (str, bytes, bool, int, float)):
        return v
    if isinstance(v, (list, tuple)):
        return tuple(_scalar(x) for x in v)
    return repr(v)


def _bucket(salt: str, name: str, version: int, key: Any) -> int:
    return stable_hash((salt, name, version, record_key(key))) % _BUCKETS


def assign_candidate(
    name: str, candidate_version: int, fraction: float, key: Any
) -> bool:
    """True iff ``key`` routes to the candidate under a canary split of
    ``fraction`` — stable per key, monotone in ``fraction`` (growing the
    canary never reassigns a key already on the candidate back to the
    incumbent)."""
    return _bucket(_CANARY_SALT, name, candidate_version, key) < int(
        round(fraction * _BUCKETS)
    )


def sample_shadow(
    name: str, candidate_version: int, sample: float, key: Any
) -> bool:
    """True iff ``key``'s event is mirrored to the candidate for shadow
    diffing. Salted independently of :func:`assign_candidate` so the
    shadow sample is not just a prefix of the future canary population."""
    return _bucket(_SHADOW_SALT, name, candidate_version, key) < int(
        round(sample * _BUCKETS)
    )
