"""Rollout control plane: staged deployment for dynamically served models.

The reference system flips 100% of a name's traffic to a new version
the moment an ``AddMessage``'s document warms. This package interposes
a guarded lifecycle — shadow scoring, deterministic canary splits, and
guardrail-driven auto-promotion/rollback — on top of the existing
registry/control-stream machinery:

- :mod:`~flink_jpmml_tpu.rollout.state` — stages, guardrail specs, and
  the pure transition function shared by the registry and the fleet
  book (checkpoint-shaped: a restore mid-canary resumes the stage).
- :mod:`~flink_jpmml_tpu.rollout.split` — replay-stable per-key hash
  assignment (canary side + shadow sampling).
- :mod:`~flink_jpmml_tpu.rollout.controller` — the sliding-window
  guardrail loop that turns the PR 3 obs structs into promote/rollback
  decisions, locally or fleet-wide via the supervisor's heartbeat
  control channel.

Entry points: push a :class:`~flink_jpmml_tpu.models.control
.RolloutMessage` on the control stream (the ``fjt-rollout`` CLI writes
the wire form), and the :class:`~flink_jpmml_tpu.serving.scorer
.DynamicScorer` does the rest. See docs/operations.md §Rollouts.
"""

from flink_jpmml_tpu.rollout.controller import (
    RolloutBook,
    RolloutController,
    labelled,
)
from flink_jpmml_tpu.rollout.split import (
    assign_candidate,
    record_key,
    sample_shadow,
)
from flink_jpmml_tpu.rollout.state import (
    ACTIVE_STAGES,
    STAGE_CANARY,
    STAGE_FULL,
    STAGE_ROLLBACK,
    STAGE_SHADOW,
    STAGES,
    GuardrailSpec,
    RolloutState,
    apply_rollout,
)

__all__ = [
    "ACTIVE_STAGES",
    "GuardrailSpec",
    "RolloutBook",
    "RolloutController",
    "RolloutState",
    "STAGES",
    "STAGE_CANARY",
    "STAGE_FULL",
    "STAGE_ROLLBACK",
    "STAGE_SHADOW",
    "apply_rollout",
    "assign_candidate",
    "labelled",
    "record_key",
    "sample_shadow",
]
