"""Rollout lifecycle state: stages, guardrail specs, pure transitions.

The reference system's control stream flips traffic atomically on
``AddMessage`` — the newest served version takes 100% of events the
moment it warms. A staged rollout interposes a lifecycle between "the
candidate is registered" and "the candidate owns the traffic":

    shadow ──promote──▶ canary(p) ──promote──▶ full
       │                   │
       └────rollback───────┴──▶ candidate removed, incumbent keeps 100%

- **shadow** — the incumbent serves every event; the candidate scores a
  mirrored, sampled copy off the hot path and the outputs are diffed
  (disagreement rate, numeric drift). Nothing the candidate produces
  reaches a sink.
- **canary(p)** — a deterministic per-key hash fraction ``p`` of the
  traffic routes to the candidate; the incumbent serves the rest. The
  split is a pure function of (name, candidate version, record key), so
  a checkpoint replay routes every record identically.
- **full** — the rollout entry clears; the candidate is simply the
  newest served version (the reference's latest-wins routing resumes).
- **rollback** — the candidate is dropped from serving entirely; the
  incumbent keeps 100%. Terminal, like ``full``.

This module is deliberately leaf-level (stdlib only): the control
message (:mod:`flink_jpmml_tpu.models.control`), the registry, and the
guardrail controller all import it, in that order, without cycles. All
state is JSON-shaped for the checkpoint wire (C7): a restore mid-canary
resumes the same stage, fraction, and dwell clock instead of
re-flipping to full.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field, replace
from typing import Dict, Optional, Tuple

STAGE_SHADOW = "shadow"
STAGE_CANARY = "canary"
STAGE_FULL = "full"
STAGE_ROLLBACK = "rollback"

# stages a RolloutMessage may carry; shadow/canary keep an entry alive,
# full/rollback are the two terminal transitions
STAGES = (STAGE_SHADOW, STAGE_CANARY, STAGE_FULL, STAGE_ROLLBACK)
ACTIVE_STAGES = (STAGE_SHADOW, STAGE_CANARY)

# the next stage a healthy candidate promotes into
NEXT_STAGE = {STAGE_SHADOW: STAGE_CANARY, STAGE_CANARY: STAGE_FULL}


@dataclass(frozen=True)
class GuardrailSpec:
    """What "healthy" means for a candidate, and how fast to promote.

    All rates are over the controller's sliding ``window_s``; a verdict
    (either direction) requires at least ``min_samples`` observations of
    the relevant signal in the window — a guardrail must not fire, nor a
    promotion clear, on three records' worth of noise.
    """

    # rollback when shadow-diff disagreements exceed this rate
    max_disagree_rate: float = 0.02
    # rollback when candidate p99 latency exceeds incumbent p99 × this
    max_latency_ratio: float = 2.0
    # rollback when candidate dispatch/decode errors exceed this rate
    max_error_rate: float = 0.0
    # prediction-drift guardrails (obs/drift.py PSI of the candidate's
    # windowed score distribution against the incumbent's, both sides
    # past min_samples): above ``hold`` the controller withholds
    # promotion even after the dwell (the candidate keeps proving
    # itself); above ``max`` it rolls back. None disables each;
    # ``hold`` unset with ``max`` set defaults to half of ``max``.
    max_prediction_psi: Optional[float] = None
    hold_prediction_psi: Optional[float] = None
    # observations required in-window before any verdict counts
    min_samples: int = 100
    # healthy dwell at a stage before the controller promotes
    promote_after_s: float = 30.0
    # sliding evaluation window
    window_s: float = 10.0
    # traffic share the canary stage starts with
    canary_fraction: float = 0.1
    # fraction of incumbent traffic mirrored to the candidate for diffing
    shadow_sample: float = 1.0

    def __post_init__(self) -> None:
        if not (0.0 <= self.max_disagree_rate <= 1.0):
            raise ValueError(
                f"max_disagree_rate must be in [0, 1]: {self.max_disagree_rate}"
            )
        if self.max_latency_ratio <= 0:
            raise ValueError(
                f"max_latency_ratio must be > 0: {self.max_latency_ratio}"
            )
        if not (0.0 <= self.max_error_rate <= 1.0):
            raise ValueError(
                f"max_error_rate must be in [0, 1]: {self.max_error_rate}"
            )
        for f_name in ("max_prediction_psi", "hold_prediction_psi"):
            v = getattr(self, f_name)
            if v is not None and v <= 0:
                raise ValueError(f"{f_name} must be > 0: {v}")
        if (
            self.max_prediction_psi is not None
            and self.hold_prediction_psi is not None
            and self.hold_prediction_psi > self.max_prediction_psi
        ):
            raise ValueError(
                "hold_prediction_psi must not exceed max_prediction_psi: "
                f"{self.hold_prediction_psi} > {self.max_prediction_psi}"
            )
        if self.min_samples < 1:
            raise ValueError(f"min_samples must be >= 1: {self.min_samples}")
        if not (0.0 < self.canary_fraction <= 1.0):
            raise ValueError(
                f"canary_fraction must be in (0, 1]: {self.canary_fraction}"
            )
        if not (0.0 < self.shadow_sample <= 1.0):
            raise ValueError(
                f"shadow_sample must be in (0, 1]: {self.shadow_sample}"
            )

    @property
    def effective_hold_psi(self) -> Optional[float]:
        """The promotion-hold threshold actually enforced: the explicit
        ``hold_prediction_psi``, else half the rollback threshold when
        only ``max_prediction_psi`` is set, else None (disabled)."""
        if self.hold_prediction_psi is not None:
            return self.hold_prediction_psi
        if self.max_prediction_psi is not None:
            return self.max_prediction_psi / 2.0
        return None

    def as_dict(self) -> dict:
        out = {
            "max_disagree_rate": self.max_disagree_rate,
            "max_latency_ratio": self.max_latency_ratio,
            "max_error_rate": self.max_error_rate,
            "min_samples": self.min_samples,
            "promote_after_s": self.promote_after_s,
            "window_s": self.window_s,
            "canary_fraction": self.canary_fraction,
            "shadow_sample": self.shadow_sample,
        }
        # absent unless configured: the wire form (checkpoints, control
        # frames) stays byte-compatible with pre-drift readers
        if self.max_prediction_psi is not None:
            out["max_prediction_psi"] = self.max_prediction_psi
        if self.hold_prediction_psi is not None:
            out["hold_prediction_psi"] = self.hold_prediction_psi
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "GuardrailSpec":
        base = cls()
        kw = {}
        for f_name, conv in (
            ("max_disagree_rate", float),
            ("max_latency_ratio", float),
            ("max_error_rate", float),
            ("min_samples", int),
            ("promote_after_s", float),
            ("window_s", float),
            ("canary_fraction", float),
            ("shadow_sample", float),
            ("max_prediction_psi", float),
            ("hold_prediction_psi", float),
        ):
            if f_name in d and d[f_name] is not None:
                kw[f_name] = conv(d[f_name])
        return replace(base, **kw)


@dataclass(frozen=True)
class RolloutState:
    """One name's in-progress rollout (absent = normal latest-wins).

    ``stage_since`` is wall-clock (``time.time()``) so the promotion
    dwell survives checkpoint/restore across processes; a restore
    mid-canary therefore resumes the dwell, it does not restart it.
    """

    name: str
    candidate_version: int
    stage: str
    fraction: float
    spec: GuardrailSpec = field(default_factory=GuardrailSpec)
    stage_since: float = 0.0

    def __post_init__(self) -> None:
        if self.stage not in ACTIVE_STAGES:
            raise ValueError(
                f"a stored rollout stage must be one of {ACTIVE_STAGES}: "
                f"{self.stage!r}"
            )
        if not (0.0 < self.fraction <= 1.0):
            raise ValueError(
                f"rollout fraction must be in (0, 1]: {self.fraction}"
            )

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "candidate_version": self.candidate_version,
            "stage": self.stage,
            "fraction": self.fraction,
            "spec": self.spec.as_dict(),
            "stage_since": self.stage_since,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "RolloutState":
        return cls(
            name=str(d["name"]),
            candidate_version=int(d["candidate_version"]),
            stage=str(d["stage"]),
            fraction=float(d["fraction"]),
            spec=GuardrailSpec.from_dict(d.get("spec") or {}),
            stage_since=float(d.get("stage_since", 0.0)),
        )


def apply_rollout(
    states: Dict[str, RolloutState], msg
) -> Tuple[Dict[str, RolloutState], bool]:
    """Pure transition: (rollout map, RolloutMessage) → (new map, changed).

    Shared by the registry (which adds the serving-metadata side
    effects) and the supervisor-side fleet book, so local and fleet
    rollout state machines cannot drift. Never mutates the input.

    Semantics:
    - ``shadow``/``canary`` upsert the entry. A stage *change* resets the
      dwell clock; re-sending the current stage updates fraction/spec in
      place (dwell preserved) — the knob-turn case.
    - ``full``/``rollback`` drop the entry (terminal). A terminal message
      for a version that is not the tracked candidate is a no-op: a
      replayed decision must not cancel a newer rollout.
    """
    cur = states.get(msg.name)
    if msg.stage in ACTIVE_STAGES:
        spec = msg.guardrails or (
            cur.spec if cur is not None and cur.candidate_version == msg.version
            else GuardrailSpec()
        )
        if msg.fraction is not None:
            fraction = msg.fraction
        elif msg.stage == STAGE_CANARY:
            fraction = spec.canary_fraction
        else:
            fraction = 1.0  # shadow mirrors per spec.shadow_sample, not this
        same = (
            cur is not None
            and cur.candidate_version == msg.version
            and cur.stage == msg.stage
        )
        new = RolloutState(
            name=msg.name,
            candidate_version=msg.version,
            stage=msg.stage,
            fraction=fraction,
            spec=spec,
            stage_since=(
                cur.stage_since if same else (msg.timestamp or time.time())
            ),
        )
        if cur == new:
            return states, False
        out = dict(states)
        out[msg.name] = new
        return out, True
    # terminal: full / rollback
    if cur is None or cur.candidate_version != msg.version:
        return states, False
    out = dict(states)
    del out[msg.name]
    return out, True


def incumbent_version(
    served_versions, state: Optional[RolloutState]
) -> int:
    """Newest served version excluding an active rollout's candidate
    (−1 if none): the version latest-wins routing should serve while
    the candidate is still proving itself."""
    cand = state.candidate_version if state is not None else None
    versions = [v for v in served_versions if v != cand]
    return max(versions) if versions else -1
