"""Guardrail controller: the loop that makes the obs plane *actuate*.

PR 3's observability plane produces mergeable per-worker measurement
structs (``MetricsRegistry.struct_snapshot``) and an exact fleet merge
(``merge_structs``) — the map-style measure / reduce-style aggregate
pattern. This module closes the loop: a :class:`RolloutController`
consumes those structs over a sliding window and automatically
promotes (shadow → canary → full) or rolls back every active rollout,
emitting each decision to the flight recorder and the ``rollout_*``
metric family.

The controller is deliberately agnostic about WHOSE structs it reads
and WHERE its decisions land:

- **local** (the default): bound to a :class:`ModelRegistry` and the
  scorer's own registry of metrics — decisions apply in-process. The
  :class:`~flink_jpmml_tpu.serving.scorer.DynamicScorer` ticks it from
  the batch loop, so actuation happens between micro-batches on the
  serving thread: no lock dance with routing, no extra thread.
- **fleet**: bound to a :class:`RolloutBook` whose ``apply`` broadcasts
  the decision through the supervisor's heartbeat control channel
  (``Supervisor.broadcast_rollout``) and whose metrics come from
  ``Supervisor.fleet_metrics()`` — one guardrail verdict, every worker
  converges. Run it on a thread via :meth:`start`.

Guardrails evaluated per active rollout, each over the trailing
``spec.window_s`` and only past ``spec.min_samples`` observations:

- **disagreement** — shadow-diff disagreements / comparisons;
- **latency** — candidate p99 vs incumbent p99 of the per-dispatch
  rollout latency histograms (mergeable, so the fleet p99 is exact);
- **errors** — candidate dispatch/decode failures per attempt.

A violation rolls back immediately. A candidate that is healthy, has
met the sample floor, and has dwelt at its stage ``promote_after_s``
is promoted one stage. ``stage_since`` rides the checkpoint, so a
restore mid-canary resumes the dwell rather than restarting it.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from flink_jpmml_tpu.obs import drift as drift_mod
from flink_jpmml_tpu.obs import recorder as flight
from flink_jpmml_tpu.rollout.state import (
    NEXT_STAGE,
    STAGE_CANARY,
    STAGE_FULL,
    STAGE_ROLLBACK,
    STAGE_SHADOW,
    RolloutState,
    apply_rollout,
)
from flink_jpmml_tpu.utils.metrics import Histogram, MetricsRegistry

# numeric stage levels for the rollout_stage gauge (dashboards can
# threshold/graph them); 0 = no rollout active
STAGE_LEVEL = {STAGE_SHADOW: 1.0, STAGE_CANARY: 2.0, STAGE_FULL: 3.0}

_NAMED = re.compile(r'^(?P<base>[a-zA-Z0-9_]+)\{model="(?P<name>[^"]*)"\}$')


def _make_message(name: str, version: int, stage: str, timestamp: float):
    # deferred: models.control imports rollout.state at module load, so
    # importing it here at module level would be circular
    from flink_jpmml_tpu.models.control import RolloutMessage

    return RolloutMessage(
        name=name, version=version, stage=stage, timestamp=timestamp
    )


def labelled(base: str, name: str) -> str:
    """The registry-name convention for per-model rollout series:
    ``rollout_x{model="name"}`` — the obs server renders the suffix as a
    real Prometheus label (cf. ``kafka_lag{partition="..."}``)."""
    return f'{base}{{model="{name}"}}'


def labelled_role(base: str, name: str, role: str) -> str:
    """Two-label variant for the per-role score-distribution sketches
    (``rollout_score_dist{model=...,role=...}`` — the order the scorer
    registers them in)."""
    return f'{base}{{model="{name}",role="{role}"}}'


def _named_values(section: dict, base: str) -> Dict[str, float]:
    """→ {model name: value} for every ``base{model="..."}`` entry."""
    out: Dict[str, float] = {}
    if not isinstance(section, dict):
        return out
    for raw, v in section.items():
        m = _NAMED.match(raw)
        if m and m.group("base") == base:
            try:
                out[m.group("name")] = float(v)
            except (TypeError, ValueError):
                continue
    return out


def _counter_delta(new: dict, old: Optional[dict], key: str) -> float:
    nc = (new.get("counters") or {}) if isinstance(new, dict) else {}
    oc = (old.get("counters") or {}) if isinstance(old, dict) else {}
    try:
        d = float(nc.get(key, 0.0)) - float(oc.get(key, 0.0))
    except (TypeError, ValueError):
        return 0.0
    # a restarted worker resets its counters; a negative window means the
    # baseline frame is from a previous incarnation — fall back to the
    # cumulative value rather than reporting impossible negatives
    return d if d >= 0 else float(nc.get(key, 0.0))


def _sketch_window(new: dict, old: Optional[dict], key: str):
    """The observation window's score-distribution sketch (newest
    cumulative state minus the baseline frame — the
    ``drift.sketch_window`` delta, with the same worker-restart
    cumulative fallback as :func:`_hist_window`)."""
    ns = (new.get("sketches") or {}).get(key) if isinstance(new, dict) else None
    os_ = (old.get("sketches") or {}).get(key) if isinstance(old, dict) else None
    return drift_mod.sketch_window(ns, os_)


def _hist_window(new: dict, old: Optional[dict], key: str) -> Optional[Histogram]:
    """The observation window's histogram: newest state minus the
    baseline frame's bucket counts (buckets ADD, so they subtract too).
    None when the window holds no observations or the states don't
    parse; a bucket going backwards (worker restart) falls back to the
    cumulative histogram."""
    nh = (new.get("histograms") or {}).get(key) if isinstance(new, dict) else None
    if not isinstance(nh, dict):
        return None
    oh = (old.get("histograms") or {}).get(key) if isinstance(old, dict) else None
    try:
        if not isinstance(oh, dict) or oh.get("layout") != nh.get("layout"):
            h = Histogram.from_state(nh)
            return h if h.count() > 0 else None
        counts = {k: int(v) for k, v in (nh.get("counts") or {}).items()}
        for k, v in (oh.get("counts") or {}).items():
            counts[k] = counts.get(k, 0) - int(v)
        if any(v < 0 for v in counts.values()):
            h = Histogram.from_state(nh)
            return h if h.count() > 0 else None
        n = int(nh.get("n", 0)) - int(oh.get("n", 0))
        if n <= 0:
            return None
        return Histogram.from_state({
            "layout": nh["layout"],
            "counts": {k: v for k, v in counts.items() if v},
            "sum": float(nh.get("sum", 0.0)) - float(oh.get("sum", 0.0)),
            "n": n,
            # the window max is unknowable from cumulative states; the
            # cumulative max is a safe upper clamp for quantiles
            "max": float(nh.get("max", 0.0)),
        })
    except (KeyError, IndexError, TypeError, ValueError):
        return None


class RolloutBook:
    """Registry-less rollout state book (the supervisor/fleet side).

    Tracks stages with the same pure transitions the registry uses
    (``rollout/state.py apply_rollout``) and hands every applied message
    to ``forward`` — ``Supervisor.broadcast_rollout`` in the fleet
    wiring — so the book's view and the fleet's converge on the same
    message stream."""

    def __init__(self, forward: Callable[..., None]):
        self._forward = forward
        self._mu = threading.Lock()
        self._states: Dict[str, RolloutState] = {}

    def rollouts(self) -> Dict[str, RolloutState]:
        with self._mu:
            return dict(self._states)

    def apply(self, msg) -> bool:
        with self._mu:
            self._states, changed = apply_rollout(self._states, msg)
        # forward even a no-op transition: a worker that missed earlier
        # frames must still converge on the current stage
        self._forward(msg)
        return changed


class RolloutController:
    """Sliding-window guardrail evaluation + promote/rollback actuation.

    ``book`` needs ``rollouts() -> {name: RolloutState}`` and
    ``apply(RolloutMessage)`` — a :class:`ModelRegistry` or a
    :class:`RolloutBook`. ``struct_fn`` yields the cumulative metrics
    struct to window over (a registry's ``struct_snapshot`` or a
    supervisor's ``fleet_metrics``). ``metrics`` receives the decision
    counters and stage gauges (pass the same registry the scorer uses so
    one scrape shows signals and verdicts together)."""

    def __init__(
        self,
        book,
        struct_fn: Callable[[], dict],
        metrics: Optional[MetricsRegistry] = None,
        interval_s: float = 0.5,
        clock: Callable[[], float] = time.time,
    ):
        self._book = book
        self._struct_fn = struct_fn
        self.metrics = metrics or MetricsRegistry()
        self._interval = interval_s
        self._clock = clock
        self._frames: List[Tuple[float, dict]] = []  # (t, cumulative struct)
        self._last_tick = 0.0
        self._mu = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- ticking -----------------------------------------------------------

    def maybe_tick(self) -> List[dict]:
        """Rate-limited :meth:`tick` — the batch-loop piggyback entry
        point (cheap no-op between intervals and with no active
        rollouts)."""
        now = self._clock()
        if now - self._last_tick < self._interval:
            return []
        return self.tick(now)

    def tick(self, now: Optional[float] = None) -> List[dict]:
        """Evaluate every active rollout once; → the decisions taken
        (each ``{"name", "action", "stage", "reason", ...}``)."""
        now = self._clock() if now is None else now
        with self._mu:
            self._last_tick = now
            rollouts = self._book.rollouts()
            if not rollouts:
                # idle: drop any stale window so (a) no per-tick struct
                # snapshot keeps burning the serving thread forever and
                # (b) the next rollout starts a fresh baseline
                self._frames.clear()
                return []
            # gauges BEFORE evaluation: _actuate writes the terminal
            # level for promoted/rolled-back names, and the entry is
            # gone from the book afterwards — a post-decision sweep over
            # this (pre-decision) snapshot would resurrect stale stages
            self._set_stage_gauges(rollouts)
            struct = self._struct_fn()
            self._frames.append((now, struct))
            # keep exactly one frame older than every window (the
            # baseline); specs may differ per rollout, so prune to the
            # widest active window
            widest = max(
                st.spec.window_s for st in rollouts.values()
            )
            while (
                len(self._frames) >= 2
                and self._frames[1][0] <= now - widest
            ):
                self._frames.pop(0)
            old = self._frames[0][1] if len(self._frames) >= 2 else None
            decisions = []
            for name, st in sorted(rollouts.items()):
                d = self._evaluate(name, st, struct, old, now)
                if d is not None:
                    decisions.append(d)
        return decisions

    def _set_stage_gauges(self, rollouts: Dict[str, RolloutState]) -> None:
        for name, st in rollouts.items():
            # literal f-string names keep tools/metrics_lint.py able to
            # see the emission sites (same below for the decision counters)
            self.metrics.gauge(f'rollout_stage{{model="{name}"}}').set(
                STAGE_LEVEL.get(st.stage, 0.0)
            )

    # -- evaluation --------------------------------------------------------

    def _evaluate(
        self, name: str, st: RolloutState, new: dict,
        old: Optional[dict], now: float,
    ) -> Optional[dict]:
        spec = st.spec
        compared = _counter_delta(
            new, old, labelled("rollout_shadow_compared", name)
        )
        disagree = _counter_delta(
            new, old, labelled("rollout_shadow_disagree", name)
        )
        cand_records = _counter_delta(
            new, old, labelled("rollout_candidate_records", name)
        )
        errors = _counter_delta(
            new, old, labelled("rollout_candidate_errors", name)
        )
        ch = _hist_window(
            new, old, labelled("rollout_candidate_latency_s", name)
        )
        ih = _hist_window(
            new, old, labelled("rollout_incumbent_latency_s", name)
        )
        stats = {
            "compared": compared, "disagree": disagree,
            "candidate_records": cand_records, "errors": errors,
        }

        reason = None
        if compared >= spec.min_samples:
            rate = disagree / compared
            stats["disagree_rate"] = rate
            if rate > spec.max_disagree_rate:
                reason = (
                    f"disagreement rate {rate:.4f} > "
                    f"{spec.max_disagree_rate:.4f}"
                )
        attempts = cand_records + compared + errors
        if reason is None and attempts >= spec.min_samples and errors > 0:
            rate = errors / attempts
            stats["error_rate"] = rate
            if rate > spec.max_error_rate:
                reason = (
                    f"error rate {rate:.4f} > {spec.max_error_rate:.4f}"
                )
        if (
            reason is None
            and ch is not None and ih is not None
            and ch.count() >= spec.min_samples
            and ih.count() >= spec.min_samples
        ):
            cp99, ip99 = ch.quantile(0.99), ih.quantile(0.99)
            if cp99 is not None and ip99 is not None and ip99 > 0:
                stats["candidate_p99_s"] = cp99
                stats["incumbent_p99_s"] = ip99
                if cp99 > spec.max_latency_ratio * ip99:
                    reason = (
                        f"candidate p99 {cp99 * 1e3:.2f}ms > "
                        f"{spec.max_latency_ratio:g}x incumbent "
                        f"{ip99 * 1e3:.2f}ms"
                    )
        # prediction drift (the data-plane guardrail, obs/drift.py):
        # PSI of the candidate's windowed score distribution against
        # the incumbent's — a candidate can agree record-by-record
        # within tolerance yet shift the score DISTRIBUTION your
        # downstream thresholds were calibrated on
        hold_psi = spec.effective_hold_psi
        pred_psi = None
        if hold_psi is not None or spec.max_prediction_psi is not None:
            cw = _sketch_window(
                new, old,
                labelled_role("rollout_score_dist", name, "candidate"),
            )
            iw = _sketch_window(
                new, old,
                labelled_role("rollout_score_dist", name, "incumbent"),
            )
            if (
                cw is not None and iw is not None
                and cw.count() >= spec.min_samples
                and iw.count() >= spec.min_samples
            ):
                pred_psi = drift_mod.psi(iw, cw)
            if pred_psi is not None:
                stats["prediction_psi"] = pred_psi
                self.metrics.gauge(
                    f'rollout_prediction_psi{{model="{name}"}}'
                ).set(round(pred_psi, 4))
                if (
                    reason is None
                    and spec.max_prediction_psi is not None
                    and pred_psi > spec.max_prediction_psi
                ):
                    reason = (
                        f"prediction PSI {pred_psi:.4f} > "
                        f"{spec.max_prediction_psi:.4f}"
                    )
        if reason is not None:
            return self._actuate(
                name, st, STAGE_ROLLBACK, reason, stats, now
            )

        # promotion: healthy + sample floor met this window + dwelt long
        # enough at the current stage; a prediction PSI above the hold
        # threshold withholds promotion (the candidate keeps serving its
        # current stage until the drift subsides or crosses max)
        floor = compared if st.stage == STAGE_SHADOW else cand_records
        if (
            floor >= spec.min_samples
            and now - st.stage_since >= spec.promote_after_s
        ):
            if (
                hold_psi is not None
                and pred_psi is not None
                and pred_psi > hold_psi
            ):
                flight.record(
                    "rollout_promotion_held", model=name,
                    version=st.candidate_version, stage=st.stage,
                    prediction_psi=round(pred_psi, 4),
                    hold_threshold=hold_psi,
                )
                return None
            return self._actuate(
                name, st, NEXT_STAGE[st.stage],
                f"healthy for {now - st.stage_since:.1f}s", stats, now,
            )
        return None

    # -- actuation ---------------------------------------------------------

    def _actuate(
        self, name: str, st: RolloutState, stage: str,
        reason: str, stats: dict, now: float,
    ) -> dict:
        msg = _make_message(name, st.candidate_version, stage, now)
        self._book.apply(msg)
        action = "rollback" if stage == STAGE_ROLLBACK else "promote"
        if action == "rollback":
            self.metrics.counter(f'rollout_rollbacks{{model="{name}"}}').inc()
        else:
            self.metrics.counter(f'rollout_promotions{{model="{name}"}}').inc()
        if stage in (STAGE_ROLLBACK, STAGE_FULL):
            self.metrics.gauge(f'rollout_stage{{model="{name}"}}').set(
                STAGE_LEVEL[STAGE_FULL] if stage == STAGE_FULL else 0.0
            )
        decision = {
            "name": name, "version": st.candidate_version,
            "action": action, "from_stage": st.stage, "stage": stage,
            "reason": reason, **stats,
        }
        # every decision is a flight-recorder event: the postmortem
        # question after a surprise rollback is always "why"
        flight.record(f"rollout_{action}", **decision)
        return decision

    def promote(self, name: str) -> Optional[dict]:
        """Manual promotion by one stage (the operator override)."""
        st = self._book.rollouts().get(name)
        if st is None:
            return None
        return self._actuate(
            name, st, NEXT_STAGE[st.stage], "manual promote", {},
            self._clock(),
        )

    def rollback(self, name: str, reason: str = "manual") -> Optional[dict]:
        """Manual rollback (the operator override)."""
        st = self._book.rollouts().get(name)
        if st is None:
            return None
        return self._actuate(
            name, st, STAGE_ROLLBACK, reason, {}, self._clock()
        )

    # -- thread mode (fleet controllers) -----------------------------------

    def start(self) -> "RolloutController":
        """Tick on a daemon thread every ``interval_s`` (for controllers
        with no batch loop to piggyback on, e.g. the supervisor's fleet
        controller); idempotent."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def _loop():
            while not self._stop.wait(self._interval):
                try:
                    self.tick()
                except Exception:
                    # a guardrail evaluation crash must not silently end
                    # supervision of every other rollout
                    flight.record("rollout_controller_error")

        self._thread = threading.Thread(
            target=_loop, name="fjt-rollout-ctl", daemon=True
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
