"""``fjt-score``: score a PMML document over a CSV/JSONL file from the
shell — the quickest "switching user" path from a model file to
predictions, no code required.

    fjt-score model.pmml records.csv            # CSV with a header row
    fjt-score model.pmml records.jsonl -o out.jsonl
    cat records.jsonl | fjt-score model.pmml - --format jsonl

Input: CSV (header row names the fields; empty cells = missing) or
JSONL (one record object per line); ``-`` reads stdin. Output: one JSON
object per input record —

    {"value": 1.25, "label": "versicolor", "probs": {...}}
    {"empty": true}                                 # invalid lane (C5)

The hot path is the same compiled scorer the streaming runtime uses
(`ModelReader.load()` → ``score_records`` in batches); this is a
convenience frontend, not a second engine.
"""

from __future__ import annotations

import argparse
import csv
import json
import sys
from typing import Any, Dict, Iterator, List, Optional, TextIO


def _records_csv(f: TextIO, codec_fields) -> Iterator[Dict[str, Any]]:
    reader = csv.DictReader(f)
    for row in reader:
        rec: Dict[str, Any] = {}
        for k, v in row.items():
            if k is None or v is None or v == "":
                continue  # absent cell = missing value
            if k in codec_fields:
                # categorical: the raw string must ride the codec — a
                # numeric-looking category ("2") float-parsed here would
                # bypass it and alias onto a wrong category code
                rec[k] = v
                continue
            try:
                rec[k] = float(v)
            except ValueError:
                rec[k] = v
        yield rec


def _records_jsonl(f: TextIO) -> Iterator[Dict[str, Any]]:
    for i, line in enumerate(f, 1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            raise SystemExit(f"input line {i}: invalid JSON ({e})")
        if not isinstance(rec, dict):
            raise SystemExit(f"input line {i}: expected an object")
        yield rec


def _pred_json(pred) -> Dict[str, Any]:
    if pred.is_empty:
        return {"empty": True}
    out: Dict[str, Any] = {"value": pred.score.value}
    if pred.target is not None:
        if pred.target.label is not None:
            out["label"] = pred.target.label
        if pred.target.probabilities:
            out["probs"] = {
                k: round(float(v), 6)
                for k, v in pred.target.probabilities.items()
            }
    if pred.outputs:
        out["outputs"] = {k: v for k, v in pred.outputs.items()}
    return out


def score_main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="fjt-score",
        description="Score a PMML document over CSV/JSONL records.",
    )
    ap.add_argument("model", help="PMML path or URI (any ModelReader scheme)")
    ap.add_argument("input", help="records file (.csv / .jsonl) or - for stdin")
    ap.add_argument("-o", "--output", default="-",
                    help="output JSONL path (default stdout)")
    ap.add_argument("--format", choices=("auto", "csv", "jsonl"),
                    default="auto")
    ap.add_argument("--batch", type=int, default=4096,
                    help="records per scoring dispatch")
    ap.add_argument("--replace-nan", type=float, default=None,
                    help="replace missing/NaN inputs with this value")
    ap.add_argument("--platform", default=None,
                    help="force the jax platform (e.g. cpu) before init; "
                         "without it the default backend initializes "
                         "under a 60s wedge watchdog (FJT_PLATFORM "
                         "honored)")
    args = ap.parse_args(argv)

    from flink_jpmml_tpu.utils.demo import resolve_backend

    # same demo-safe bootstrap as the examples: a wedged TPU tunnel
    # re-execs this process onto CPU instead of hanging a no-code user
    resolve_backend(args.platform, argv_rest=argv)

    from flink_jpmml_tpu.api import ModelReader

    fmt = args.format
    if fmt == "auto":
        if args.input == "-":
            fmt = "jsonl"
        elif args.input.lower().endswith(".csv"):
            fmt = "csv"
        else:
            fmt = "jsonl"

    cm = ModelReader(args.model).load(batch_size=args.batch)

    try:
        fin = sys.stdin if args.input == "-" else open(
            args.input, "r", encoding="utf-8"
        )
    except OSError as e:
        raise SystemExit(f"cannot read {args.input!r}: {e}")
    try:
        fout = sys.stdout if args.output == "-" else open(
            args.output, "w", encoding="utf-8"
        )
    except OSError as e:
        if fin is not sys.stdin:
            fin.close()
        raise SystemExit(f"cannot write {args.output!r}: {e}")
    n = 0
    try:
        records = (
            _records_csv(fin, set(cm.field_space.codecs))
            if fmt == "csv"
            else _records_jsonl(fin)
        )
        # --replace-nan fills missing/NaN NUMERIC active fields (the
        # reference's replaceNan option); categorical fields keep the
        # missing-value semantics their codecs define
        numeric_fields = [
            f for f in cm.field_space.fields
            if f not in cm.field_space.codecs
        ]

        def fill(rec: Dict[str, Any]) -> Dict[str, Any]:
            if args.replace_nan is None:
                return rec
            for f in numeric_fields:
                v = rec.get(f)
                if v is None or (isinstance(v, float) and v != v):
                    rec[f] = args.replace_nan
            return rec

        batch: List[Dict[str, Any]] = []

        def flush() -> None:
            nonlocal n
            if not batch:
                return
            preds = cm.score_records(batch)
            for p in preds:
                fout.write(json.dumps(_pred_json(p)) + "\n")
            n += len(batch)
            batch.clear()

        for rec in records:
            batch.append(fill(rec))
            if len(batch) >= args.batch:
                flush()
        flush()
    finally:
        if fin is not sys.stdin:
            fin.close()
        if fout is not sys.stdout:
            fout.close()
    print(f"scored {n} records", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(score_main())
