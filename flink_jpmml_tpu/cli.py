"""``fjt-score``: score a PMML document over a CSV/JSONL file from the
shell — the quickest "switching user" path from a model file to
predictions, no code required.

    fjt-score model.pmml records.csv            # CSV with a header row
    fjt-score model.pmml records.jsonl -o out.jsonl
    cat records.jsonl | fjt-score model.pmml - --format jsonl

Input: CSV (header row names the fields; empty cells = missing) or
JSONL (one record object per line); ``-`` reads stdin. Output: one JSON
object per input record —

    {"value": 1.25, "label": "versicolor", "probs": {...}}
    {"empty": true}                                 # invalid lane (C5)

The hot path is the same compiled scorer the streaming runtime uses
(`ModelReader.load()` → ``score_records`` in batches); this is a
convenience frontend, not a second engine.

``fjt-rollout``: drive staged rollouts from the shell by appending
control frames (models/control.py wire form) to a JSONL control file a
pipeline tails as its control stream (``JsonlFileSource(path,
follow=True)`` → ``with_control_stream``; the dynamic scorer decodes
wire dicts natively). The manual promote/rollback recipe — see
docs/operations.md §Rollouts:

    fjt-rollout ctrl.jsonl shadow   --name m --version 2 --path v2.pmml
    fjt-rollout ctrl.jsonl canary   --name m --version 2 --fraction 0.1
    fjt-rollout ctrl.jsonl full     --name m --version 2   # promote
    fjt-rollout ctrl.jsonl rollback --name m --version 2   # abort

``fjt-top``: render the latency-attribution plane (obs/attr.py) as a
ranked table — per-stage p50/p99/total share, live device occupancy,
top exemplars — from a running pipeline's ``/varz`` endpoint or a
struct dump (a ``/varz`` JSON file or a ``BENCH_*.json`` artifact).
Turns "the chip is 94% idle" into the ordered list of which stage to
attack next. No jax import — safe on any host:

    fjt-top http://127.0.0.1:9100          # live /varz scrape
    fjt-top BENCH_r06.json                 # bench artifact's varz
    fjt-top /tmp/varz-dump.json
    fjt-top --overload http://host:9100    # admission/deadline panel
    fjt-top --drift http://host:9100       # per-feature data-health panel

``fjt-drift``: the data-drift baseline registry (obs/drift.py) —
snapshot a live pipeline's per-feature profiles as the reference,
list what's stored, or check a source against it:

    fjt-drift snapshot http://127.0.0.1:9100
    fjt-drift check http://127.0.0.1:9100   # exit 1 past --psi

``fjt-trace``: reconstruct one record's causal journey (obs/trace.py)
as an ordered timeline by merging journey rows + flight events + DLQ
envelopes + trace-id'd spans across ALL worker incarnations — from a
dump directory (journey store / flight dumps / DLQ / span files,
scanned recursively), a live ``/trace`` endpoint (journeys + flight +
the active span file's trace-id'd events; DLQ envelopes ride only the
directory scan — the store's own ``dlq`` hops carry the quarantine
either way), or a BENCH artifact:

    fjt-trace /data/ckpt --grep offset=1374   # who touched record 1374?
    fjt-trace http://127.0.0.1:9100 --slowest 5
    fjt-trace BENCH_r13.json --id 3fa1…       # the fjt-top exemplar pivot

``fjt-replay``: retrospective incident replay from the durable
telemetry history (obs/history.py) — a per-window timeline (records,
shed, pressure, offered vs capacity, headroom) plus any fjt-top panel
rendered over the merged range, reconstructed from on-disk frames
alone, so it works after every involved process is dead:

    fjt-replay /data/history --last 600 --step 15
    fjt-replay http://127.0.0.1:9100 --panel zoo
    fjt-replay /data/history --source _fleet --panel overload
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import sys
from typing import Any, Dict, Iterator, List, Optional, TextIO


def _records_csv(f: TextIO, codec_fields) -> Iterator[Dict[str, Any]]:
    reader = csv.DictReader(f)
    for row in reader:
        rec: Dict[str, Any] = {}
        for k, v in row.items():
            if k is None or v is None or v == "":
                continue  # absent cell = missing value
            if k in codec_fields:
                # categorical: the raw string must ride the codec — a
                # numeric-looking category ("2") float-parsed here would
                # bypass it and alias onto a wrong category code
                rec[k] = v
                continue
            try:
                rec[k] = float(v)
            except ValueError:
                rec[k] = v
        yield rec


def _records_jsonl(f: TextIO) -> Iterator[Dict[str, Any]]:
    for i, line in enumerate(f, 1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            raise SystemExit(f"input line {i}: invalid JSON ({e})")
        if not isinstance(rec, dict):
            raise SystemExit(f"input line {i}: expected an object")
        yield rec


def _pred_json(pred) -> Dict[str, Any]:
    if pred.is_empty:
        return {"empty": True}
    out: Dict[str, Any] = {"value": pred.score.value}
    if pred.target is not None:
        if pred.target.label is not None:
            out["label"] = pred.target.label
        if pred.target.probabilities:
            out["probs"] = {
                k: round(float(v), 6)
                for k, v in pred.target.probabilities.items()
            }
    if pred.outputs:
        out["outputs"] = {k: v for k, v in pred.outputs.items()}
    return out


def score_main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="fjt-score",
        description="Score a PMML document over CSV/JSONL records.",
    )
    ap.add_argument("model", help="PMML path or URI (any ModelReader scheme)")
    ap.add_argument("input", help="records file (.csv / .jsonl) or - for stdin")
    ap.add_argument("-o", "--output", default="-",
                    help="output JSONL path (default stdout)")
    ap.add_argument("--format", choices=("auto", "csv", "jsonl"),
                    default="auto")
    ap.add_argument("--batch", type=int, default=4096,
                    help="records per scoring dispatch")
    ap.add_argument("--replace-nan", type=float, default=None,
                    help="replace missing/NaN inputs with this value")
    ap.add_argument("--platform", default=None,
                    help="force the jax platform (e.g. cpu) before init; "
                         "without it the default backend initializes "
                         "under a 60s wedge watchdog (FJT_PLATFORM "
                         "honored)")
    args = ap.parse_args(argv)

    from flink_jpmml_tpu.utils.demo import resolve_backend

    # same demo-safe bootstrap as the examples: a wedged TPU tunnel
    # re-execs this process onto CPU instead of hanging a no-code user
    resolve_backend(args.platform, argv_rest=argv)

    from flink_jpmml_tpu.api import ModelReader

    fmt = args.format
    if fmt == "auto":
        if args.input == "-":
            fmt = "jsonl"
        elif args.input.lower().endswith(".csv"):
            fmt = "csv"
        else:
            fmt = "jsonl"

    cm = ModelReader(args.model).load(batch_size=args.batch)

    try:
        fin = sys.stdin if args.input == "-" else open(
            args.input, "r", encoding="utf-8"
        )
    except OSError as e:
        raise SystemExit(f"cannot read {args.input!r}: {e}")
    try:
        fout = sys.stdout if args.output == "-" else open(
            args.output, "w", encoding="utf-8"
        )
    except OSError as e:
        if fin is not sys.stdin:
            fin.close()
        raise SystemExit(f"cannot write {args.output!r}: {e}")
    n = 0
    try:
        records = (
            _records_csv(fin, set(cm.field_space.codecs))
            if fmt == "csv"
            else _records_jsonl(fin)
        )
        # --replace-nan fills missing/NaN NUMERIC active fields (the
        # reference's replaceNan option); categorical fields keep the
        # missing-value semantics their codecs define
        numeric_fields = [
            f for f in cm.field_space.fields
            if f not in cm.field_space.codecs
        ]

        def fill(rec: Dict[str, Any]) -> Dict[str, Any]:
            if args.replace_nan is None:
                return rec
            for f in numeric_fields:
                v = rec.get(f)
                if v is None or (isinstance(v, float) and v != v):
                    rec[f] = args.replace_nan
            return rec

        batch: List[Dict[str, Any]] = []

        def flush() -> None:
            nonlocal n
            if not batch:
                return
            preds = cm.score_records(batch)
            for p in preds:
                fout.write(json.dumps(_pred_json(p)) + "\n")
            n += len(batch)
            batch.clear()

        for rec in records:
            batch.append(fill(rec))
            if len(batch) >= args.batch:
                flush()
        flush()
    finally:
        if fin is not sys.stdin:
            fin.close()
        if fout is not sys.stdout:
            fout.close()
    print(f"scored {n} records", file=sys.stderr)
    return 0


def rollout_main(argv: Optional[List[str]] = None) -> int:
    """``fjt-rollout``: append one staged-rollout control frame to a
    JSONL control file (no jax import — safe on any host)."""
    ap = argparse.ArgumentParser(
        prog="fjt-rollout",
        description="Stage, promote, or roll back a served-model rollout "
                    "by appending a control frame to a JSONL control file.",
    )
    ap.add_argument("control_file",
                    help="JSONL control file the pipeline tails "
                         "(JsonlFileSource(follow=True) as its control "
                         "stream)")
    ap.add_argument("stage",
                    choices=("shadow", "canary", "full", "rollback"),
                    help="target stage: shadow/canary start or advance a "
                         "rollout; full promotes; rollback aborts")
    ap.add_argument("--name", required=True, help="served model name")
    ap.add_argument("--version", type=int, required=True,
                    help="candidate version")
    ap.add_argument("--path", default=None,
                    help="candidate PMML path/URI (registers it in the "
                         "same message; required unless already served)")
    ap.add_argument("--fraction", type=float, default=None,
                    help="canary traffic share (default: the guardrail "
                         "spec's canary_fraction)")
    g = ap.add_argument_group("guardrails (any flag builds a spec; "
                              "unset fields keep the defaults)")
    g.add_argument("--max-disagree-rate", type=float, default=None)
    g.add_argument("--max-latency-ratio", type=float, default=None)
    g.add_argument("--max-error-rate", type=float, default=None)
    g.add_argument("--max-prediction-psi", type=float, default=None,
                   help="roll back when the candidate's windowed score "
                        "distribution drifts past this PSI vs the "
                        "incumbent (obs/drift.py)")
    g.add_argument("--hold-prediction-psi", type=float, default=None,
                   help="withhold promotion while prediction PSI "
                        "exceeds this (default: half of "
                        "--max-prediction-psi)")
    g.add_argument("--min-samples", type=int, default=None)
    g.add_argument("--promote-after-s", type=float, default=None)
    g.add_argument("--window-s", type=float, default=None)
    g.add_argument("--shadow-sample", type=float, default=None)
    args = ap.parse_args(argv)

    import time

    from flink_jpmml_tpu.models.control import RolloutMessage, to_wire
    from flink_jpmml_tpu.rollout.state import GuardrailSpec

    guard_kw = {
        k: v for k, v in (
            ("max_disagree_rate", args.max_disagree_rate),
            ("max_latency_ratio", args.max_latency_ratio),
            ("max_error_rate", args.max_error_rate),
            ("max_prediction_psi", args.max_prediction_psi),
            ("hold_prediction_psi", args.hold_prediction_psi),
            ("min_samples", args.min_samples),
            ("promote_after_s", args.promote_after_s),
            ("window_s", args.window_s),
            ("shadow_sample", args.shadow_sample),
        ) if v is not None
    }
    try:
        msg = RolloutMessage(
            name=args.name, version=args.version, stage=args.stage,
            timestamp=time.time(), path=args.path,
            fraction=args.fraction,
            guardrails=(
                GuardrailSpec.from_dict(guard_kw) if guard_kw else None
            ),
        )
    except ValueError as e:
        raise SystemExit(f"invalid rollout message: {e}")
    try:
        with open(args.control_file, "a", encoding="utf-8") as f:
            f.write(json.dumps(to_wire(msg)) + "\n")
    except OSError as e:
        raise SystemExit(f"cannot append to {args.control_file!r}: {e}")
    print(
        f"queued {args.stage} for {args.name}_{args.version} on "
        f"{args.control_file}",
        file=sys.stderr,
    )
    return 0


def _top_load(source: str) -> Dict[str, dict]:
    """→ {label: metrics struct} from a /varz URL, a /varz JSON dump,
    or a BENCH artifact (its embedded ``varz`` structs, per mode)."""
    if source.startswith(("http://", "https://")):
        import urllib.error
        import urllib.request

        url = source.rstrip("/")
        if not url.endswith("/varz"):
            url += "/varz"
        try:
            with urllib.request.urlopen(url, timeout=10) as r:
                payload = json.loads(r.read().decode())
        except (urllib.error.URLError, OSError,
                json.JSONDecodeError) as e:
            raise SystemExit(f"cannot read {url!r}: {e}")
    else:
        try:
            with open(source, encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise SystemExit(f"cannot read {source!r}: {e}")
    if not isinstance(payload, dict):
        raise SystemExit(f"{source!r} is not a JSON object")
    if isinstance(payload.get("parsed"), dict):
        payload = payload["parsed"]  # the bench driver's artifact wrap
    if "histograms" in payload or "counters" in payload:
        return {"": payload}  # a bare struct dump
    out: Dict[str, dict] = {}
    if isinstance(payload.get("varz"), dict):
        out[""] = payload["varz"]  # a bench artifact's top-level mode
    for k, v in payload.items():
        if k == "varz" and "" in out:
            continue  # the headline struct, already the aggregate
        if isinstance(v, dict):
            if "histograms" in v or "counters" in v:
                out[str(k)] = v  # a /varz {label: struct} mapping
            elif isinstance(v.get("varz"), dict):
                out[str(k)] = v["varz"]  # bench sub-modes (latency/kafka)
    if not out:
        raise SystemExit(f"no metrics structs found in {source!r}")
    return out


def _top_render(label: str, struct: dict, out, source: str = None) -> None:
    from flink_jpmml_tpu.obs import attr

    title = label or "aggregate"
    print(f"== {title} ==", file=out)
    gauges = struct.get("gauges") or {}

    def g(name):
        v = gauges.get(name)
        return v.get("value") if isinstance(v, dict) else None

    mfu, membw = g("device_mfu"), g("device_membw_util")
    nsrec, flops = g("device_ns_per_record"), g("flops_per_record")
    if any(x is not None for x in (mfu, membw, nsrec)):
        parts = []
        if mfu is not None:
            parts.append(f"mfu {100.0 * mfu:6.2f}%")
        if membw is not None:
            parts.append(f"membw {100.0 * membw:6.2f}%")
        if nsrec is not None:
            parts.append(f"{nsrec:,.0f} ns/rec (device, sampled)")
        if flops is not None:
            parts.append(f"{flops:,.0f} flops/rec")
        print("device   " + "   ".join(parts), file=out)
    slo_ok = g("slo_ok")
    if slo_ok is not None:
        burns = ", ".join(
            f"{k.split('=', 1)[1].strip(chr(34) + '}')}s: "
            f"{v['value']:.2f}x"
            for k, v in sorted(gauges.items())
            if k.startswith("slo_burn_rate{") and isinstance(v, dict)
        )
        state = "OK" if slo_ok else "BREACHED"
        print(f"slo      {state}" + (f"   burn [{burns}]" if burns else ""),
              file=out)
    summary = attr.summary(struct)
    if summary is None:
        print("(no stage attribution recorded)", file=out)
        return
    print(
        f"{'stage':<14}{'thread':<10}{'batches':>9}{'p50 ms':>10}"
        f"{'p99 ms':>10}{'total ms':>12}{'share':>8}",
        file=out,
    )
    ranked = sorted(
        summary.items(), key=lambda kv: kv[1]["total_ms"], reverse=True
    )
    for stage, row in ranked:
        # decode-thread stages vs hot-path stages (obs/attr.py): with
        # pipelined ingest armed, "ingest" time runs on the prefetch
        # sidecar and overlaps scoring — only "score"/"ring-feed" rows
        # steal from the hot path
        thread = attr.STAGE_THREADS.get(stage, "-")
        print(
            f"{stage:<14}{thread:<10}{row['n']:>9}{row['p50_ms']:>10.3f}"
            f"{row['p99_ms']:>10.3f}{row['total_ms']:>12.3f}"
            f"{100.0 * row['share']:>7.1f}%",
            file=out,
        )
    # top exemplars: the tail batches a p99 scrape would link to
    exemplars = []
    for name, hstate in (struct.get("histograms") or {}).items():
        for ex in (hstate.get("exemplars") or {}).values():
            try:
                exemplars.append((float(ex[1]), str(ex[0]), name))
            except (IndexError, TypeError, ValueError):
                continue
    if exemplars:
        exemplars.sort(reverse=True)
        print("exemplars (worst observed per bucket):", file=out)
        # the attribution→journey pivot: an exemplar captured under an
        # active journey context carries the journey's trace id, so the
        # printed invocation reconstructs that record's whole timeline
        src = source if source is not None else "<journey-source>"
        for v, tid, name in exemplars[:5]:
            print(
                f"  {1000.0 * v:10.3f} ms  trace_id={tid}  {name}",
                file=out,
            )
            print(f"      ↳ fjt-trace {src} --id {tid}", file=out)


def _top_render_freshness(label: str, struct: dict, out) -> None:
    """The ``--freshness`` panel: event-time watermark lag and kafka
    lag per partition (with observation age), record staleness
    quantiles, drain forecast, and the composite pressure score —
    obs/freshness.py + obs/pressure.py rendered as one operator view."""
    import re as _re

    from flink_jpmml_tpu.utils.metrics import Histogram

    title = label or "aggregate"
    print(f"== {title} · freshness ==", file=out)
    gauges = struct.get("gauges") or {}
    counters = struct.get("counters") or {}

    def g(name):
        v = gauges.get(name)
        return v.get("value") if isinstance(v, dict) else None

    rendered = False
    p = g("pressure")
    if p is not None:
        rendered = True
        comps = "  ".join(
            f"{k.split('_', 1)[1]} {g(k):.2f}"
            for k in ("pressure_ring", "pressure_window", "pressure_wait")
            if g(k) is not None
        )
        breaches = counters.get("pressure_breaches", 0)
        print(
            f"pressure {p:5.2f}   [{comps}]   breaches {breaches:.0f}",
            file=out,
        )
    eta, trend = g("lag_drain_eta_s"), g("lag_trend")
    if eta is not None or trend is not None:
        rendered = True
        diverging = bool(g("lag_diverging"))
        eta_s = (
            "DIVERGING" if diverging
            else ("-" if eta is None else f"{eta:,.1f}s")
        )
        print(
            f"drain    eta {eta_s}   trend "
            f"{trend if trend is not None else 0:+,.1f} rec/s "
            "(+ = falling behind)",
            file=out,
        )
    hstate = (struct.get("histograms") or {}).get("record_staleness_s")
    if isinstance(hstate, dict):
        try:
            h = Histogram.from_state(hstate)
            if h.count():
                rendered = True
                print(
                    f"stale    p50 {1000.0 * (h.quantile(0.5) or 0):,.1f} ms"
                    f"   p99 {1000.0 * (h.quantile(0.99) or 0):,.1f} ms"
                    f"   n {h.count()}",
                    file=out,
                )
        except (KeyError, TypeError, ValueError):
            pass
    wm = g("watermark_ts")
    if wm is not None:
        rendered = True
        import datetime

        ts = datetime.datetime.fromtimestamp(
            wm, datetime.timezone.utc
        ).strftime("%H:%M:%S.%f")[:-3]
        print(f"watermark sink low-watermark {ts}Z", file=out)
    # per-partition table, keyed across the three labelled families
    pat = _re.compile(
        r'^(watermark_lag_s|kafka_lag|kafka_lag_age_s)'
        r'\{partition="([^"]+)"\}$'
    )
    parts: Dict[str, Dict[str, float]] = {}
    for name, v in gauges.items():
        m = pat.match(name)
        if m and isinstance(v, dict):
            parts.setdefault(m.group(2), {})[m.group(1)] = v["value"]
    if parts:
        rendered = True
        print(
            f"{'partition':<12}{'wm lag s':>10}{'kafka lag':>12}"
            f"{'obs age s':>11}",
            file=out,
        )
        for part in sorted(parts):
            row = parts[part]

            def cell(key, fmt):
                v = row.get(key)
                return "-" if v is None else format(v, fmt)

            print(
                f"{part:<12}{cell('watermark_lag_s', '.3f'):>10}"
                f"{cell('kafka_lag', ',.0f'):>12}"
                f"{cell('kafka_lag_age_s', '.1f'):>11}",
                file=out,
            )
    if not rendered:
        # nothing above actually printed (an eagerly-registered but
        # empty staleness histogram is not telemetry)
        print("(no freshness telemetry recorded)", file=out)


def _top_render_drift(label: str, struct: dict, out) -> None:
    """The ``--drift`` panel: the data-health plane (obs/drift.py) as a
    ranked per-feature table — live-vs-baseline PSI, missing and
    out-of-domain rates, sketch sample counts, alarm markers — plus the
    per-model prediction-distribution drift line. Rows rank worst
    first: the feature to investigate is the top one."""
    from flink_jpmml_tpu.obs import drift as drift_mod

    title = label or "aggregate"
    print(f"== {title} · drift ==", file=out)
    s = drift_mod.summary(struct)
    counters = struct.get("counters") or {}
    if not s:
        print("(no drift telemetry recorded — set FJT_DRIFT_SAMPLE "
              "and snapshot a baseline with fjt-drift)", file=out)
        return
    alarms = counters.get("drift_alarms", 0)
    if alarms:
        print(f"alarms   {alarms:.0f} raised (see drift_alarm flight "
              "events)", file=out)
    for model in sorted(s):
        m = s[model]
        pred = m.get("prediction_psi")
        head = f"model {model}"
        if pred is not None:
            mark = " [ALARM]" if m.get("prediction_alarmed") else ""
            head += f"   prediction drift PSI {pred:.4f}{mark}"
        print(head, file=out)
        rows = m.get("features") or {}
        if not rows:
            continue
        print(
            f"{'feature':<20}{'psi':>9}{'missing':>9}{'unseen':>9}"
            f"{'n':>10}  alarm",
            file=out,
        )
        ranked = sorted(
            rows.items(),
            key=lambda kv: (
                kv[1]["psi"] if kv[1]["psi"] is not None else -1.0
            ),
            reverse=True,
        )
        for name, row in ranked:
            def cell(key, fmt):
                v = row.get(key)
                return "-" if v is None else format(v, fmt)

            print(
                f"{name:<20}{cell('psi', '.4f'):>9}"
                f"{cell('missing_rate', '.2%'):>9}"
                f"{cell('unseen_rate', '.2%'):>9}"
                f"{cell('n', ',.0f'):>10}"
                f"  {'ALARM' if row.get('alarmed') else '-'}",
                file=out,
            )


def _top_render_overload(label: str, struct: dict, out) -> None:
    """The ``--overload`` panel: the admission/adaptive-batching plane
    (serving/overload.py) as one operator view — deadline vs live p99,
    the chosen dispatch size, shed level + per-lane shed counts, and
    the pressure signal the controller sheds on."""
    from flink_jpmml_tpu.serving import overload as overload_mod

    title = label or "aggregate"
    print(f"== {title} · overload ==", file=out)
    gauges = struct.get("gauges") or {}

    def g(name):
        v = gauges.get(name)
        return v.get("value") if isinstance(v, dict) else None

    s = overload_mod.summary(struct) or {}
    rendered = False
    deadline = s.get("deadline_ms")
    if deadline:
        rendered = True
        p99 = s.get("p99_ms")
        ratio = s.get("p99_vs_deadline_ratio")
        verdict = (
            "-" if ratio is None
            else ("MET" if ratio <= 1.0 else "BREACHED")
        )
        line = f"deadline {deadline:,.1f} ms   {verdict}"
        if p99 is not None:
            line += (
                f"   p99 {p99:,.1f} ms ({ratio:.2f}x, "
                f"{s.get('latency_source')})"
            )
        print(line, file=out)
    batch = s.get("adaptive_batch")
    if batch is not None:
        rendered = True
        print(f"batch    {batch:,.0f} records/dispatch (adaptive cap)",
              file=out)
    p = g("pressure")
    if p is not None:
        rendered = True
        print(f"pressure {p:5.2f}", file=out)
    level = s.get("shed_level")
    admitted = s.get("admitted_records")
    shed = s.get("shed_records") or {}
    if level is not None or admitted is not None or shed:
        rendered = True
        total_shed = sum(shed.values())
        print(
            f"admission level {level if level is not None else 0:.0f}   "
            f"admitted {admitted or 0:,.0f}   shed {total_shed:,.0f}",
            file=out,
        )
        if shed:
            print(f"{'lane':<12}{'shed records':>14}", file=out)
            for lane in sorted(shed):
                print(f"{lane:<12}{shed[lane]:>14,.0f}", file=out)
    backoff = g("reconnect_backoff_s")
    if backoff:
        rendered = True
        print(f"backoff  {backoff:,.3f}s (retry streak in progress)",
              file=out)
    if not rendered:
        print("(no overload telemetry recorded)", file=out)


def _top_render_failover(label: str, struct: dict, out,
                         source: str = None) -> None:
    """The ``--failover`` panel: the device-fault resilience plane
    (runtime/devfault.py + serving/failover.py) as one operator view —
    circuit state per served model, the fallback tier's share of
    delivered records, redispatch/OOM-shrink counts, the device-fault
    taxonomy totals, and the checkpoint-suspension flag. The last
    device error itself rides the rate-limited ``device_fault`` flight
    event with the journey's trace id — the printed ``fjt-trace``
    invocation is the pivot."""
    from flink_jpmml_tpu.serving import failover as failover_mod

    title = label or "aggregate"
    print(f"== {title} · failover ==", file=out)
    s = failover_mod.summary(struct) or {}
    rendered = False
    states = s.get("states") or {}
    if states:
        rendered = True
        print(f"{'model':<24}{'circuit':>10}", file=out)
        for model in sorted(states):
            print(f"{model:<24}{states[model]:>10}", file=out)
    share = s.get("fallback_share")
    fb = s.get("fallback_records")
    if fb:
        rendered = True
        line = f"fallback   {fb:,.0f} records"
        if share is not None:
            line += f" ({100.0 * share:.2f}% of delivered)"
        print(line, file=out)
    rd = s.get("redispatch_records")
    if rd:
        rendered = True
        print(f"redispatch {rd:,.0f} records", file=out)
    oo = s.get("oom_shrinks")
    if oo:
        rendered = True
        print(f"oom-shrink {oo:,.0f} batch-size bisections", file=out)
    faults_by_kind = s.get("device_faults") or {}
    if faults_by_kind:
        rendered = True
        print(f"{'fault kind':<24}{'observed':>10}", file=out)
        for kind in sorted(faults_by_kind):
            print(f"{kind:<24}{faults_by_kind[kind]:>10,.0f}", file=out)
    if s.get("checkpoint_suspended"):
        rendered = True
        print("checkpoint plane SUSPENDED (disk full — replay window "
              "widening)", file=out)
    if s.get("mesh_lost_devices"):
        rendered = True
        print(f"mesh: {s['mesh_lost_devices']:.0f} chip(s) lost "
              "(degraded-mesh mode)", file=out)
    if not rendered:
        print("(no failover telemetry recorded)", file=out)
    elif source:
        # the trace pivot: device_fault flight events carry trace ids
        print(f"pivot: fjt-trace {source} --id <trace_id>   "
              "(ids ride device_fault flight events)", file=out)


def _top_render_mesh(label: str, struct: dict, out) -> None:
    """The ``--mesh`` panel: per-chip serving telemetry (obs/mesh.py)
    as one operator view — rec/s, cumulative records, in-flight window
    depth, and health state per chip, plus the surviving data-axis
    width and the degraded-mesh rebuild count. On a fleet struct the
    per-chip counters arrive SUM-merged and ``mesh_data_width``
    MIN-merged (the most-degraded worker), per the catalogue rules."""
    from flink_jpmml_tpu.obs import mesh as mesh_mod

    title = label or "aggregate"
    print(f"== {title} · mesh ==", file=out)
    s = mesh_mod.summary(struct)
    if not s:
        print("(no mesh telemetry recorded — single-chip serving)",
              file=out)
        return
    print(f"{'chip':<10}{'rec/s':>12}{'records':>14}{'in-flight':>11}"
          f"{'state':>10}", file=out)
    for chip, v in s["chips"].items():
        rate = v.get("rec_per_s")
        print(
            f"{chip:<10}"
            f"{(f'{rate:,.0f}' if rate is not None else '-'):>12}"
            f"{v['records']:>14,.0f}"
            f"{v['inflight']:>11,.0f}"
            f"{v['state']:>10}",
            file=out,
        )
    width = s.get("data_width")
    if width is not None:
        print(f"data width {width:.0f} surviving chip(s)", file=out)
    if s.get("rebuilds"):
        print(f"rebuilds   {s['rebuilds']:,.0f} degraded-mesh "
              "rebuild(s)", file=out)
    if s.get("lost_devices"):
        print(f"lost       {s['lost_devices']:.0f} device(s) retired "
              "(degraded-mesh mode)", file=out)


def _top_render_zoo(label: str, struct: dict, out) -> None:
    """The ``--zoo`` panel: multi-tenant packed-serving telemetry
    (serving/zoo.py + the per-tenant families) as one operator view —
    pack dispatch/occupancy/pad-waste, warm-pool and cold-start
    economics, and the per-tenant table ranked by delivered records
    with shed counts and latency quantiles. On a fleet struct the
    counters arrive SUM-merged, ``pack_occupancy`` MIN-merged (the
    worst-filled worker) and ``pack_pad_waste`` MAX-merged (the most
    wasteful), per the catalogue rules."""
    import re as _re

    from flink_jpmml_tpu.utils.metrics import Histogram

    title = label or "aggregate"
    print(f"== {title} · zoo ==", file=out)
    gauges = struct.get("gauges") or {}
    counters = struct.get("counters") or {}
    hists = struct.get("histograms") or {}

    def g(name):
        v = gauges.get(name)
        return v.get("value") if isinstance(v, dict) else None

    def hq(name, q):
        hstate = hists.get(name)
        if not isinstance(hstate, dict):
            return None
        try:
            h = Histogram.from_state(hstate)
            return h.quantile(q) if h.count() else None
        except (KeyError, TypeError, ValueError):
            return None

    rendered = False
    disp = counters.get("pack_dispatches", 0)
    occ, waste = g("pack_occupancy"), g("pack_pad_waste")
    res = g("zoo_resident_bytes")
    if disp or occ is not None or res is not None:
        rendered = True
        parts = [f"dispatches {disp:,.0f}"]
        if occ is not None:
            parts.append(f"occupancy {100.0 * occ:.1f}%")
        if waste is not None:
            parts.append(f"pad-waste {100.0 * waste:.1f}%")
        if res is not None:
            parts.append(f"resident {res / 1e6:,.1f} MB")
        print("packs    " + "   ".join(parts), file=out)
    hits = counters.get("warm_pool_hits", 0)
    miss = counters.get("warm_pool_misses", 0)
    evict = counters.get("zoo_evictions", 0)
    if hits or miss or evict:
        rendered = True
        line = (f"warm     hits {hits:,.0f}   misses {miss:,.0f}"
                f"   evictions {evict:,.0f}")
        p50, p99 = hq("cold_start_s", 0.5), hq("cold_start_s", 0.99)
        if p50 is not None:
            line += (f"   cold-start p50 {1000.0 * p50:,.1f} ms"
                     f"  p99 {1000.0 * (p99 or p50):,.1f} ms")
        print(line, file=out)
    # per-tenant table: the three {model=*} families joined on label
    pat = _re.compile(
        r'^(tenant_records|tenant_shed_records)\{model="([^"]+)"\}$'
    )
    tenants: Dict[str, Dict[str, float]] = {}
    for name, v in counters.items():
        m = pat.match(name)
        if m:
            tenants.setdefault(m.group(2), {})[m.group(1)] = float(v)
    if tenants:
        rendered = True
        print(
            f"{'tenant':<24}{'records':>12}{'shed':>9}{'p50 ms':>10}"
            f"{'p99 ms':>10}",
            file=out,
        )
        ranked = sorted(
            tenants.items(),
            key=lambda kv: kv[1].get("tenant_records", 0.0),
            reverse=True,
        )
        for tenant, row in ranked[:20]:
            lname = f'tenant_latency_s{{model="{tenant}"}}'
            p50, p99 = hq(lname, 0.5), hq(lname, 0.99)
            print(
                f"{tenant:<24}"
                f"{row.get('tenant_records', 0.0):>12,.0f}"
                f"{row.get('tenant_shed_records', 0.0):>9,.0f}"
                f"{(f'{1000.0 * p50:,.2f}' if p50 is not None else '-'):>10}"
                f"{(f'{1000.0 * p99:,.2f}' if p99 is not None else '-'):>10}",
                file=out,
            )
        if len(ranked) > 20:
            print(f"... and {len(ranked) - 20} more tenant(s)", file=out)
    if not rendered:
        print("(no zoo telemetry recorded — single-tenant serving or "
              "zoo mode off)", file=out)


def _top_render_state(label: str, struct: dict, out) -> None:
    """The ``--state`` panel: the keyed session-state plane
    (runtime/state.py) as one operator view — table occupancy and hit
    ratio, routing outcome counts (hits / inserts / evictions /
    collisions / overflow), and the correctness counters (bypassed
    replays, rollbacks). Empty-by-default: a pipeline without a state
    table registers nothing, and the panel says so instead of
    rendering a wall of zeros. On a fleet struct ``state_*`` counters
    and ``state_resident_keys`` arrive SUM-merged,
    ``state_occupancy_frac`` MAX-merged (the fullest table) and
    ``state_hit_ratio`` MIN-merged (the coldest), per the catalogue
    rules."""
    title = label or "aggregate"
    print(f"== {title} · state ==", file=out)
    gauges = struct.get("gauges") or {}
    counters = struct.get("counters") or {}

    def g(name):
        v = gauges.get(name)
        return v.get("value") if isinstance(v, dict) else None

    def c(name):
        try:
            return float(counters.get(name, 0) or 0)
        except (TypeError, ValueError):
            return 0.0

    resident, occ = g("state_resident_keys"), g("state_occupancy_frac")
    hit_ratio = g("state_hit_ratio")
    records = c("state_records")
    rendered = False
    if resident is not None or records:
        rendered = True
        parts = []
        if resident is not None:
            parts.append(f"resident {resident:,.0f} keys")
        if occ is not None:
            parts.append(f"occupancy {100.0 * occ:.1f}%")
        if hit_ratio is not None:
            parts.append(f"hit-ratio {100.0 * hit_ratio:.1f}%")
        print("table    " + "   ".join(parts), file=out)
        print(
            f"routing  records {records:,.0f}   hits "
            f"{c('state_hits'):,.0f}   inserts "
            f"{c('state_inserts'):,.0f}   evictions "
            f"{c('state_evictions'):,.0f}   collisions "
            f"{c('state_collisions'):,.0f}   overflow "
            f"{c('state_overflow'):,.0f}",
            file=out,
        )
    bypassed, rollbacks = c("state_bypass_records"), c("state_rollbacks")
    if bypassed or rollbacks:
        rendered = True
        print(
            f"safety   bypassed replays {bypassed:,.0f}   rollbacks "
            f"{rollbacks:,.0f}",
            file=out,
        )
    if not rendered:
        print("(no keyed-state telemetry recorded — state plane "
              "unarmed)", file=out)


def top_main(argv: Optional[List[str]] = None) -> int:
    """``fjt-top``: the fleet attribution table (see module docstring).
    Renders every labelled source (the supervisor's /varz serves the
    aggregate under ``""`` plus one struct per worker); ``--worker``
    narrows to one label."""
    ap = argparse.ArgumentParser(
        prog="fjt-top",
        description="Render per-stage latency attribution, live device "
                    "occupancy, and top exemplars from /varz or a "
                    "struct dump.",
    )
    ap.add_argument("source",
                    help="obs-server base URL (or /varz URL), a /varz "
                         "JSON dump, or a BENCH_*.json artifact")
    ap.add_argument("--worker", default=None,
                    help="render only this source label "
                         "(default: all, aggregate first)")
    ap.add_argument("--freshness", action="store_true",
                    help="render the freshness/backpressure panel "
                         "(event-time watermark lag, staleness, drain "
                         "forecast, pressure) instead of the stage table")
    ap.add_argument("--overload", action="store_true",
                    help="render the overload/admission panel (deadline "
                         "vs p99, adaptive batch, shed level + per-lane "
                         "shed counts) instead of the stage table")
    ap.add_argument("--drift", action="store_true",
                    help="render the data-drift panel (per-feature "
                         "live-vs-baseline PSI ranked worst-first, "
                         "missing/out-of-domain rates, prediction "
                         "drift, alarms) instead of the stage table")
    ap.add_argument("--failover", action="store_true",
                    help="render the device-fault/failover panel "
                         "(circuit state per model, fallback-tier "
                         "share, redispatch/OOM-shrink counts, device "
                         "fault taxonomy, checkpoint suspension) "
                         "instead of the stage table")
    ap.add_argument("--mesh", action="store_true",
                    help="render the multichip panel (per-chip rec/s, "
                         "in-flight depth, health state, surviving "
                         "data width, degraded-mesh rebuilds) instead "
                         "of the stage table")
    ap.add_argument("--zoo", action="store_true",
                    help="render the multi-tenant zoo panel (pack "
                         "dispatch/occupancy/pad-waste, warm-pool and "
                         "cold-start economics, per-tenant records/"
                         "shed/latency ranked by traffic) instead of "
                         "the stage table")
    ap.add_argument("--state", action="store_true",
                    help="render the keyed session-state panel (table "
                         "occupancy/hit-ratio, routing outcome counts, "
                         "bypassed replays and rollbacks) instead of "
                         "the stage table")
    ap.add_argument("--watch", type=float, default=None, metavar="N",
                    help="re-render every N seconds from a live source "
                         "(operator console mode; mid-watch fetch "
                         "failures retry instead of exiting)")
    args = ap.parse_args(argv)
    if args.watch is not None and args.watch <= 0:
        raise SystemExit(f"--watch must be > 0, got {args.watch}")
    if sum((args.freshness, args.overload, args.drift,
            args.failover, args.mesh, args.zoo, args.state)) > 1:
        raise SystemExit(
            "--freshness, --overload, --drift, --failover, --mesh, "
            "--zoo, and --state are exclusive"
        )
    render = (
        _top_render_freshness if args.freshness
        else _top_render_overload if args.overload
        else _top_render_drift if args.drift
        else _top_render_mesh if args.mesh
        else _top_render_zoo if args.zoo
        else _top_render_state if args.state
        else (
            lambda label, struct, out: _top_render_failover(
                label, struct, out, source=args.source
            )
        ) if args.failover
        else (
            lambda label, struct, out: _top_render(
                label, struct, out, source=args.source
            )
        )
    )

    def _render_once(sources, stale_after=None, now=None) -> None:
        from flink_jpmml_tpu.obs import attr as _attr

        if args.worker is not None:
            if args.worker not in sources:
                raise SystemExit(
                    f"no source {args.worker!r}; have "
                    f"{sorted(sources)}"
                )
            sources = {args.worker: sources[args.worker]}
        first = True
        for label in sorted(sources, key=lambda k: (k != "", k)):
            if not first:
                print(file=sys.stdout)
            disp = label
            if stale_after is not None:
                # the snapshot's OWN capture timestamp, not fetch time:
                # a supervisor keeps serving a dead worker's last struct,
                # and that panel must say so instead of reading as live
                tag = _attr.staleness_tag(
                    sources[label], stale_after, now=now
                )
                if tag:
                    disp = (label or "aggregate") + tag
            render(disp, sources[label], sys.stdout)
            first = False

    if args.watch is None:
        _render_once(_top_load(args.source))
        return 0
    import time as _time

    from flink_jpmml_tpu.obs import attr as _attr

    try:
        stale_after = float(os.environ["FJT_TOP_STALE_S"])
    except (KeyError, ValueError):
        stale_after = max(10.0, 3.0 * args.watch)

    while True:
        try:
            sources = _top_load(args.source)
        except (SystemExit, Exception) as e:
            # an operator console must ride out a worker restart or a
            # dropped tunnel: note the failure, keep watching (a
            # missing --worker label is surfaced the same way — it
            # reappears when the worker rejoins). Any Exception, not
            # just the wrapped SystemExit: a proxy's non-UTF-8 error
            # page or a half-written struct must not kill the console
            # at exactly the moment it promises to ride out
            print(f"[fjt-top] {e!r}; retrying in {args.watch:g}s",
                  file=sys.stderr, flush=True)
        else:
            if sys.stdout.isatty():  # console: repaint in place
                print("\x1b[2J\x1b[H", end="", file=sys.stdout)
            now = _time.time()
            ages = [
                a for a in (
                    _attr.snapshot_age_s(s, now=now)
                    for s in sources.values()
                )
                if a is not None
            ]
            hdr = _time.strftime("-- %H:%M:%S ")
            if ages:
                lo, hi = min(ages), max(ages)
                hdr += f" (frame age {lo:.1f}s"
                if hi - lo > 0.05:
                    hdr += f" .. {hi:.1f}s"
                hdr += ")"
            print(hdr, file=sys.stdout)
            try:
                _render_once(sources, stale_after=stale_after, now=now)
            except (SystemExit, Exception) as e:
                print(f"[fjt-top] {e!r}; retrying in {args.watch:g}s",
                      file=sys.stderr, flush=True)
            sys.stdout.flush()
        _time.sleep(args.watch)


def _replay_load(source: str, qargs: dict) -> dict:
    """→ a ``/history`` payload (obs/history.py ``query`` shape) from a
    history directory or an obs-server base (or /history) URL."""
    if source.startswith(("http://", "https://")):
        import urllib.error
        import urllib.parse
        import urllib.request

        url = source.rstrip("/")
        if not url.endswith("/history"):
            url += "/history"
        q = {}
        if qargs.get("names"):
            q["name"] = ",".join(qargs["names"])
        if qargs.get("sources"):
            q["source"] = ",".join(qargs["sources"])
        for k in ("start", "end", "step"):
            if qargs.get(k) is not None:
                q[k] = repr(float(qargs[k]))
        if q:
            url += "?" + urllib.parse.urlencode(q)
        try:
            with urllib.request.urlopen(url, timeout=10) as r:
                payload = json.loads(r.read().decode())
        except (urllib.error.URLError, OSError,
                json.JSONDecodeError) as e:
            raise SystemExit(f"cannot read {url!r}: {e}")
        if not isinstance(payload, dict):
            raise SystemExit(f"{url!r} is not a JSON object")
        return payload
    from flink_jpmml_tpu.obs import history

    if not os.path.isdir(source):
        raise SystemExit(
            f"{source!r} is neither a history directory nor an "
            "obs-server URL"
        )
    return history.query(source, **qargs)


def replay_main(argv: Optional[List[str]] = None) -> int:
    """``fjt-replay``: retrospective incident replay from the durable
    telemetry history (obs/history.py). Reads delta frames from a
    history directory (``FJT_HISTORY_DIR``) or a live ``/history``
    endpoint, prints a per-window timeline (records, shed, pressure,
    offered vs fitted capacity, headroom), then renders the whole range
    through the selected ``fjt-top`` panel — the console a worker's
    SIGKILL cannot erase, because the frames are already on disk:

        fjt-replay /data/history --last 600 --step 15
        fjt-replay http://127.0.0.1:9100 --panel zoo
        fjt-replay /data/history --source _fleet --panel overload
    """
    ap = argparse.ArgumentParser(
        prog="fjt-replay",
        description="Replay recorded telemetry history: a per-window "
                    "incident timeline plus any fjt-top panel rendered "
                    "over the range, from durable frames alone.",
    )
    ap.add_argument("path", metavar="DIR|URL",
                    help="history directory (FJT_HISTORY_DIR) or "
                         "obs-server base / /history URL")
    ap.add_argument("--start", type=float, default=None, metavar="TS",
                    help="range start (unix seconds)")
    ap.add_argument("--end", type=float, default=None, metavar="TS",
                    help="range end (unix seconds)")
    ap.add_argument("--last", type=float, default=None, metavar="S",
                    help="shorthand: the trailing S seconds "
                         "(end defaults to now)")
    ap.add_argument("--step", type=float, default=None, metavar="S",
                    help="timeline window width in seconds (default: "
                         "the finest stored resolution)")
    ap.add_argument("--source", default=None,
                    help="comma-separated frame sources (worker ids, "
                         "or _fleet for the supervisor's aggregate; "
                         "default: all workers — _fleet excluded, it "
                         "re-counts the same traffic)")
    ap.add_argument("--name", default=None,
                    help="comma-separated metric name patterns "
                         "(fnmatch) to project frames down to")
    ap.add_argument("--panel", default="stage",
                    choices=["stage", "freshness", "overload", "drift",
                             "failover", "mesh", "zoo", "state",
                             "none"],
                    help="fjt-top panel to render over the merged "
                         "range (default: stage; none = timeline only)")
    ap.add_argument("--json", action="store_true",
                    help="dump the raw query payload (frames keep the "
                         "exact wire encoding) instead of rendering")
    args = ap.parse_args(argv)
    if args.last is not None and args.last <= 0:
        raise SystemExit(f"--last must be > 0, got {args.last}")
    import time as _time

    from flink_jpmml_tpu.obs import history as _hist

    qargs = {
        "names": (
            [p for p in args.name.split(",") if p] if args.name else None
        ),
        "sources": (
            [p for p in args.source.split(",") if p]
            if args.source else None
        ),
        "start": args.start,
        "end": args.end,
        "step": args.step,
    }
    if args.last is not None:
        qargs["end"] = args.end if args.end is not None else _time.time()
        qargs["start"] = qargs["end"] - args.last
    payload = _replay_load(args.path, qargs)
    if args.json:
        json.dump(payload, sys.stdout, sort_keys=True)
        print(file=sys.stdout)
        return 0
    frames = [
        f for f in (payload.get("frames") or []) if isinstance(f, dict)
    ]
    if not frames:
        res = payload.get("resolutions") or []
        print(
            "no frames in range"
            + (f" (stored resolutions: {res})" if res
               else " (nothing recorded — FJT_HISTORY_DIR armed?)"),
            file=sys.stderr,
        )
        return 1

    def _cnt(f: dict, *bases: str) -> float:
        """Exact-wire counter sum over the given base families (label
        series included), rendered as a float."""
        tot = 0.0
        for n, v in (f.get("counters") or {}).items():
            if n.split("{", 1)[0] in bases:
                try:
                    tot += _hist.wire_float(v)
                except (TypeError, ValueError, ZeroDivisionError):
                    pass
        return tot

    def _gv(f: dict, name: str) -> Optional[float]:
        g = (f.get("gauges") or {}).get(name)
        if not isinstance(g, dict):
            return None
        try:
            return _hist.combined_last(name, g.get("last"))
        except (AttributeError, TypeError, ValueError):
            return None

    def _fmt(v: Optional[float], spec: str) -> str:
        return format(v, spec) if v is not None else "-"

    print(
        f"{'time':<10}{'records':>10}{'rec/s':>9}{'shed':>8}"
        f"{'press':>7}{'offered':>9}{'capacity':>9}{'headroom':>9}"
        f"{'resets':>7}",
        file=sys.stdout,
    )
    for f in frames:
        t0, t1 = float(f.get("t0", 0.0)), float(f.get("t1", 0.0))
        span = max(t1 - t0, 1e-9)
        rec = _cnt(f, "records_out")
        shed = _cnt(f, "shed_records", "tenant_shed_records")
        hr = _gv(f, "headroom_frac")
        print(
            f"{_time.strftime('%H:%M:%S', _time.localtime(t0)):<10}"
            f"{rec:>10,.0f}"
            f"{rec / span:>9,.0f}"
            f"{shed:>8,.0f}"
            f"{_fmt(_gv(f, 'pressure'), '.2f'):>7}"
            f"{_fmt(_gv(f, 'offered_rec_s'), ',.0f'):>9}"
            f"{_fmt(_gv(f, 'capacity_rec_s'), ',.0f'):>9}"
            f"{_fmt(100.0 * hr if hr is not None else None, '.1f'):>8}"
            f"{'%' if hr is not None else ' '}"
            f"{int(f.get('resets', 0) or 0):>7}",
            file=sys.stdout,
        )
    merged = _hist.merge_frames(frames)
    srcs = str(merged.get("src", ""))
    total_resets = int(merged.get("resets", 0) or 0)
    print(
        f"{len(frames)} window(s)   sources [{srcs}]"
        + (f"   {total_resets} counter reset(s) — worker restart(s) "
           "inside the range" if total_resets else ""),
        file=sys.stdout,
    )
    if args.panel == "none":
        return 0
    struct = _hist.frame_to_struct(merged)
    t0s = _time.strftime(
        "%H:%M:%S", _time.localtime(float(merged.get("t0", 0.0)))
    )
    t1s = _time.strftime(
        "%H:%M:%S", _time.localtime(float(merged.get("t1", 0.0)))
    )
    label = f"replay {t0s}..{t1s}"
    render = {
        "stage": lambda l, s, o: _top_render(l, s, o, source=args.path),
        "freshness": _top_render_freshness,
        "overload": _top_render_overload,
        "drift": _top_render_drift,
        "failover": lambda l, s, o: _top_render_failover(
            l, s, o, source=args.path
        ),
        "mesh": _top_render_mesh,
        "zoo": _top_render_zoo,
        "state": _top_render_state,
    }[args.panel]
    print(file=sys.stdout)
    render(label, struct, sys.stdout)
    return 0


def _drift_merge_sources(sources: Dict[str, dict]) -> dict:
    """One struct to snapshot/check against: the aggregate (``""``)
    label when the source carries one, else the fleet merge of every
    labelled struct (a supervisor /varz without a precomputed
    aggregate)."""
    from flink_jpmml_tpu.utils.metrics import merge_structs

    if "" in sources:
        return sources[""]
    return merge_structs(list(sources.values()))


def drift_main(argv: Optional[List[str]] = None) -> int:
    """``fjt-drift``: manage the data-drift baseline registry
    (obs/drift.py) from the shell — no jax import, safe on any host.

        fjt-drift snapshot http://127.0.0.1:9100   # live profile → baseline
        fjt-drift snapshot BENCH_r09.json --model <hash>
        fjt-drift list
        fjt-drift check http://127.0.0.1:9100      # PSI table vs baseline

    ``snapshot`` captures the source's CURRENT cumulative per-feature
    profiles as the reference the DriftMonitor diffs live windows
    against, content-addressed beside the autotune cache (override with
    --dir). A corrupt baseline file on disk reads as absent — simply
    re-snapshot."""
    ap = argparse.ArgumentParser(
        prog="fjt-drift",
        description="Capture, list, and check data-drift baselines.",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    ap_snap = sub.add_parser(
        "snapshot",
        help="capture a reference profile per (model, feature) from a "
             "live /varz URL, a struct dump, or a BENCH artifact",
    )
    ap_snap.add_argument("source")
    ap_snap.add_argument("--model", default=None,
                         help="only this model label (default: all)")
    ap_snap.add_argument("--dir", default=None,
                         help="baseline directory (default: "
                              "drift_baselines/ beside the autotune "
                              "cache)")
    ap_list = sub.add_parser("list", help="list stored baselines")
    ap_list.add_argument("--dir", default=None)
    ap_check = sub.add_parser(
        "check",
        help="PSI of a source's cumulative profiles vs the stored "
             "baselines (exit 1 when any feature exceeds --psi)",
    )
    ap_check.add_argument("source")
    ap_check.add_argument("--model", default=None)
    ap_check.add_argument("--dir", default=None)
    ap_check.add_argument("--psi", type=float, default=0.25,
                          help="failure threshold (default 0.25)")
    args = ap.parse_args(argv)

    from flink_jpmml_tpu.obs import drift as drift_mod
    from flink_jpmml_tpu.utils.metrics import QuantileSketch

    store = drift_mod.BaselineStore(args.dir)

    if args.cmd == "list":
        models = store.models()
        if not models:
            print(f"no baselines under {store.root}", file=sys.stderr)
            return 0
        for m in models:
            payload = store.load(m)
            feats = sorted((payload or {}).get("features") or {})
            print(f"{m}  features={len(feats)}  "
                  f"predictions={'yes' if (payload or {}).get('predictions') else 'no'}  "
                  f"({store.path(m)})")
        return 0

    struct = _drift_merge_sources(_top_load(args.source))
    payloads = drift_mod.snapshot_from_struct(struct)
    if args.model is not None:
        payloads = {
            k: v for k, v in payloads.items() if k == args.model
        }
    if not payloads:
        raise SystemExit(
            f"no drift profiles in {args.source!r}"
            + (f" for model {args.model!r}" if args.model else "")
            + " — is FJT_DRIFT_SAMPLE set on the pipeline?"
        )

    if args.cmd == "snapshot":
        for label, payload in sorted(payloads.items()):
            try:
                path = store.save(label, payload)
            except OSError as e:
                # a snapshot that didn't land must FAIL — the operator
                # would otherwise believe the drift plane is armed
                raise SystemExit(
                    f"cannot write baseline for {label!r}: {e}"
                )
            print(
                f"baselined {label}: {len(payload['features'])} features"
                + (", predictions" if payload.get("predictions") else "")
                + f" -> {path}",
                file=sys.stderr,
            )
        return 0

    # check: cumulative-vs-baseline PSI per feature
    rc = 0
    for label, payload in sorted(payloads.items()):
        base = store.load(label)
        if base is None:
            print(f"{label}: no baseline stored (fjt-drift snapshot "
                  "first)", file=sys.stderr)
            continue
        print(f"model {label}")
        rows = []
        for feat, lstate in sorted(payload.get("features", {}).items()):
            bstate = (base.get("features") or {}).get(feat)
            if bstate is None:
                continue
            try:
                score = drift_mod.psi(
                    QuantileSketch.from_state(bstate),
                    QuantileSketch.from_state(lstate),
                )
            except (KeyError, TypeError, ValueError):
                score = None
            rows.append((feat, score))
        bpred, lpred = base.get("predictions"), payload.get("predictions")
        if isinstance(bpred, dict) and isinstance(lpred, dict):
            try:
                rows.append(("(predictions)", drift_mod.psi(
                    QuantileSketch.from_state(bpred),
                    QuantileSketch.from_state(lpred),
                )))
            except (KeyError, TypeError, ValueError):
                pass
        rows.sort(key=lambda r: -1.0 if r[1] is None else r[1],
                  reverse=True)
        for feat, score in rows:
            verdict = "-"
            if score is not None and score > args.psi:
                verdict = "DRIFTED"
                rc = 1
            s = "-" if score is None else f"{score:.4f}"
            print(f"  {feat:<20} psi {s:>9}  {verdict}")
    return rc


# ---------------------------------------------------------------------------
# fjt-trace: causal record-journey reconstruction (obs/trace.py)
# ---------------------------------------------------------------------------

# flight-event kinds worth placing on a journey timeline (others are
# process-wide noise for this view); offset-carrying ones get their
# range fields normalized below
_TRACE_FLIGHT_KINDS = {
    "poison_suspect_mode", "poison_suspect_exit", "poison_isolation",
    "poison_isolated", "poison_quarantined", "latency_exemplar",
    "decode_error", "dispatch_abandon", "dlq_truncated",
    "worker_death", "worker_restart", "worker_spawn", "worker_give_up",
    "fault_injected", "drift_alarm",
}


def _trace_norm_flight(ev: dict) -> Optional[dict]:
    """Flight-recorder event → journey-row shape (None = not journey-
    relevant). ``lo``/``hi`` and ``first``/``n`` normalize to the
    journey rows' ``first_off``/``n`` so offset selection is uniform."""
    kind = ev.get("kind")
    if kind not in _TRACE_FLIGHT_KINDS:
        return None
    row = dict(ev)
    row["src"] = "flight"
    if "lo" in ev and "hi" in ev:
        try:
            row["first_off"] = int(ev["lo"])
            row["n"] = int(ev["hi"]) - int(ev["lo"])
        except (TypeError, ValueError):
            pass
    elif "first" in ev:
        try:
            row["first_off"] = int(ev["first"])
            if ev.get("n") is not None:
                row["n"] = int(ev["n"])
        except (TypeError, ValueError):
            pass
    return row


def _trace_norm_dlq(env: dict) -> dict:
    return {
        "t": env.get("t"),
        "pid": env.get("pid"),
        "kind": "dlq_envelope",
        "offset": env.get("offset"),
        "partition": env.get("partition"),
        "reason": env.get("reason"),
        "attempts": env.get("attempts"),
        "fingerprint": env.get("fingerprint"),
        "exception": env.get("exception"),
        "trace_id": env.get("trace_id"),
        "span_id": env.get("span_id"),
        "src": "dlq",
    }


def _trace_norm_span(ev: dict) -> Optional[dict]:
    """Chrome-trace span event → journey-row shape, ONLY when it
    carries a trace id (an uncorrelated span belongs in Perfetto, not
    here). Spans ride the monotonic clock, not unix time — they render
    in their own section, never interleaved by wall clock."""
    args = ev.get("args") or {}
    tid = args.get("trace_id")
    if not tid:
        return None
    row = {
        "t": None,  # monotonic clock: not comparable to unix rows
        "mono_us": ev.get("ts"),
        "dur_us": ev.get("dur"),
        "pid": ev.get("pid"),
        "kind": f"span:{ev.get('name')}",
        "trace_id": tid,
        "span_id": args.get("span_id"),
        "src": "span",
    }
    for k in ("first_off", "n", "offset"):
        if args.get(k) is not None:
            row[k] = args[k]
    return row


def _trace_rows_from_dir(directory: str) -> List[Dict[str, Any]]:
    """Recursively scan a dump directory for every durable journey
    fragment: journey-store segments (``journeys-*.jsonl``), flight
    dumps (``flight-*.jsonl``), DLQ segments (``dlq-*.jsonl``), and
    span files (``spans-*.trace.json``). Torn/garbage lines skip (the
    shared tolerant reader, ``obs.trace.iter_jsonl``)."""
    from flink_jpmml_tpu.obs.trace import iter_jsonl as _jsonl

    rows: List[Dict[str, Any]] = []
    for root, _dirs, names in os.walk(directory):
        for nm in sorted(names):
            path = os.path.join(root, nm)
            if nm.startswith("journeys-") and nm.endswith(".jsonl"):
                for obj in _jsonl(path):
                    obj.setdefault("src", "journey")
                    rows.append(obj)
            elif nm.startswith("flight-") and nm.endswith(".jsonl"):
                for obj in _jsonl(path):
                    norm = _trace_norm_flight(obj)
                    if norm is not None:
                        rows.append(norm)
            elif nm.startswith("dlq-") and nm.endswith(".jsonl"):
                for obj in _jsonl(path):
                    rows.append(_trace_norm_dlq(obj))
            elif nm.startswith("spans-") and nm.endswith(".trace.json"):
                for obj in _jsonl(path):
                    norm = _trace_norm_span(obj)
                    if norm is not None:
                        rows.append(norm)
    return rows


def _trace_load(source: str) -> List[Dict[str, Any]]:
    """→ normalized journey rows from a dump directory, a live
    ``/trace`` endpoint, or a BENCH artifact's embedded ``journeys``."""
    if source.startswith(("http://", "https://")):
        import urllib.error
        import urllib.request

        url = source.rstrip("/")
        if not url.endswith("/trace"):
            url += "/trace"
        try:
            with urllib.request.urlopen(url, timeout=10) as r:
                payload = json.loads(r.read().decode())
        except (urllib.error.URLError, OSError,
                json.JSONDecodeError) as e:
            raise SystemExit(f"cannot read {url!r}: {e}")
        rows = []
        for obj in payload.get("journeys") or []:
            if isinstance(obj, dict):
                obj.setdefault("src", "journey")
                rows.append(obj)
        for ev in payload.get("flight") or []:
            if isinstance(ev, dict):
                norm = _trace_norm_flight(ev)
                if norm is not None:
                    rows.append(norm)
        for ev in payload.get("spans") or []:
            if isinstance(ev, dict):
                norm = _trace_norm_span(ev)
                if norm is not None:
                    rows.append(norm)
        return rows
    if os.path.isdir(source):
        return _trace_rows_from_dir(source)
    try:
        with open(source, encoding="utf-8") as f:
            payload = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise SystemExit(f"cannot read {source!r}: {e}")
    if not isinstance(payload, dict):
        raise SystemExit(f"{source!r} is not a JSON object")
    if isinstance(payload.get("parsed"), dict):
        payload = payload["parsed"]  # the bench driver's artifact wrap
    rows = payload.get("journeys")
    if rows is None:
        for v in payload.values():  # one nested level (drill sub-line)
            if isinstance(v, dict) and isinstance(v.get("journeys"), list):
                rows = v["journeys"]
                break
    if not isinstance(rows, list):
        raise SystemExit(
            f"no journey rows in {source!r} (need a dump dir, a /trace "
            "URL, or an artifact with an embedded 'journeys' list)"
        )
    out = []
    for obj in rows:
        if isinstance(obj, dict):
            obj.setdefault("src", "journey")
            out.append(obj)
    return out


def _trace_row_covers(row: dict, offset: int) -> bool:
    if row.get("offset") is not None:
        try:
            if int(row["offset"]) == offset:
                return True
        except (TypeError, ValueError):
            pass
    fo = row.get("first_off")
    if fo is not None:
        try:
            fo = int(fo)
            n = int(row.get("n") or 1)
            return fo <= offset < fo + n
        except (TypeError, ValueError):
            return False
    return False


def _trace_select(
    rows: List[dict],
    trace_id: Optional[str] = None,
    offset: Optional[int] = None,
) -> List[dict]:
    """The journey join: rows matching the selector directly, expanded
    one round through what the direct matches carry — an offset pulls
    in the trace ids of every batch that contained it (other
    incarnations' fragments), a trace id pulls in the per-record
    terminal hops (dlq/shed — minted under per-RECORD ids) whose
    offset falls inside its batches' ``(first_off, n)`` ranges, so the
    fjt-top exemplar pivot's timeline shows a quarantine that happened
    inside the slow batch."""
    direct = []
    id_ranges: List[tuple] = []  # (lo, hi) of rows matched BY trace id
    for r in rows:
        if trace_id is not None and r.get("trace_id") == trace_id:
            direct.append(r)
            fo, n = r.get("first_off"), r.get("n")
            if fo is not None:
                try:
                    id_ranges.append((int(fo), int(fo) + int(n or 1)))
                except (TypeError, ValueError):
                    pass
        elif offset is not None and _trace_row_covers(r, offset):
            direct.append(r)
    ids = {r["trace_id"] for r in direct if r.get("trace_id")}
    offsets = set()
    if offset is not None:
        offsets.add(offset)
    for r in direct:
        if r.get("offset") is not None:
            try:
                offsets.add(int(r["offset"]))
            except (TypeError, ValueError):
                pass
    direct_ids = {id(r) for r in direct}

    def _off_in_id_ranges(r: dict) -> bool:
        # only rows with an EXPLICIT per-record offset join through a
        # batch range (range∩range would let a fetch-run ingest row
        # matched by offset pull in every batch it ever fed)
        if not id_ranges or r.get("offset") is None:
            return False
        try:
            o = int(r["offset"])
        except (TypeError, ValueError):
            return False
        return any(lo <= o < hi for lo, hi in id_ranges)

    seen = set()
    out = []
    for r in rows:
        match = (
            id(r) in direct_ids
            or (r.get("trace_id") in ids)
            or any(_trace_row_covers(r, o) for o in offsets)
            or _off_in_id_ranges(r)
        )
        if not match:
            continue
        key = id(r)
        if key in seen:
            continue
        seen.add(key)
        out.append(r)
    return out


def _trace_render(rows: List[dict], out, title: str = "journey") -> None:
    timed = [r for r in rows if isinstance(r.get("t"), (int, float))]
    spans_ = [r for r in rows if r.get("src") == "span"]
    timed.sort(key=lambda r: r["t"])
    ids = sorted({
        str(r["trace_id"])[:12] for r in rows if r.get("trace_id")
    })
    print(f"== {title} · trace ids [{', '.join(ids) or '-'}] ==",
          file=out)
    if not timed:
        print("(no timeline rows matched)", file=out)
    t0 = timed[0]["t"] if timed else 0.0
    last_pid = None
    for r in timed:
        pid = r.get("pid")
        if last_pid is not None and pid is not None and pid != last_pid:
            print(
                f"-- incarnation boundary: pid {last_pid} → pid {pid} --",
                file=out,
            )
        if pid is not None:
            last_pid = pid
        where = ""
        if r.get("offset") is not None:
            where = f"offset={r['offset']}"
        elif r.get("first_off") is not None:
            n = r.get("n")
            where = (
                f"[{r['first_off']}..{int(r['first_off']) + int(n)})"
                if n is not None else f"@{r['first_off']}"
            )
        detail = "  ".join(
            f"{k}={r[k]}" for k in (
                "reason", "lane", "attempts", "restarts", "latency_s",
                "sampled", "stage", "seconds", "model", "error",
                "exception", "redriven",
            )
            if r.get(k) not in (None, False)
        )
        tid = str(r.get("trace_id") or "")[:8]
        sid = str(r.get("span_id") or "")[:8]
        par = str(r.get("parent_id") or "")[:8]
        link = f"{tid}/{sid}" + (f"<-{par}" if par else "")
        print(
            f"+{r['t'] - t0:9.3f}s  pid {pid or '?':>7}  "
            f"{r.get('kind', '?'):<18} {where:<18} {link:<28} {detail}",
            file=out,
        )
    if spans_:
        print("spans (monotonic clock, per pid — not wall-aligned):",
              file=out)
        spans_.sort(key=lambda r: (r.get("pid") or 0,
                                   r.get("mono_us") or 0))
        for r in spans_[:64]:
            dur = r.get("dur_us")
            print(
                f"  pid {r.get('pid') or '?':>7}  "
                f"{r.get('kind', '?'):<24} "
                f"dur {0.0 if dur is None else dur / 1000.0:9.3f} ms  "
                f"trace {str(r.get('trace_id'))[:8]}",
                file=out,
            )


def _trace_summary(rows: List[dict], out, limit: int) -> None:
    """No selector: one line per known journey, newest last."""
    by_id: Dict[str, List[dict]] = {}
    for r in rows:
        tid = r.get("trace_id")
        if tid:
            by_id.setdefault(str(tid), []).append(r)
    if not by_id:
        print("(no journeys found)", file=out)
        return
    items = sorted(
        by_id.items(),
        key=lambda kv: max(
            (r.get("t") or 0) for r in kv[1]
        ),
    )[-limit:]
    print(f"{'TRACE':<14}{'HOPS':>5}  {'KINDS':<40} OFFSETS", file=out)
    for tid, rs in items:
        kinds = sorted({r.get("kind", "?") for r in rs})
        offs = sorted({
            int(r["first_off"]) for r in rs
            if r.get("first_off") is not None
        } | {
            int(r["offset"]) for r in rs
            if r.get("offset") is not None
        })
        off_s = (
            f"{offs[0]}..{offs[-1]}" if len(offs) > 1
            else (str(offs[0]) if offs else "-")
        )
        print(
            f"{tid[:12]:<14}{len(rs):>5}  "
            f"{','.join(kinds)[:40]:<40} {off_s}",
            file=out,
        )
    print(
        f"{len(by_id)} journey(s); fjt-trace <source> --id <TRACE> or "
        "--grep offset=K for a timeline",
        file=out,
    )


def trace_main(argv: Optional[List[str]] = None) -> int:
    """``fjt-trace``: reconstruct causal record journeys (see module
    docstring) — no jax import, safe on any host."""
    ap = argparse.ArgumentParser(
        prog="fjt-trace",
        description="Reconstruct a record's causal journey from journey "
                    "rows, flight events, DLQ envelopes, and spans.",
    )
    ap.add_argument("source",
                    help="dump directory (journey store / checkpoint "
                         "dir — scanned recursively), obs-server base "
                         "URL (its /trace endpoint), or a BENCH "
                         "artifact with embedded journeys")
    ap.add_argument("--id", dest="trace_id", default=None,
                    help="render the journey with this trace id (the "
                         "id an fjt-top exemplar row shows)")
    ap.add_argument("--grep", default=None, metavar="KEY=VAL",
                    help="find journeys without knowing ids; supported: "
                         "offset=K (every fragment whose offset range "
                         "contains record K)")
    ap.add_argument("--slowest", type=int, default=None, metavar="N",
                    help="rank completed journeys by sink latency and "
                         "list the N slowest (with their trace ids)")
    ap.add_argument("--limit", type=int, default=32,
                    help="journeys shown in the no-selector summary "
                         "(default 32)")
    args = ap.parse_args(argv)

    rows = _trace_load(args.source)
    offset = None
    if args.grep is not None:
        key, _, val = args.grep.partition("=")
        if key.strip() != "offset" or not val.strip():
            raise SystemExit(
                f"unsupported --grep {args.grep!r} (supported: offset=K)"
            )
        try:
            offset = int(val)
        except ValueError:
            raise SystemExit(f"--grep offset wants an int, got {val!r}")

    if args.slowest is not None:
        sinks = [
            r for r in rows
            if r.get("kind") == "sink"
            and isinstance(r.get("latency_s"), (int, float))
        ]
        sinks.sort(key=lambda r: -float(r["latency_s"]))
        if not sinks:
            print("(no completed journeys with latencies)",
                  file=sys.stdout)
            return 0
        print(f"{'LATENCY':>11}  {'TRACE':<14}{'RANGE':<18}PID",
              file=sys.stdout)
        for r in sinks[: args.slowest]:
            fo, n = r.get("first_off"), r.get("n")
            rng = (
                f"[{fo}..{int(fo) + int(n)})"
                if fo is not None and n is not None else "-"
            )
            print(
                f"{1000.0 * float(r['latency_s']):9.3f}ms  "
                f"{str(r.get('trace_id'))[:12]:<14}{rng:<18}"
                f"{r.get('pid', '?')}",
                file=sys.stdout,
            )
        print("pivot: fjt-trace <source> --id <TRACE>", file=sys.stdout)
        return 0

    if args.trace_id is None and offset is None:
        _trace_summary(rows, sys.stdout, max(1, args.limit))
        return 0

    sel = _trace_select(rows, trace_id=args.trace_id, offset=offset)
    if not sel:
        raise SystemExit(
            "no fragments matched "
            + (f"trace id {args.trace_id!r}" if args.trace_id
               else f"offset {offset}")
        )
    title = (
        f"offset {offset}" if offset is not None
        else f"id {str(args.trace_id)[:12]}"
    )
    _trace_render(sel, sys.stdout, title=title)
    return 0


def _dlq_open(directory: str):
    """Accept either the DLQ directory itself or the checkpoint
    directory it sits beside (``<ckpt>/dlq`` — the pipelines' default
    layout)."""
    import glob as _glob

    from flink_jpmml_tpu.runtime.dlq import DeadLetterQueue

    d = directory
    if not _glob.glob(os.path.join(d, "dlq-*.jsonl")):
        nested = os.path.join(d, "dlq")
        if _glob.glob(os.path.join(nested, "dlq-*.jsonl")):
            d = nested
        elif not os.path.isdir(d) and os.path.isdir(nested):
            d = nested
    if not os.path.isdir(d):
        raise SystemExit(f"no DLQ at {directory!r}")
    return DeadLetterQueue(d)


def _dlq_payload_preview(env: dict) -> str:
    from flink_jpmml_tpu.runtime.dlq import payload_bytes

    raw = payload_bytes(env)
    head = raw[:64]
    lines = [f"payload: {len(raw)} bytes, hex {head.hex()}"
             + ("…" if len(raw) > 64 else "")]
    try:
        lines.append(f"as text: {raw.decode('utf-8')!r}")
    except UnicodeDecodeError:
        pass
    if len(raw) % 4 == 0 and raw:
        import numpy as _np

        vals = _np.frombuffer(raw, _np.float32)
        if vals.size <= 64:
            lines.append(f"as f32 row: {vals.tolist()}")
    return "\n".join(lines)


def dlq_main(argv: Optional[List[str]] = None) -> int:
    """``fjt-dlq``: inspect and redrive the dead-letter queue
    (runtime/dlq.py) from the shell — no jax import, safe on any host.

        fjt-dlq list /data/ckpt              # table of quarantined records
        fjt-dlq inspect /data/ckpt --offset 1374
        fjt-dlq redrive /data/ckpt --host b1 --port 9092 --topic records

    ``redrive`` produces the quarantined payload bytes back INTO the
    topic (Kafka Produce), so a corrected pipeline re-scores them
    through the live consume path — the quarantine lifecycle's exit.
    Envelopes stay in place after a redrive (the DLQ is an append-only
    audit trail); re-running redrive re-produces them."""
    ap = argparse.ArgumentParser(
        prog="fjt-dlq",
        description="List, inspect, and redrive dead-letter records.",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    ap_list = sub.add_parser("list", help="one line per envelope")
    ap_list.add_argument("dir")
    ap_list.add_argument("--limit", type=int, default=64,
                         help="newest N envelopes (default 64; 0 = all)")
    ap_ins = sub.add_parser("inspect", help="full envelope + payload")
    ap_ins.add_argument("dir")
    g = ap_ins.add_mutually_exclusive_group(required=True)
    g.add_argument("--offset", type=int, default=None)
    g.add_argument("--index", type=int, default=None,
                   help="0-based position in scan order")
    ap_re = sub.add_parser(
        "redrive",
        help="produce quarantined payloads back into a Kafka topic",
    )
    ap_re.add_argument("dir")
    ap_re.add_argument("--host", required=True)
    ap_re.add_argument("--port", type=int, required=True)
    ap_re.add_argument("--topic", required=True)
    ap_re.add_argument("--partition", type=int, default=None,
                       help="target partition (default: the envelope's "
                            "own, else 0)")
    ap_re.add_argument("--offset", type=int, action="append",
                       default=None,
                       help="redrive only these quarantined offsets "
                            "(repeatable; default: every envelope)")
    ap_re.add_argument("--reason", default=None,
                       help="redrive only envelopes with this reason "
                            "(score / decode / crash_loop)")
    args = ap.parse_args(argv)

    q = _dlq_open(args.dir)
    envs = list(q.scan())

    if args.cmd == "list":
        if not envs:
            print(f"DLQ empty at {q.directory}", file=sys.stderr)
            return 0
        shown = envs if args.limit <= 0 else envs[-args.limit:]
        print(f"{'OFFSET':>10} {'PART':>4} {'REASON':<10} {'ATT':>3} "
              f"{'FINGERPRINT':<16} EXCEPTION")
        for e in shown:
            exc = (e.get("exception") or "-").splitlines()[0]
            part = e.get("partition")
            print(f"{e.get('offset', '?'):>10} "
                  f"{'-' if part is None else part:>4} "
                  f"{e.get('reason', '?'):<10} "
                  f"{e.get('attempts', 1):>3} "
                  f"{e.get('fingerprint', '?'):<16} {exc[:80]}")
        print(f"{len(envs)} envelope(s) in {q.directory}",
              file=sys.stderr)
        return 0

    if args.cmd == "inspect":
        if args.index is not None:
            if not (0 <= args.index < len(envs)):
                raise SystemExit(
                    f"index {args.index} out of range (have {len(envs)})"
                )
            picked = [envs[args.index]]
        else:
            picked = [
                e for e in envs if e.get("offset") == args.offset
            ]
            if not picked:
                raise SystemExit(
                    f"no envelope with offset {args.offset}"
                )
        for e in picked:
            print(json.dumps(e, indent=2, sort_keys=True))
            print(_dlq_payload_preview(e))
        return 0

    # redrive
    from flink_jpmml_tpu.runtime.dlq import payload_bytes
    from flink_jpmml_tpu.runtime.kafka import (
        KafkaClient, KafkaProtocolError,
    )

    picked = envs
    if args.offset is not None:
        want = set(args.offset)
        picked = [e for e in picked if e.get("offset") in want]
    if args.reason is not None:
        picked = [e for e in picked if e.get("reason") == args.reason]
    # one produce per envelope at most once per fingerprint: replays
    # across restarts can leave duplicate envelopes for the same record
    seen: set = set()
    unique = []
    for e in picked:
        key = (e.get("fingerprint"), e.get("offset"))
        if key in seen:
            continue
        seen.add(key)
        unique.append(e)
    if not unique:
        raise SystemExit("nothing to redrive (filters matched nothing)")
    client = KafkaClient(args.host, args.port, client_id="fjt-dlq")
    count = 0
    try:
        for e in unique:
            part = args.partition
            if part is None:
                part = e.get("partition")
            if part is None:
                part = 0
            # journey continuity (obs/trace.py): the envelope carries
            # the quarantined record's trace context — stamp it back
            # into the topic as a traceparent record header, so the
            # redriven record's ingest opens a CHILD span of the
            # original journey instead of starting an unlinked one
            headers = None
            tid, sid = e.get("trace_id"), e.get("span_id")
            if tid and sid:
                from flink_jpmml_tpu.obs.trace import TraceContext

                tp = TraceContext(str(tid), str(sid)).to_traceparent()
                headers = [[("traceparent", tp.encode("ascii"))]]
            try:
                base = client.produce(
                    args.topic, int(part), [payload_bytes(e)],
                    headers=headers,
                )
            except (OSError, ConnectionError, KafkaProtocolError) as ex:
                raise SystemExit(
                    f"redrive failed at offset {e.get('offset')}: {ex} "
                    f"({count} redriven before the failure)"
                )
            count += 1
            print(
                f"redrove offset {e.get('offset')} "
                f"({e.get('reason')}, {e.get('fingerprint')}) -> "
                f"{args.topic}[{part}]@{base}"
            )
    finally:
        client.close()
    print(f"{count} record(s) redriven", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(score_main())
