"""Block pipeline: the high-throughput vector path (≥1M rec/s).

The record-object :class:`~flink_jpmml_tpu.runtime.engine.Pipeline` is
flexible but pays Python-object costs per record — fine for thousands of
records/sec, fatal for millions. On this path records are contiguous
float32 *blocks* end to end:

    BlockSource.poll() → [n, F] numpy block
      → C++ ring (native.NativeRing; Python fallback)  ← backpressure
      → fill-or-deadline drain into a reused batch buffer
      → pad → jitted scoring (async dispatch, in-flight window)
      → sink(outputs)

No Python object per record exists anywhere; the only per-batch host work
is one memcpy into the ring and one out. This is the "no CPU evaluator in
the hot path" half of the BASELINE north star made concrete on the host
side.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from flink_jpmml_tpu.compile import prepare
from flink_jpmml_tpu.compile.compiler import CompiledModel
from flink_jpmml_tpu.obs import attr as attr_mod
from flink_jpmml_tpu.obs import drift as drift_mod
from flink_jpmml_tpu.obs import freshness as fresh_mod
from flink_jpmml_tpu.obs import pressure as pressure_mod
from flink_jpmml_tpu.obs import recorder as flight
from flink_jpmml_tpu.obs import spans
from flink_jpmml_tpu.obs import trace as trace_mod
from flink_jpmml_tpu.runtime import devfault
from flink_jpmml_tpu.runtime import faults
from flink_jpmml_tpu.runtime import prefetch as prefetch_mod
from flink_jpmml_tpu.runtime import state as state_mod
from flink_jpmml_tpu.runtime.checkpoint import CheckpointPolicy
from flink_jpmml_tpu.runtime.dlq import (
    REASON_CRASH_LOOP,
    REASON_SCORE,
    CrashFingerprint,
    PoisonIsolationOverflow,
    dlq_for_checkpoint,
    env_count,
)
from flink_jpmml_tpu.runtime.pipeline import (
    OverlappedDispatcher,
    _block_ready,
    _prefetch_host,  # noqa: F401  (re-export: engine.py imports it here)
    dispatch_quantized,
    filter_donate_warning,
)
from flink_jpmml_tpu.utils.config import RuntimeConfig
from flink_jpmml_tpu.utils.exceptions import (
    FlinkJpmmlTpuError,
    InputValidationException,
)
from flink_jpmml_tpu.utils.metrics import MetricsRegistry


class BlockSource:
    """poll() → (first_offset, block [n,F]) or None when drained/starved."""

    def poll(self) -> Optional[Tuple[int, np.ndarray]]:
        raise NotImplementedError

    def seek(self, offset: int) -> None:
        """Resume hook: next poll starts at this record offset."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support offset seek/resume"
        )

    @property
    def exhausted(self) -> bool:
        return False


class CyclingBlockSource(BlockSource):
    """Cycles over a fixed dataset in blocks forever (bench/load-gen)."""

    def __init__(self, data: np.ndarray, block_size: int):
        self._data = np.ascontiguousarray(data, np.float32)
        self._block = block_size
        self._pos = 0
        self._offset = 0

    def poll(self):
        n = self._data.shape[0]
        if self._pos + self._block <= n:
            blk = self._data[self._pos : self._pos + self._block]
            self._pos += self._block
        else:
            a = self._data[self._pos :]
            b = self._data[: self._block - a.shape[0]]
            blk = np.concatenate([a, b], axis=0)
            self._pos = self._block - a.shape[0]
        off = self._offset
        self._offset += blk.shape[0]
        return off, blk

    def seek(self, offset: int) -> None:
        self._offset = offset
        self._pos = offset % self._data.shape[0]


class FiniteBlockSource(BlockSource):
    def __init__(self, data: np.ndarray, block_size: int):
        self._data = np.ascontiguousarray(data, np.float32)
        self._block = block_size
        self._pos = 0

    def poll(self):
        if self._pos >= self._data.shape[0]:
            return None
        blk = self._data[self._pos : self._pos + self._block]
        off = self._pos
        self._pos += blk.shape[0]
        return off, blk

    def seek(self, offset: int) -> None:
        self._pos = offset

    @property
    def exhausted(self) -> bool:
        return self._pos >= self._data.shape[0]


class _PyRing:
    """Pure-Python fallback with the NativeRing interface (chunk list +
    condition variables; same fill-or-deadline semantics, more GIL)."""

    def __init__(self, capacity: int, arity: int, batch_size: int):
        self._cap = capacity
        self._arity = arity
        self._chunks: List[Tuple[int, np.ndarray]] = []
        self._count = 0
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._closed = False
        self._batch = np.zeros((batch_size, arity), np.float32)
        self._offsets = np.zeros((batch_size,), np.uint64)

    def push_block(self, block, first_offset, timeout_us=-1) -> int:
        block = np.ascontiguousarray(block, np.float32)
        pushed = 0
        deadline = (
            None if timeout_us < 0 else time.monotonic() + timeout_us / 1e6
        )
        with self._not_full:
            while pushed < block.shape[0]:
                while self._count >= self._cap and not self._closed:
                    remaining = (
                        None if deadline is None else deadline - time.monotonic()
                    )
                    if remaining is not None and remaining <= 0:
                        return pushed
                    self._not_full.wait(remaining if remaining else 0.1)
                if self._closed:
                    return pushed
                room = self._cap - self._count
                take = min(room, block.shape[0] - pushed)
                self._chunks.append(
                    (first_offset + pushed, block[pushed : pushed + take])
                )
                self._count += take
                pushed += take
                self._not_empty.notify()
        return pushed

    def drain(self, deadline_us: int, idle_timeout_us: int = -1):
        with self._not_empty:
            idle_deadline = (
                None
                if idle_timeout_us < 0
                else time.monotonic() + idle_timeout_us / 1e6
            )
            while self._count == 0:
                if self._closed:
                    return self._batch[:0], self._offsets[:0]
                if idle_deadline is None:
                    self._not_empty.wait(0.1)
                else:
                    remaining = idle_deadline - time.monotonic()
                    if remaining <= 0:
                        # idle bound: empty return on an open ring lets
                        # the consumer run control-plane work
                        return self._batch[:0], self._offsets[:0]
                    self._not_empty.wait(min(remaining, 0.1))
            deadline = time.monotonic() + deadline_us / 1e6
            drained = 0
            max_n = self._batch.shape[0]
            while drained < max_n:
                while self._chunks and drained < max_n:
                    off, chunk = self._chunks[0]
                    take = min(chunk.shape[0], max_n - drained)
                    self._batch[drained : drained + take] = chunk[:take]
                    self._offsets[drained : drained + take] = np.arange(
                        off, off + take, dtype=np.uint64
                    )
                    if take == chunk.shape[0]:
                        self._chunks.pop(0)
                    else:
                        self._chunks[0] = (off + take, chunk[take:])
                    self._count -= take
                    drained += take
                    self._not_full.notify_all()
                if drained >= max_n or self._closed:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._not_empty.wait(remaining)
            return self._batch[:drained], self._offsets[:drained]

    def close(self):
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    @property
    def closed(self):
        with self._lock:
            return self._closed

    def __len__(self):
        with self._lock:
            return self._count


def make_ring(capacity: int, arity: int, batch_size: int, native: bool = True):
    """NativeRing when the C++ plane builds; _PyRing otherwise."""
    if native:
        from flink_jpmml_tpu.runtime import native as native_mod

        if native_mod.available():
            return native_mod.NativeRing(capacity, arity, batch_size)
    return _PyRing(capacity, arity, batch_size)


class BoundScorer:
    """One servable compiled model bound for block scoring: its (maybe)
    rank-wire scorer, the ``rank_wire_*``/``f32`` backend tag, and the
    decode callable (carrying ``model_key``) handed to dynamic sinks.
    Shared by the static and dynamic pipelines so the probe/backend/
    decode logic cannot diverge between them."""

    def __init__(self, key: str, model, use_quantized: bool):
        self.key = key
        self.model = model
        probe = getattr(model, "quantized_scorer", None)
        self.q = probe() if (use_quantized and probe is not None) else None
        self.backend = (
            f"rank_wire_{self.q.backend}" if self.q is not None else "f32"
        )

        def decode(out, n):
            if self.q is not None:
                return self.q.decode(out, n)
            return self.model.decode(out, n)

        decode.model_key = key
        # the drift plane's content-addressed label: matches the
        # feature-profile label dispatch_quantized records under, so a
        # model's feature and prediction series share one baseline
        decode.model_hash = (
            self.q.model_hash if self.q is not None else None
        )
        self.decode = decode


class BlockPipelineBase:
    """Shared machinery of the static and dynamic block pipelines:
    ingest→ring, lifecycle (start/stop/join/run_*), the ``_drain_all``
    stop protocol, and the score loop skeleton. Subclass hooks:

    - ``_acquire(finish_one)`` → per-batch scoring handle (or None to
      abandon the loop — the dynamic pipeline's bounded registry-gap
      give-up); called with a drained batch pending, between batches.
    - ``_dispatch(handle, X, n)`` → ``(raw_out, decode_or_None)``, the
      async device dispatch.
    - ``_emit(out, n, first_off, decode)`` → deliver to the sink.
    - ``_on_idle()`` — called when the ring drain returns empty on an
      open ring; reachable only when ``_IDLE_WAIT_US >= 0`` bounds the
      drain's wait for a first record (the dynamic pipeline sets it so
      Add/Del messages apply promptly on an idle stream).
    """

    _THREAD_TAG = "blk"
    _IDLE_WAIT_US = -1  # block indefinitely for the first record

    def __init__(
        self,
        source: BlockSource,
        sink: Callable,
        arity: int,
        batch_size: int,
        config: Optional[RuntimeConfig],
        metrics: Optional[MetricsRegistry],
        use_native: bool,
        in_flight: int,
        checkpoint,
        max_dispatch_chunks: int = 8,
        donate: Optional[bool] = None,
        slo=None,
        batcher=None,
        admission=None,
        shed_lane: str = "block",
        dlq=None,
        prefetch: Optional[bool] = None,
        failover=None,
        tenant: Optional[str] = None,
        state=None,
    ):
        # per-tenant delivery label (serving/zoo.py plane): see
        # engine.Pipeline — records_out stays the total, the labelled
        # counter adds the tenant axis. Mutable via set_tenant so the
        # dynamic block pipeline re-labels on a served-model swap.
        self._tenant = tenant
        self._source = source
        self._sink = sink
        # optional obs/slo.SLOTracker: ticked from the completion path
        # (between batches, on the score thread — the RolloutController
        # piggyback pattern), so burn-rate state stays live without a
        # thread of its own
        self._slo = slo
        # overload plane (serving/overload.py), both optional:
        # - batcher: AdaptiveBatcher — caps opportunistic multi-chunk
        #   aggregation at the size predicted to fit the deadline, fed
        #   from every completed dispatch (deadline-aware batching with
        #   no recompile);
        # - admission: AdmissionController — drained batches it refuses
        #   ride the FIFO window as no-op entries (offsets commit in
        #   order, the SINK NEVER SEES a shed record) under
        #   ``shed_lane``; its controller ticks piggyback on the
        #   completion path like the SLO tracker's.
        self._batcher = batcher
        self._admission = admission
        self._shed_lane = shed_lane
        if admission is not None and shed_lane not in admission.lanes:
            # unknown lanes are never shed (the safe per-record
            # default), which here would mean a controller that climbs
            # levels and reports shedding while refusing NOTHING —
            # silent no-op protection is the wrong default for a
            # whole-pipeline wire, so fail loudly at construction
            raise InputValidationException(
                f"shed_lane {shed_lane!r} is not one of the admission "
                f"controller's lanes {admission.lanes!r} — this "
                "pipeline could never shed"
            )
        self._arity = arity
        self._batch_size = batch_size
        # >1 enables opportunistic multi-chunk dispatch on a backed-up
        # ring (see _aggregate_full_batches); 1 = one batch per dispatch
        self._max_dispatch_chunks = max(1, max_dispatch_chunks)
        self._config = config or RuntimeConfig()
        self.metrics = metrics or MetricsRegistry()
        # pipelined ingest (runtime/prefetch.py): sources that mark
        # themselves prefetchable (the Kafka sources — real network
        # fetch + wire decode) get a sidecar thread running their poll
        # loop, so this pipeline's ingest thread only moves decoded
        # blocks into the ring. prefetch=None is auto; the wrapper
        # proxies seek/checkpoint hooks, so restore() is unchanged.
        self._source = prefetch_mod.maybe_wrap_block(
            self._source, metrics=self.metrics, enable=prefetch
        )
        self._ring = make_ring(
            self._config.batch.queue_capacity,
            arity,
            batch_size,
            native=use_native,
        )
        self._in_flight_max = max(1, in_flight)
        # buffer donation on the rank-wire dispatch: None = auto (on
        # when the backend isn't CPU — XLA:CPU ignores donation with a
        # warning per compile, so tests stay quiet by default)
        self._donate = donate
        self._donation_hits = self.metrics.counter("donation_hits")
        # drained-but-undispatched batches carried across loop
        # iterations (aggregation stops at an offset discontinuity —
        # a cycling source's wrap — and a chunk cannot be re-queued;
        # the poison plane additionally splits mid-batch gaps, which
        # can queue a second carry, hence a deque)
        self._carry_drain: "List[Tuple[np.ndarray, np.ndarray]]" = []
        # see engine.Pipeline: True only for run_until_exhausted's full
        # drain; plain stop() discards the uncommitted ring backlog so it
        # returns promptly under a flooding source
        self._drain_all = False
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._error: Optional[BaseException] = None
        self.committed_offset = 0
        self._ckpt = CheckpointPolicy(
            checkpoint, self._config.checkpoint_interval_s,
            metrics=self.metrics,
        )
        # -- delivery-correctness plane (runtime/dlq.py) ------------------
        # The DLQ defaults to living BESIDE the checkpoints: record-level
        # error isolation only makes sense when the quarantine survives
        # the restarts it exists to prevent. dlq=None with no checkpoint
        # keeps the historical behavior exactly (a scoring error kills
        # the worker).
        self._dlq = dlq if dlq is not None else dlq_for_checkpoint(
            checkpoint, metrics=self.metrics
        )
        ckpt_dir = getattr(checkpoint, "directory", None)
        self._ckpt_dir = ckpt_dir
        self._fingerprint = (
            CrashFingerprint(ckpt_dir)
            if (ckpt_dir is not None and self._dlq is not None) else None
        )
        # -- keyed per-record state (runtime/state.py) --------------------
        # state=StateSpec arms the fused state stage (the table joins
        # THIS pipeline's registry so state_* metrics scrape/merge like
        # every other family); a prebuilt KeyedStateTable passes
        # through (caller chose the registry). Unarmed pipelines pay
        # one None check per dispatch.
        if isinstance(state, state_mod.StateSpec):
            state = state_mod.KeyedStateTable(state, metrics=self.metrics)
        self._state = state
        # >0 while a recovery/isolation path is dispatching: those
        # re-dispatches (and bisection probes, which score records
        # MORE THAN ONCE) must never mutate the table — the PR 8/12
        # never-delivered contract extended to state
        self._state_bypass = 0
        # the batch offsets of the dispatch currently being launched
        # (stashed by _dispatch_checked for the state stage; the score
        # loop is single-threaded by the ring contract)
        self._cur_offsets = None
        # -- device-fault resilience (runtime/devfault.py +
        #    serving/failover.py) ------------------------------------------
        # The recovery ladder (redispatch → OOM batch bisection →
        # circuit breaker → fallback tier) arms by default wherever the
        # staging batches are ALREADY retained past the async dispatch
        # (a DLQ is wired — the production shape), or explicitly via
        # failover=<plane> / FJT_FAILOVER=1. A bare bench loop with no
        # durable state pays neither the retention copy nor the plane.
        # failover=False disables outright (historical fail-fast).
        if failover is False:
            self._failover = None
        elif failover is not None:
            self._failover = failover
        elif self._dlq is not None or os.environ.get("FJT_FAILOVER"):
            from flink_jpmml_tpu.serving import failover as failover_mod

            self._failover = failover_mod.plane_for(self.metrics)
        else:
            self._failover = None
        # retain the drained batch (private copy) past the async
        # dispatch: poison isolation AND device-fault recovery both
        # re-dispatch from this host-retained staging copy
        self._retain_batches = (
            self._dlq is not None or self._failover is not None
        )
        # highest offset ever handed to a dispatch (+n): checkpointed as
        # inflight_hi so a restart knows the at-least-once replay region
        self._dispatched_hi = 0
        # replay accounting + crash-loop suspect mode, armed by restore()
        self._replay_until = 0
        self._suspect_until: Optional[int] = None
        self._death_marker: Optional[dict] = None
        # 1 while scoring in suspect mode (fleet merge: worst-of — one
        # worker bisecting poison flags the fleet)
        self._suspect_gauge = self.metrics.gauge("poison_suspect_mode")
        # per-chip mesh telemetry (obs/mesh.MeshTelemetry), attached by
        # the subclass when the bound model is mesh-sharded; None keeps
        # the single-chip hot path at one attribute test per batch
        self._mesh_obs = None

    @property
    def native(self) -> bool:
        return not isinstance(self._ring, _PyRing)

    def _ckpt_state(self) -> dict:
        state = {
            "source_offset": self.committed_offset,
            # the in-flight offset range's upper bound: on restore,
            # [source_offset, inflight_hi) is exactly the at-least-once
            # replay region — what records_replayed counts and what a
            # crash-loop fingerprint resumes in suspect mode
            "inflight_hi": max(self._dispatched_hi, self.committed_offset),
        }
        # sources whose resume needs more than the scalar offset (e.g.
        # multi-partition Kafka's per-partition cursor vector) embed it
        # via the checkpoint_state/restore_state hooks
        snap = getattr(self._source, "checkpoint_state", None)
        if snap is not None:
            extra = snap(self.committed_offset)
            if extra is not None:
                state["source_state"] = extra
        if self._state is not None:
            # the keyed state table rides the checkpoint: an npz
            # sidecar beside the snapshots (same atomic-writer
            # discipline) referenced by name, or an inline payload for
            # small dirless tables. Saved at the SAME instant as the
            # offsets (this method runs when the policy fires, on the
            # score thread), so offsets and state agree; the table's
            # own applied_hi makes replayed records below it bypass
            # after restore (exactly-once state).
            ref = (
                self._state.save_sidecar(self._ckpt_dir)
                if self._ckpt_dir is not None else None
            )
            if ref is not None:
                state["state_sidecar"] = ref
            else:
                try:
                    state["state"] = self._state.to_payload()
                except Exception:
                    # a large table with no checkpoint directory:
                    # state is not durable — restart loses it (the
                    # runbook's sizing note), offsets stay correct
                    pass
        return state

    def restore(self) -> bool:
        """Resume from the latest checkpoint: seek the source to the last
        committed record offset (commit happens after sink, C7). A
        source-state payload (per-partition offset vector) takes
        precedence — its effective resume offset may sit one emission
        boundary below the scalar commit (at-least-once replay)."""
        state = self._ckpt.restore_latest()
        if state is None:
            # no snapshot yet — but the crash-loop fingerprint must
            # still count this restore: a poison record in the FIRST
            # uncommitted window crash-loops at offset 0 before any
            # checkpoint ever lands
            self._init_poison_state({})
            return False
        off = int(state.get("source_offset", 0))
        sstate = state.get("source_state")
        rst = getattr(self._source, "restore_state", None)
        if sstate is not None and rst is not None:
            off = int(rst(sstate))
        else:
            self._source.seek(off)
        self.committed_offset = off
        self._init_poison_state(state)
        self._restore_extra(state)
        return True

    def _init_poison_state(self, state: dict) -> None:
        """Crash-loop fingerprinting at restore: count consecutive
        restores stuck at the same committed offset (``crashes.json``
        beside the checkpoints) and read the supervisor's
        ``FJT_RESTART_STREAK`` hint — EITHER crossing
        ``FJT_POISON_RESTARTS`` flips the checkpoint's in-flight range
        into suspect mode, converting a crash loop into a DLQ entry
        instead of an ``on_give_up`` outage."""
        self._replay_until = max(
            int(state.get("inflight_hi", 0)), self.committed_offset
        )
        if self._fingerprint is None:
            return
        committed = self.committed_offset
        count = self._fingerprint.note_restore(committed)
        streak = env_count("FJT_RESTART_STREAK", 0)
        self._death_marker = self._fingerprint.read_marker()
        if (
            self._death_marker is not None
            and self._death_marker["hi"] <= committed
        ):
            # marker from a range that later committed: stale
            self._death_marker = None
            self._fingerprint.clear_marker()
        jstore = trace_mod.store_for(self.metrics)
        if jstore is not None:
            # the incarnation boundary, durable: fjt-trace renders the
            # pid change + the committed offset this restore resumed at
            jstore.hop(
                "restore", trace_mod.context_for(committed),
                first_off=committed, durable=True,
                restarts=max(count - 1, streak),
            )
        threshold = env_count("FJT_POISON_RESTARTS", 3)
        if max(count - 1, streak) >= threshold:
            # count-1: the FIRST restore at an offset is a normal
            # restart, not yet a loop
            hi = self._replay_until
            if hi <= committed:
                hi = committed + self._batch_size
            self._suspect_until = hi
            self._suspect_gauge.set(1.0)
            if jstore is not None:
                # suspect mode flips the journey store to write-through:
                # every hop of the bisection protocol must be on disk
                # BEFORE a process-killing record strikes again — the
                # marker protocol's observability twin
                jstore.write_through = True
                jstore.hop(
                    "suspect_mode", trace_mod.context_for(committed),
                    first_off=committed, n=hi - committed, durable=True,
                    restarts=max(count - 1, streak),
                )
            flight.record(
                "poison_suspect_mode", lo=committed, hi=hi,
                restarts=max(count - 1, streak),
                marker=self._death_marker,
            )

    def _restore_extra(self, state: dict) -> None:
        if self._state is None:
            return
        ref = state.get("state_sidecar")
        if ref and self._ckpt_dir is not None:
            self._state.restore_sidecar(self._ckpt_dir, ref)
        elif state.get("state"):
            self._state.from_payload(state["state"])

    def start(self):
        t1 = threading.Thread(
            target=self._ingest,
            name=f"fjt-{self._THREAD_TAG}-ingest",
            daemon=True,
        )
        t2 = threading.Thread(
            target=self._score,
            name=f"fjt-{self._THREAD_TAG}-score",
            daemon=True,
        )
        self._threads = [t1, t2]
        t1.start()
        t2.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        stop_sidecar = getattr(self._source, "stop_prefetch", None)
        if stop_sidecar is not None:
            # park the prefetch sidecar too: without this it would keep
            # fetching into the (bounded) handoff queue until the
            # process exits — harmless but dishonest in lag gauges
            stop_sidecar()
        self._ring.close()

    def join(self, timeout: Optional[float] = None) -> None:
        for t in self._threads:
            t.join(timeout)
        if self._error is not None:
            raise self._error

    def run_for(self, seconds: float) -> None:
        self.start()
        time.sleep(seconds)
        self.stop()
        self.join(timeout=30.0)

    def run_until_exhausted(self, timeout: float = 60.0) -> None:
        """Deterministic drain: join the ingest thread (exits once the
        source is exhausted and fully pushed), then close the ring — the
        score loop drains the ring's remainder plus its in-flight window
        before exiting. No sleep-based settle windows."""
        self.start()
        deadline = time.monotonic() + timeout
        ingest = self._threads[0]
        while ingest.is_alive() and self._error is None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            ingest.join(timeout=min(remaining, 0.05))
        self._drain_all = True
        self.stop()
        self.join(timeout=max(30.0, deadline - time.monotonic()))

    # -- subclass hooks ----------------------------------------------------

    def _acquire(self, finish_one):
        raise NotImplementedError

    def _dispatch(self, handle, X, n):
        raise NotImplementedError

    def _emit(self, out, n, first_off, decode) -> None:
        self._sink(out, n, first_off)

    def _on_idle(self) -> None:
        pass

    def _aggregate_full_batches(self, X, offsets, bs: int):
        """Opportunistic multi-chunk dispatch: when the ring is backed
        up (the first drain came back FULL), immediately drain further
        already-full batches and ship them as ONE dispatch. Each device
        dispatch pays an RPC round trip (~25 ms on the tunneled chip),
        so K chunks per dispatch amortize it K-fold exactly like the
        scan in the hand-written bench loop; a lightly-loaded stream
        never aggregates (the ring holds at most one full batch), so
        the latency operating point is untouched.

        K is rounded DOWN to a power of two ≤ ``max_dispatch_chunks``:
        the Pallas scorer compiles one scan program per distinct K, and
        a drifting backlog yielding K=3,5,6,7… would pay a mid-stream
        compile for each — power-of-two K bounds that to log2(max)
        programs. Only provably-FULL extra batches are drained (a
        partial cannot be pushed back and would force a padded
        dispatch — measured 418k → 74k rec/s on the Kafka stream when
        partials rode along). Drained views alias the ring's reuse
        buffer, hence the copies."""
        avail = 1 + len(self._ring) // bs  # full batches on hand NOW
        k_cap = self._max_dispatch_chunks
        if self._batcher is not None:
            # deadline-aware aggregation cap: a backed-up ring wants the
            # biggest dispatch, the deadline wants the smallest — the
            # capacity model's max_records() is where they meet (None =
            # no deadline/no fit yet: keep the static cap)
            mr = self._batcher.max_records()
            if mr is not None:
                k_cap = min(k_cap, max(1, mr // bs))
        k_target = 1
        while k_target * 2 <= avail and k_target * 2 <= k_cap:
            k_target *= 2
        if k_target == 1:
            return X, offsets, bs
        parts = [np.array(X, copy=True)]
        # carry the REAL drained offset arrays, never a fabricated
        # np.arange: a cycling source's wrap-to-0 can land INSIDE the
        # first drained batch (the ring stitches chunks from both sides
        # of the wrap), and synthesized-contiguous offsets would mislabel
        # every record after the wrap
        off_parts = [np.array(offsets, copy=True)]
        total = bs
        while total < bs * k_target and len(self._ring) >= bs:
            X2, off2 = self._ring.drain(0, 0)
            n2 = X2.shape[0]
            if n2 == 0:
                break
            if n2 < bs or int(off2[0]) != int(off_parts[-1][-1]) + 1:
                # offset discontinuity: cycling sources legitimately
                # wrap back to 0 (steady-state benches), and aggregating
                # across the gap would break the one-dispatch ==
                # contiguous-commit-range invariant — carry the drained
                # chunk to the NEXT loop iteration as its own dispatch
                self._carry_drain.append(
                    (np.array(X2, copy=True), np.array(off2, copy=True))
                )
                break
            parts.append(np.array(X2, copy=True))
            off_parts.append(np.array(off2, copy=True))
            total += n2
        if len(parts) == 1:
            # MUST return the copies, not the drained views: X/offsets
            # alias the ring's reuse buffer, and a discontinuous extra
            # drain above just overwrote it in place — returning the
            # aliased views would ship the carried chunk's data twice
            # and lose this batch entirely
            return parts[0], off_parts[0], bs
        X = np.concatenate(parts, axis=0)
        offsets = np.concatenate(off_parts)
        return X, offsets, total

    def _resolve_donate(self) -> bool:
        """Donation default: on unless the backend is CPU. Resolved
        once, lazily — backend identity needs jax initialized.

        The wire batch (uint8/uint16 [B, F]) can never output-alias the
        f32 score outputs, so XLA flags every donated compile with a
        "donated buffers were not usable" warning; the donation still
        releases the staging buffer to the device allocator at dispatch
        (bounding steady-state input allocations to the window depth)
        rather than holding it to fetch time, so it is kept — and the
        known-inert warning is silenced once, only when a pipeline
        actually donates, and only for the rank-wire uint dtypes
        (pipeline.filter_donate_warning — the fused f32 shape gets the
        same treatment there): an application's own f32 donation
        warnings (where failed aliasing IS actionable) stay visible."""
        if self._donate is None:
            from flink_jpmml_tpu.compile import common

            self._donate = not common.backend_is_cpu()
        if self._donate:
            filter_donate_warning(r"uint(8|16)\[")
        return self._donate

    def _dispatch_bound(self, bound: "BoundScorer", X, n):
        """Shared async dispatch through a :class:`BoundScorer` — the
        rank wire when eligible, the f32 path otherwise. The rank-wire
        hop runs through :func:`runtime.pipeline.dispatch_quantized`:
        host encode (the bucketizer folds NaN→missing — no separate
        host-side NaN pass, no f32 mask plane) or the fused on-device
        encode stage, per the scorer's autotuned ``encode_mode``.

        Rank-wire dispatches stage the batch onto the device explicitly
        (``jax.device_put``, async) and donate the staging buffer to
        the jitted call: the buffer is released to the device allocator
        at dispatch instead of being pinned until fetch, so with the
        depth-2 in-flight window steady-state input allocations stay
        bounded at two staging buffers. ``donation_hits`` counts
        dispatches whose staging buffer was actually consumed
        (invalidated) by the call — 0 on backends that ignore
        donation. ``encode_s``/``h2d_bytes`` accounting lands in this
        pipeline's metrics registry."""
        if bound.q is not None:
            # keyed state arms here — and ONLY here: recovery ladders
            # and bisection probes raise _state_bypass, so re-scored
            # records can never fold into the table twice
            st = (
                self._state
                if self._state is not None and not self._state_bypass
                else None
            )
            return dispatch_quantized(
                bound.q, X,
                donate=self._resolve_donate(),
                metrics=self.metrics,
                donation_hits=self._donation_hits,
                state=st,
                offsets=self._cur_offsets if st is not None else None,
            )
        if self._state is not None and not self._state_bypass:
            raise InputValidationException(
                "stateful scoring requires the rank-wire scorer "
                "(f32 fallback dispatch cannot carry the state stage)"
            )
        return self._score_f32(bound.model, X, n)

    def _score_f32(self, model, X, n):
        """Shared f32 fallback dispatch: NaN cells are the missing
        convention on this path; one isnan pass builds the mask (any()
        on bools is cheap), not a scan-then-rescan."""
        B = model.batch_size
        # a mesh-sharded model's data axis must divide the dispatch: a
        # degraded-mesh rebuild can leave a divisor that no longer
        # divides B (or an aggregated multiple of B), so the pad target
        # rounds up to the divisor — single-chip models (divisor 1)
        # keep the exact historical pad-to-B geometry
        target = max(B, n)
        target += (-target) % getattr(model, "batch_divisor", 1)
        Mb = np.isnan(X)
        if Mb.any():
            Xb = np.where(Mb, 0.0, X).astype(np.float32)
        else:
            Xb, Mb = X, _ZEROS_M.get(n, self._arity)
        if n < target:
            Xb, Mb, _ = prepare.pad_batch(Xb, Mb, target)
        if Xb is X:
            # a full, NaN-free batch reaches here still aliasing the
            # ring's reuse buffer; jax's CPU backend can zero-copy that
            # numpy array into the async dispatch, so the next drain
            # would overwrite an in-flight batch — ship a private copy
            # (cf. pipeline.dispatch_quantized's fused branch)
            Xb = np.array(Xb, copy=True)
        return model.predict(Xb, Mb)  # async dispatch

    # -- poison isolation (runtime/dlq.py) ---------------------------------

    def _dispatch_checked(self, handle, X, n, offsets):
        """The one dispatch entry carrying the batch's offsets past the
        fault harness: ``poison_record`` / offset-targeted
        ``worker_crash`` faults match against exactly the range being
        scored, so bisection isolates an injected poison the same way
        it isolates a real one."""
        faults.fire("score_batch", offsets=offsets)
        self._cur_offsets = offsets  # state-stage decay clock + replay guard
        return self._dispatch(handle, X, n)

    def _on_dispatch_error(self, out, meta, error) -> bool:
        """OverlappedDispatcher error hook, with device-fault triage
        FIRST (runtime/devfault.py): a sick device runs the recovery
        ladder (redispatch → OOM bisection → fallback tier) and record
        poison enters suspect mode — the PR 12 bisection must never
        quarantine clean records for a device fault. → False (re-raise)
        when the entry carries no retained batch (shed no-ops) or the
        matching plane isn't wired."""
        if meta is None or len(meta) < 7:
            return False
        n, first_off, t_start, shed, handle, X, offsets = meta[:7]
        if shed or X is None or offsets is None:
            return False
        ctx = meta[7] if len(meta) > 7 else None
        if self._state is not None and not self._state_bypass:
            # the failed dispatch donated (and thereby poisoned) the
            # state buffer and may have chained later in-flight batches
            # on it: restore the last snapshot before ANY recovery
            # re-dispatch. Bounded, counted loss (state_rollbacks);
            # the recovery paths below score statelessly.
            self._state.rollback()
        kind = devfault.classify(error)
        if kind is not None:
            if self._failover is None:
                return False  # historical fail-fast: die, restart
            self._device_recover(handle, X, offsets, error, kind, ctx=ctx)
            return True
        if self._dlq is None:
            return False
        self._suspect_scan(handle, X, offsets, error=error, ctx=ctx)
        return True

    # -- device-fault recovery ladder (runtime/devfault.py) ----------------

    def _redispatch_sync(self, handle, X, n, offsets):
        """One synchronous re-dispatch of a host-retained staging copy
        through the REAL dispatch path (fault hook sites included, so
        an injected persistent fault keeps failing here exactly like a
        real one) → (out, decode), device-synchronized."""
        faults.fire("device_dispatch")
        self._state_bypass += 1  # recovery re-scores: never re-fold state
        try:
            out, decode = self._dispatch_checked(handle, X, n, offsets)
        finally:
            self._state_bypass -= 1
        faults.fire("device_readback")
        _block_ready(out)
        return out, decode

    def set_tenant(self, tenant) -> None:
        """Re-label delivered records (the dynamic block pipeline calls
        this on a served-model swap so tenant_records follows the key
        actually serving)."""
        self._tenant = tenant

    def _book_tenant(self, n: int) -> None:
        if self._tenant is not None:
            self.metrics.counter(
                f'tenant_records{{model="{self._tenant}"}}'
            ).inc(n)

    def _emit_recovered(self, out, decode, offsets, lo, hi,
                        ctx=None, t0=None) -> None:
        """Deliver + commit one recovered run (redispatch, OOM
        sub-batch, or fallback-tier score): sink in offset order,
        freshness stamps consumed, offsets committed — idempotent with
        the sink contract because the failed dispatch never reached
        ``_complete`` (zero loss, no duplication beyond restart
        replay)."""
        n_run = hi - lo
        first = int(offsets[lo])
        self._emit(out, n_run, first, decode)
        self.metrics.counter("records_out").inc(n_run)
        self._book_tenant(n_run)
        freshness = fresh_mod.freshness_for(self.metrics)
        if freshness is not None:
            freshness.observe_sink(first, n_run)
        jstore = trace_mod.store_for(self.metrics)
        if jstore is not None:
            c = ctx if ctx is not None else trace_mod.context_for(first)
            jstore.hop(
                "sink", c.child(), first, n_run, durable=True,
                recovered=True,
            )
        if t0 is not None:
            # fallback/recovered batches are real deliveries: their
            # latency belongs in the histogram the SLO plane watches —
            # a degraded tier must not flatter p99
            self.metrics.histogram("batch_latency_s").observe(
                time.monotonic() - t0
            )
        self.committed_offset = int(offsets[hi - 1]) + 1
        self._ckpt.maybe_save(self._ckpt_state)

    def _device_recover(self, handle, X, offsets, error, kind,
                        ctx=None) -> None:
        """The recovery ladder for one device-classified dispatch
        failure: (1) transient errors re-dispatch the retained batch
        under the shared full-jitter backoff; (2) OOM bisects the
        BATCH SIZE (never the records) and feeds the proven cap back
        into the AdaptiveBatcher; (3) exhausted retries fall through
        to the fallback tier (the circuit breaker keeps later batches
        off the device entirely); (4) chip loss escalates to the
        supervisor (restart with FJT_RESTART_STREAK context)."""
        from flink_jpmml_tpu.utils.retry import Backoff

        plane = self._failover
        n = int(X.shape[0])
        first = int(offsets[0])
        key = getattr(handle, "key", None) or "default"
        plane.note_fault(kind, key, first_off=first, n=n, error=error)
        if kind == devfault.KIND_LOST:
            self._lost_recover(handle, X, offsets, error, ctx=ctx)
            return
        breaker = plane.breaker_for(key)
        breaker.record_failure(kind)
        if kind == devfault.KIND_OOM:
            self._oom_recover(handle, X, offsets, error, ctx=ctx)
            return
        bo = Backoff(
            "device", base_s=0.02, cap_s=0.5,
            max_attempts=plane.retries,
        )
        while not bo.exhausted:
            bo.sleep()
            try:
                out, decode = self._redispatch_sync(
                    handle, X, n, offsets
                )
            except Exception as e2:
                k2 = devfault.classify(e2)
                if k2 is None:
                    # the device fault cleared and a RECORD error
                    # surfaced underneath: that is poison's jurisdiction
                    if self._dlq is not None:
                        self._suspect_scan(
                            handle, X, offsets, error=e2, ctx=ctx
                        )
                        return
                    raise
                plane.note_fault(k2, key, first_off=first, n=n, error=e2)
                if k2 == devfault.KIND_LOST:
                    self._lost_recover(handle, X, offsets, e2, ctx=ctx)
                    return
                breaker.record_failure(k2)
                if k2 == devfault.KIND_OOM:
                    self._oom_recover(handle, X, offsets, e2, ctx=ctx)
                    return
                error = e2
                continue
            breaker.record_success()
            plane.redispatch_records.inc(n)
            flight.record(
                "device_redispatch", model=key, first=first, n=n,
                attempts=bo.attempts,
            )
            self._emit_recovered(out, decode, offsets, 0, n, ctx=ctx)
            return
        # retries exhausted: degraded-mode serving beats a crash loop
        if plane.tier.supports(handle):
            self._serve_fallback(handle, X, offsets, jctx=ctx)
            return
        raise error

    def _lost_recover(self, handle, X, offsets, error, ctx=None) -> None:
        """The KIND_LOST rung of the ladder, mesh-aware: a sharded
        model rebuilds over the surviving chips in place
        (``ShardedModel.without_devices`` — dispatcher state and the
        partition/key assignment carry through) and the retained batch
        redispatches synchronously on the degraded mesh: zero loss,
        (N−1)/N capacity, no process restart. A single-chip model (or
        an unsurvivable mesh) keeps the historical contract — escalate
        to the supervisor via the raise."""
        plane = self._failover
        n = int(X.shape[0])
        first = int(offsets[0])
        key = getattr(handle, "key", None) or "default"
        rebuilt = self._mesh_rebuild(handle, error)
        if rebuilt is None:
            flight.record(
                "device_lost_escalate", model=key, first=first, n=n,
                error=repr(error),
            )
            raise error
        try:
            out, decode = self._redispatch_sync(handle, X, n, offsets)
        except Exception as e2:
            k2 = devfault.classify(e2)
            if k2 is None:
                # the chip loss cleared and a RECORD error surfaced
                # underneath: poison's jurisdiction
                if self._dlq is not None:
                    self._suspect_scan(
                        handle, X, offsets, error=e2, ctx=ctx
                    )
                    return
                raise
            # the degraded mesh is live but THIS dispatch failed again:
            # re-enter the ladder from the top (another KIND_LOST
            # shrinks once more — bounded, without_devices raises once
            # no full data row survives)
            self._device_recover(handle, X, offsets, e2, k2, ctx=ctx)
            return
        plane.redispatch_records.inc(n)
        flight.record(
            "mesh_rebuild_redispatch", model=key, first=first, n=n,
            data=rebuilt.batch_divisor,
        )
        self._emit_recovered(out, decode, offsets, 0, n, ctx=ctx)

    def _mesh_rebuild(self, handle, error):
        """Chip loss on a mesh-sharded model: rebuild over the
        survivors and adopt the rebuilt model into the live scoring
        handle → the rebuilt :class:`ShardedModel`, or None when there
        is no mesh to shrink (single-chip model, one data row left) or
        no survivable rebuild."""
        model = getattr(handle, "model", None)
        if not hasattr(model, "without_devices"):
            return None
        lost = self._lost_devices(model, error)
        if not lost:
            return None
        try:
            rebuilt = model.without_devices(lost)
        except FlinkJpmmlTpuError:
            return None  # unsurvivable: escalate like a single chip
        self._adopt_rebuilt(handle, rebuilt)
        self.metrics.counter("mesh_rebuilds").inc()
        self.metrics.gauge("mesh_lost_devices").set(float(len(lost)))
        if self._mesh_obs is not None:
            self._mesh_obs.note_rebuild(rebuilt, lost)
        flight.record(
            "mesh_rebuild",
            lost=[str(getattr(d, "id", d)) for d in lost],
            data=rebuilt.batch_divisor,
        )
        return rebuilt

    def _lost_devices(self, model, error) -> list:
        """Which device(s) died. The runtime rarely names the chip in
        the raised error (XLA's loss surfaces as a bare UNAVAILABLE),
        so: an explicit ``error.devices``/``error.device`` attribute
        wins; otherwise the LAST data row of the mesh is retired —
        retiring any one full row restores (N−1)/N capacity with the
        model axis intact, and last-row is the choice every process
        derives identically with no coordination (row identity — the
        first device of each surviving row — is what the carried
        ChipAssignment's rendezvous weights key on, so survivor rows
        keep their partitions and keys)."""
        dev = getattr(error, "devices", None)
        if dev is None:
            dev = getattr(error, "device", None)
        if dev is not None:
            if isinstance(dev, (list, tuple, set, frozenset)):
                return list(dev)
            return [dev]
        mesh = getattr(model, "mesh", None)
        if mesh is None:
            return []
        from flink_jpmml_tpu.parallel.mesh import DATA_AXIS

        rows = mesh.devices.reshape(mesh.shape[DATA_AXIS], -1)
        if rows.shape[0] <= 1:
            return []  # one data row left: nothing to shrink onto
        return list(rows[-1])

    def _adopt_rebuilt(self, handle, rebuilt) -> None:
        """Swap the rebuilt model into the live scoring handle (the
        BoundScorer's decode closure follows ``handle.model``, so the
        sink path needs no rebind)."""
        handle.model = rebuilt
        if self._state is not None:
            # chip loss moves state WITH its keys: slot = hash %
            # capacity is mesh-independent, so re-placing the value
            # buffer over the survivors preserves every key's state
            mesh = getattr(rebuilt, "mesh", None)
            if mesh is not None:
                self._state.migrate(mesh)

    def _oom_recover(self, handle, X, offsets, error, ctx=None) -> None:
        """Device-OOM ladder step: bisect the BATCH SIZE until runs
        fit, deliver each run in offset order, and feed the largest
        proven size into the AdaptiveBatcher as the standing dispatch
        cap. Records are never quarantined — an allocator refusal says
        nothing about the data."""
        plane = self._failover
        n = int(X.shape[0])
        key = getattr(handle, "key", None) or "default"
        state = {"max_ok": 0}

        def attempt(lo: int, hi: int) -> None:
            size = hi - lo
            try:
                out, decode = self._redispatch_sync(
                    handle, X[lo:hi], size, offsets[lo:hi]
                )
            except Exception as e2:
                k2 = devfault.classify(e2)
                if k2 is None:
                    if self._dlq is not None:
                        self._suspect_scan(
                            handle, X[lo:hi], offsets[lo:hi],
                            error=e2, ctx=ctx,
                        )
                        return
                    raise
                plane.note_fault(
                    k2, key, first_off=int(offsets[lo]), n=size,
                    error=e2,
                )
                if k2 == devfault.KIND_LOST:
                    self._lost_recover(
                        handle, X[lo:hi], offsets[lo:hi], e2, ctx=ctx
                    )
                    return
                plane.breaker_for(key).record_failure(k2)
                if size == 1:
                    # one record alone exceeds the device: the host
                    # tier serves it (or the worker escalates) — a
                    # sick device never quarantines a clean record
                    if plane.tier.supports(handle):
                        self._serve_fallback(
                            handle, X[lo:hi], offsets[lo:hi], jctx=ctx
                        )
                        return
                    raise e2
                mid = (lo + hi) // 2
                attempt(lo, mid)
                attempt(mid, hi)
                return
            state["max_ok"] = max(state["max_ok"], size)
            plane.redispatch_records.inc(size)
            self._emit_recovered(
                out, decode, offsets, lo, hi, ctx=ctx
            )

        attempt(0, n)
        plane.oom_shrinks.inc()
        cap = state["max_ok"] or None
        if cap and self._batcher is not None:
            cap = self._batcher.note_oom_cap(cap)
        flight.record(
            "oom_batch_shrink", model=key, from_records=n,
            to_records=cap,
        )
        plane.record_success(key)

    def _fallback_dispatch(self, handle, X, n):
        """Host-tier scoring hook → (out, decode) in the subclass's
        sink shape (the static path's sink takes no decode)."""
        return self._failover.tier.score_bound(handle, X), None

    def _fallback_checked(self, handle, X, n, offsets):
        """The fallback tier's ``_dispatch_checked`` twin: still a
        real scoring site, so record-targeted faults (and real record
        poison) strike it exactly like the device path."""
        faults.fire("score_batch", offsets=offsets)
        return self._fallback_dispatch(handle, X, n)

    def _serve_fallback(self, handle, X, offsets, jctx=None) -> None:
        """Score one batch on the host fallback tier — the pipeline
        keeps serving degraded instead of crash-looping while the
        circuit is open (or the ladder exhausted its retries). Record
        poison that surfaces HERE isolates on the tier that hit it
        (the suspect scan's sub-dispatches route through the fallback
        twin) — an open circuit must not exempt poison from the DLQ
        contract, nor isolation re-dispatch to the sick device."""
        plane = self._failover
        n = int(X.shape[0])
        first = int(offsets[0])
        key = getattr(handle, "key", None) or "default"
        freshness = fresh_mod.freshness_for(self.metrics)
        if freshness is not None:
            # the fallback tier IS the dispatch stage while degraded
            freshness.propagate_low_watermark("dispatch", first, n)
        t0 = time.monotonic()
        try:
            out, decode = self._fallback_checked(handle, X, n, offsets)
        except Exception as e:
            if devfault.classify(e) is not None or self._dlq is None:
                raise
            self._suspect_scan(
                handle, X, offsets, error=e, ctx=jctx,
                dispatch=self._fallback_checked,
            )
            return
        plane.note_fallback(n, key)
        self._emit_recovered(
            out, decode, offsets, 0, n, ctx=jctx, t0=t0
        )

    def _suspect_scan(
        self, handle, X, offsets, error, persist: bool = False,
        ctx=None, dispatch=None,
    ) -> None:
        """Bisection ("suspect mode") over one failed batch: dispatch
        halves synchronously until the offending record(s) are single —
        those go to the DLQ (never the sink); every clean run proceeds
        to the sink in offset order. The whole range then commits, so a
        restart never replays the quarantined record back to life.

        ``persist=True`` (crash-loop fingerprint mode) additionally
        writes the suspect MARKER before every sub-dispatch: a record
        that kills the process outright narrows the marker by one
        bisection level per incarnation, and a single-record marker is
        quarantined WITHOUT being dispatched at all.

        More than ``FJT_DLQ_MAX_PER_BATCH`` quarantines in one batch
        aborts isolation (:class:`PoisonIsolationOverflow`): that is a
        model-level failure, not poison.

        ``dispatch`` overrides the sub-dispatch primitive (default:
        the device path's ``_dispatch_checked``) — the fallback tier
        passes its host-tier twin so poison that surfaces while the
        circuit is OPEN isolates on the tier that hit it, never by
        re-dispatching to the sick device."""
        dispatch = dispatch if dispatch is not None else (
            self._dispatch_checked
        )
        if self._state is not None:
            # bisection probes score records MORE THAN ONCE (and DLQ'd
            # records must never land at all): every sub-dispatch of
            # the scan runs with the state stage disarmed
            inner_dispatch = dispatch

            def dispatch(h, Xs, ns, os_, _inner=inner_dispatch):
                self._state_bypass += 1
                try:
                    return _inner(h, Xs, ns, os_)
                finally:
                    self._state_bypass -= 1
        n = int(X.shape[0])
        if n == 0:
            return
        freshness = fresh_mod.freshness_for(self.metrics)
        records_out = self.metrics.counter("records_out")
        cap = env_count("FJT_DLQ_MAX_PER_BATCH", 32)
        state = {"q": 0}
        # journey trail (obs/trace.py): isolation is exactly the story
        # fjt-trace exists to tell, so every bisection hop is durable
        jstore = trace_mod.store_for(self.metrics)
        if ctx is None and jstore is not None:
            ctx = trace_mod.context_for(int(offsets[0]))
        if jstore is not None:
            jstore.hop(
                "suspect_scan", ctx, int(offsets[0]), n, durable=True,
                persist=persist,
                error=None if error is None else repr(error),
            )
        flight.record(
            "poison_isolation",
            first=int(offsets[0]), n=n, persist=persist,
            error=None if error is None else repr(error),
            trace_id=None if ctx is None else ctx.trace_id,
        )
        self._suspect_gauge.set(1.0)

        def quarantine(i: int, exc, reason=REASON_SCORE, attempts=1):
            if state["q"] >= cap:
                raise PoisonIsolationOverflow(
                    state["q"], exc if exc is not None else error
                )
            state["q"] += 1
            off = int(offsets[i])
            # the terminal hop + the envelope's trace context: the ids
            # the DLQ carries are what fjt-dlq redrive stamps into the
            # traceparent header, linking the redriven journey segment
            rctx = trace_mod.TraceContext(
                trace_mod.trace_id_for(off),
                parent_id=None if ctx is None else ctx.span_id,
            )
            if jstore is not None:
                jstore.terminal(
                    "dlq", rctx, offset=off, reason=reason,
                    attempts=attempts,
                )
            self._dlq.quarantine(
                X[i].tobytes(), offset=off, reason=reason, error=exc,
                attempts=attempts, model=getattr(handle, "key", None),
                trace_id=rctx.trace_id, span_id=rctx.span_id,
            )
            if freshness is not None:
                # a quarantined record was DROPPED, not delivered: its
                # ingest stamp must not advance the sink watermark or
                # the staleness books (the PR 8 shed contract)
                freshness.discard_stamps(off, 1)

        def emit_run(out, decode, lo: int, hi: int):
            n_run = hi - lo
            first = int(offsets[lo])
            self._emit(out, n_run, first, decode)
            records_out.inc(n_run)
            self._book_tenant(n_run)
            if jstore is not None:
                jstore.hop(
                    "sink", ctx.child(), first, n_run, durable=True,
                    isolated=True,
                )
            if freshness is not None:
                freshness.observe_sink(first, n_run)

        def scan(lo: int, hi: int):
            if hi <= lo:
                return
            n_sub = hi - lo
            off_lo, off_hi = int(offsets[lo]), int(offsets[hi - 1]) + 1
            dm = self._death_marker if persist else None
            if dm is not None and off_lo <= dm["lo"] and dm["hi"] <= off_hi:
                # a previous incarnation DIED dispatching dm's range
                if dm["hi"] - dm["lo"] == 1:
                    hit = np.nonzero(
                        offsets[lo:hi] == np.uint64(dm["lo"])
                    )[0]
                    if hit.size:
                        i = lo + int(hit[0])
                        scan(lo, i)
                        quarantine(
                            i, None, reason=REASON_CRASH_LOOP,
                            attempts=dm.get("attempts", 1),
                        )
                        self._death_marker = None
                        self._fingerprint.clear_marker()
                        scan(i + 1, hi)
                        return
                elif n_sub > 1:
                    # never re-dispatch a span that already killed a
                    # process whole: split first (one narrowing per
                    # death bounds convergence at log2(batch) restarts)
                    mid = (lo + hi) // 2
                    scan(lo, mid)
                    scan(mid, hi)
                    return
            if persist and self._fingerprint is not None:
                attempts = 1
                if (
                    dm is not None
                    and dm["lo"] == off_lo and dm["hi"] == off_hi
                ):
                    attempts = dm.get("attempts", 1) + 1
                self._fingerprint.write_marker(off_lo, off_hi, attempts)
                if jstore is not None:
                    # the marker's journey twin, written BEFORE the
                    # sub-dispatch: if this range kills the process the
                    # hop survives — "the dispatch that died" stays
                    # visible across the incarnation boundary
                    jstore.hop(
                        "suspect_dispatch", ctx.child(),
                        off_lo, off_hi - off_lo, durable=True,
                        attempts=attempts,
                    )
            try:
                out, decode = dispatch(
                    handle, X[lo:hi], n_sub, offsets[lo:hi]
                )
                _block_ready(out)
            except PoisonIsolationOverflow:
                raise
            except Exception as e:
                if devfault.classify(e) is not None:
                    # a SICK DEVICE mid-bisection is not record
                    # poison: quarantining clean records for it is the
                    # one thing this scan must never do — escalate
                    # (already-emitted runs replay on restore, the
                    # at-least-once contract)
                    raise
                if n_sub == 1:
                    quarantine(lo, e)
                    return
                mid = (lo + hi) // 2
                scan(lo, mid)
                scan(mid, hi)
                return
            emit_run(out, decode, lo, hi)

        try:
            scan(0, n)
        finally:
            self._suspect_gauge.set(
                1.0 if self._suspect_until is not None else 0.0
            )
        if persist and self._fingerprint is not None:
            self._fingerprint.clear_marker()
            self._death_marker = None
        # the WHOLE range commits — quarantined offsets included, so a
        # restart cannot replay a parked poison record back to life
        self.committed_offset = int(offsets[-1]) + 1
        if state["q"]:
            flight.record(
                "poison_isolated", quarantined=state["q"],
                first=int(offsets[0]), n=n,
            )
        self._ckpt.maybe_save(self._ckpt_state)

    def _exit_suspect_mode(self) -> None:
        flight.record(
            "poison_suspect_exit", committed=self.committed_offset
        )
        self._suspect_until = None
        self._death_marker = None
        if self._fingerprint is not None:
            self._fingerprint.clear_marker()
        self._suspect_gauge.set(0.0)
        jstore = trace_mod.store_for(self.metrics)
        if jstore is not None:
            jstore.hop(
                "suspect_exit",
                trace_mod.context_for(self.committed_offset),
                first_off=self.committed_offset, durable=True,
            )
            # back to tail-sampled buffering — unless a fault drill (or
            # FJT_JOURNEY_SYNC) armed write-through for the process
            jstore.write_through = bool(
                faults.active() or os.environ.get("FJT_JOURNEY_SYNC")
            )

    # -- internals ---------------------------------------------------------

    def _ingest(self) -> None:
        records_in = self.metrics.counter("records_in")
        try:
            while not self._stop.is_set():
                polled = self._source.poll()
                if polled is None:
                    if self._source.exhausted:
                        return
                    time.sleep(0.0005)
                    continue
                off, block = polled
                pushed = 0
                while pushed < block.shape[0] and not self._stop.is_set():
                    pushed += self._ring.push_block(
                        block[pushed:], off + pushed, timeout_us=100_000
                    )
                records_in.inc(block.shape[0])
        except BaseException as e:
            self._error = e
            self._stop.set()

    def _score(self) -> None:
        batch_cfg = self._config.batch
        records_out = self.metrics.counter("records_out")
        batches = self.metrics.counter("batches")
        fill = self.metrics.counter("batch_fill_records")
        # fixed-bucket histogram, not a reservoir: N workers' bucket
        # counts ADD, so the supervisor's fleet /metrics view can merge
        # per-worker latency distributions exactly (utils/metrics.py)
        lat = self.metrics.histogram("batch_latency_s")

        ledger = attr_mod.ledger_for(self.metrics)
        # the freshness plane (event-time watermarks + staleness) and
        # the composite backpressure score: both per-registry singletons
        # shared with the source (which stamps event times at fetch)
        # and ticked from this loop — the SLOTracker piggyback pattern,
        # no thread of their own
        freshness = fresh_mod.freshness_for(self.metrics)
        monitor = pressure_mod.pressure_for(self.metrics)
        # the data-drift plane (obs/drift.py): None unless
        # FJT_DRIFT_SAMPLE is set or a bench mode armed it — predictions
        # are sketched at the sink, features already rode
        # dispatch_quantized; its monitor ticks from these record calls
        dplane = drift_mod.plane_for(self.metrics)
        # record-journey tracing (obs/trace.py): None unless
        # FJT_JOURNEY_DIR armed it — one env check at loop start, and
        # with it None every per-batch site below is a None test
        jstore = trace_mod.store_for(self.metrics)
        ring_occ = self.metrics.gauge("ring_occupancy")
        ring_cap = float(max(self._config.batch.queue_capacity, 1))

        replayed = self.metrics.counter("records_replayed")

        def _complete(pair, meta):
            """FIFO completion off the dispatcher: sink, then commit —
            offsets only advance past records that reached the sink.
            A SHED entry (admission refusal, a no-op through the same
            FIFO window) commits its offsets and consumes its freshness
            stamps without ever touching the sink — the drop is
            explicit, bounded, and replay-consistent."""
            n, first_off, t_start, shed = meta[:4]
            jctx = meta[7] if len(meta) > 7 else None
            if first_off < self._replay_until:
                # at-least-once replay accounting: records below the
                # previous incarnation's in-flight high-water mark are
                # re-deliveries, not new progress
                replayed.inc(min(n, self._replay_until - first_off))
            if shed:
                self.committed_offset = first_off + n
                if freshness is not None:
                    freshness.discard_stamps(first_off, n)
                self._ckpt.maybe_save(self._ckpt_state)
                if monitor is not None:
                    monitor.maybe_tick()
                return
            out, decode = pair
            derived = None
            if self._state is not None:
                # a state-armed dispatch returns (score_out, derived):
                # the sink sees exactly the stateless output shape,
                # and the derived session features feed the drift
                # plane under the model's "#state" label (state
                # corruption surfaces as feature drift)
                out, derived = state_mod.split_output(out)
            t_sink = time.monotonic()
            # the completing batch's OWN context wraps the sink: its
            # span (and any exemplar the sink stage captures) must
            # carry THIS journey's ids, not whichever batch the score
            # loop happens to be launching right now
            with trace_mod.use(jctx):
                self._emit(out, n, first_off, decode)
                t_done = time.monotonic()
                spans.emit(
                    "sink", t_sink, t_done - t_sink, n=n,
                    first_off=first_off,
                )
                if ledger is not None:
                    ledger.observe("sink", t_done - t_sink)
            if dplane is not None:
                # score-distribution sketch at the sink (sampled): shed
                # batches never reach here, so a shed record can no
                # more skew the prediction baseline than the watermark
                dplane.record_predictions(
                    getattr(decode, "model_hash", None)
                    or getattr(decode, "model_key", None),
                    out, n,
                )
                if derived is not None:
                    state_mod.record_derived(
                        dplane, self._state,
                        getattr(
                            getattr(meta[4], "q", None)
                            if len(meta) > 4 else None,
                            "model_hash", None,
                        ),
                        derived, n,
                    )
            if jstore is not None and jctx is not None:
                # the sink hop closes the journey: tail-sampling keeps
                # it only if it is interesting (exemplar-marked, head
                # sample, terminal elsewhere)
                jstore.finish(
                    jctx, first_off, n, latency_s=t_done - t_start,
                )
            lat.observe(t_done - t_start)
            records_out.inc(n)
            self._book_tenant(n)
            if self._mesh_obs is not None:
                # per-chip accounting (obs/mesh.py): one call per BATCH
                # — a data-parallel dispatch spans every chip equally,
                # so the split is arithmetic, not a per-record loop
                self._mesh_obs.note_batch(n, len(disp))
            if self._failover is not None:
                # green completion: clears strike streaks / counts a
                # half-open probe (a dict miss while no breaker exists)
                self._failover.record_success(
                    getattr(meta[4], "key", None) if len(meta) > 4
                    else None
                )
            if self._batcher is not None:
                # the capacity model's verify half: every completed
                # dispatch is a (size, latency) observation
                self._batcher.observe(n, t_done - t_start)
            self.committed_offset = first_off + n
            if freshness is not None:
                # consume the source's ingest stamps for this offset
                # range: record_staleness_s books + the sink-stage
                # watermark (watermark_ts) advance here, after delivery
                freshness.observe_sink(first_off, n)
            self._ckpt.maybe_save(self._ckpt_state)
            if self._slo is not None:
                self._slo.maybe_tick()
            if monitor is not None:
                monitor.maybe_tick()

        # the overlapped in-flight window: batch N executes on device
        # while batch N+1 is drained, encoded, and staged here — the
        # window only ever blocks on its own oldest dispatch, so the
        # ring's fill-or-deadline semantics are untouched. in_flight=1
        # keeps its historical meaning (finish every batch before the
        # next drain — the latency operating point) via depth 0.
        disp = OverlappedDispatcher(
            depth=self._in_flight_max if self._in_flight_max > 1 else 0,
            metrics=self.metrics,
            complete=_complete,
            # record-level poison isolation: a scoring exception runs
            # the suspect-mode bisection instead of killing the worker
            # (only when a DLQ is wired — without one the historical
            # fail-fast behavior is unchanged)
            on_error=self._on_dispatch_error,
        )

        try:
            while True:
                if self._stop.is_set() and not self._drain_all:
                    break  # stop(): skip the uncommitted backlog
                # worker-wedge injection point (runtime/faults.py): a
                # global load + None check when no faults are configured
                faults.fire("score_loop")
                # with work in flight the first-record wait must be
                # bounded: an indefinitely-blocked drain on a paused
                # feed would pin completed batches uncommitted (and
                # their offsets unsaved) until new data arrives
                idle_us = (
                    min(batch_cfg.deadline_us, 20_000)
                    if len(disp) and self._IDLE_WAIT_US < 0
                    else self._IDLE_WAIT_US
                )
                if monitor is not None:
                    # pre-drain occupancy peak-hold: the saturation
                    # signal a post-drain gauge read undersamples when
                    # one aggregated drain empties half the ring
                    monitor.note_ring(
                        min(len(self._ring) / ring_cap, 1.0)
                    )
                if self._carry_drain:
                    X, offsets = self._carry_drain.pop(0)
                else:
                    X, offsets = self._ring.drain(
                        batch_cfg.deadline_us, idle_us
                    )
                n = X.shape[0]
                # ring fill fraction AFTER the drain: the producer-side
                # saturation input to the pressure score (1.0 = the
                # ingest thread is blocked pushing)
                ring_occ.set(min(len(self._ring) / ring_cap, 1.0))
                if (
                    n == self._batch_size  # drain limit = model batch
                    and self._max_dispatch_chunks > 1
                ):
                    X, offsets, n = self._aggregate_full_batches(
                        X, offsets, self._batch_size
                    )
                if n == 0:
                    if self._ring.closed:
                        break
                    # idle stream: the in-flight window would otherwise
                    # hold completed batches uncommitted until NEW data
                    # arrives — unbounded tail latency (and a stuck
                    # committed_offset) on a paused feed. Flush it.
                    disp.flush()
                    self._on_idle()
                    continue
                if self._dlq is not None and n > 1:
                    # the delivery-correctness plane needs exact
                    # (first_off, n) sink labeling and commits, but a
                    # decode-quarantined record leaves an offset GAP
                    # that the ring can stitch into one drained batch
                    # (run tail + next run): split at the first break
                    # and carry the remainder as its own dispatch
                    brk = np.nonzero(
                        np.diff(offsets.astype(np.int64)) != 1
                    )[0]
                    if brk.size:
                        cut = int(brk[0]) + 1
                        self._carry_drain.insert(0, (
                            np.array(X[cut:], copy=True),
                            np.array(offsets[cut:], copy=True),
                        ))
                        X, offsets = X[:cut], offsets[:cut]
                        n = cut
                if self._admission is not None:
                    self._admission.maybe_tick()
                    if not self._admission.admit(self._shed_lane, n):
                        # explicit load shed: the batch rides the FIFO
                        # window as a no-op entry, so its offsets still
                        # commit strictly in launch order behind the
                        # in-flight dispatches — the sink never sees it
                        # and a restore replays nothing extra; the
                        # entry is UNACCOUNTED (no device work — it
                        # must not dilute the dispatch counters the
                        # pressure score divides by)
                        if jstore is not None and n:
                            # the shed decision IS the journey's point:
                            # terminal hop, always kept
                            jstore.terminal(
                                "shed",
                                trace_mod.context_for(int(offsets[0])),
                                int(offsets[0]), n,
                                lane=self._shed_lane,
                            )
                        disp.launch(
                            lambda: None,
                            meta=(
                                n, int(offsets[0]) if n else 0,
                                time.monotonic(), True, None, None, None,
                            ),
                            accounted=False,
                        )
                        continue
                handle = self._acquire(disp.finish_oldest)
                if handle is None:
                    # abandoned (dynamic give-up): drop un-fetched work;
                    # records replay from the committed offset on restore
                    disp.abandon()
                    return
                if self._retain_batches:
                    # isolation AND device-fault recovery need the RAW
                    # batch retained past the async dispatch (the
                    # drained views alias the ring's reuse buffer): one
                    # private copy per batch, paid only when a DLQ or
                    # the failover plane is wired
                    X = np.array(X, copy=True)
                    offsets = np.array(offsets, copy=True)
                first_off = int(offsets[0]) if n else 0
                self._dispatched_hi = max(self._dispatched_hi, first_off + n)
                if (
                    self._suspect_until is not None
                    and first_off < self._suspect_until
                ):
                    # crash-loop fingerprint: this range killed previous
                    # incarnations — score it synchronously under
                    # persisted suspect markers so a process-killing
                    # record converges to a DLQ entry across restarts.
                    # Flush first: the marker protocol and the FIFO
                    # commit contract both need nothing else in flight.
                    disp.flush()
                    self._suspect_scan(
                        handle, X, offsets, error=None, persist=True,
                        ctx=(
                            trace_mod.context_for(first_off)
                            if jstore is not None else None
                        ),
                    )
                    if self.committed_offset >= self._suspect_until:
                        self._exit_suspect_mode()
                    batches.inc()
                    fill.inc(n)
                    continue
                if (
                    self._failover is not None
                    and self._failover.should_fallback(
                        getattr(handle, "key", None), handle
                    )
                ):
                    # circuit OPEN for this model: the window must
                    # drain first (FIFO commit order), then this batch
                    # serves synchronously on the host fallback tier —
                    # degraded, not down
                    disp.flush()
                    self._serve_fallback(
                        handle, X, offsets,
                        jctx=(
                            trace_mod.context_for(first_off)
                            if jstore is not None else None
                        ),
                    )
                    batches.inc()
                    fill.inc(n)
                    continue
                if freshness is not None:
                    # stage-boundary watermark propagation: the batch
                    # crossing ring→device advances the dispatch-stage
                    # watermark with ITS OWN ingest-stamp event times
                    # (exported as watermark_stage_ts{stage="dispatch"},
                    # fleet MIN) — under backpressure the ring holds old
                    # records, and the fetch-time watermark would lie;
                    # monotone by construction, so a replayed or
                    # out-of-order chunk can never regress it
                    freshness.propagate_low_watermark(
                        "dispatch", int(offsets[0]) if n else None, n
                    )
                t_start = time.monotonic()
                # the batch's journey context: trace id derived purely
                # from first_off (deterministic across incarnations and
                # — later — chips), one dispatch hop per BATCH so the
                # fan-out to per-record journeys costs nothing per
                # record; active around the launch so the featurize/
                # h2d/readback spans and any exemplar carry its ids
                jctx = (
                    trace_mod.context_for(first_off)
                    if jstore is not None else None
                )
                if jstore is not None:
                    jstore.hop(
                        "dispatch", jctx, first_off, n,
                        model=getattr(handle, "key", None),
                    )
                    if (
                        self._state is not None
                        and not self._state_bypass
                    ):
                        # the state read/update rides THIS dispatch:
                        # one hop per batch so fjt-trace shows the
                        # session-state hop in the journey
                        jstore.hop(
                            "state", jctx, first_off, n,
                            resident=self._state.resident,
                        )
                try:
                    with trace_mod.use(jctx):
                        disp.launch(
                            lambda h=handle, X=X, n=n, o=offsets: (
                                self._dispatch_checked(h, X, n, o)
                            ),
                            meta=(
                                n, first_off, t_start, False,
                                handle,
                                X if self._retain_batches else None,
                                offsets if self._retain_batches else None,
                                jctx,
                            ),
                            # opts this launch into the sampled
                            # device-timing pool (rate-limited;
                            # obs/profiler.py) — the live MFU/membw
                            # gauges and the kernel cost ledger; skipped
                            # entirely when profiling is off
                            profile=(
                                attr_mod.dispatch_profile(handle, n)
                                if disp.profiling else None
                            ),
                        )
                except PoisonIsolationOverflow:
                    raise  # isolation already abandoned: die honestly
                except Exception as e:
                    # the dispatch itself raised (host featurize, an
                    # injected poison, a device fault at launch time):
                    # device-fault triage FIRST — errors from OLDER
                    # window entries were already handled (or
                    # re-raised) inside launch's trim via on_error, so
                    # this exception belongs to THIS batch
                    kind = devfault.classify(e)
                    if (
                        self._state is not None
                        and not self._state_bypass
                        and (kind is not None and self._failover
                             is not None
                             or kind is None and self._dlq is not None)
                    ):
                        # a recoverable launch failure may have half-
                        # applied this batch to the table (host mirror
                        # mutated, device update never dispatched):
                        # restore the snapshot before recovery
                        self._state.rollback()
                    if kind is not None and self._failover is not None:
                        # older in-flight batches must commit BEFORE
                        # this one's synchronous recovery commits its
                        # range (FIFO contract)
                        disp.flush()
                        self._device_recover(
                            handle, X, offsets, e, kind, ctx=jctx
                        )
                    elif kind is not None or self._dlq is None:
                        raise
                    else:
                        disp.flush()
                        self._suspect_scan(
                            handle, X, offsets, error=e, ctx=jctx
                        )
                batches.inc()
                fill.inc(n)
            disp.close()  # drain the window: every dispatched batch sinks
            self._ckpt.save_now(self._ckpt_state)  # clean drain → exact resume
        except BaseException as e:
            self._error = e
            self._stop.set()


class BlockPipeline(BlockPipelineBase):
    """source → ring → padded batches → async scoring → sink.

    ``sink(out, n: int, first_offset: int)`` receives raw device outputs
    (decode is the caller's choice — fetching to host costs a D2H transfer
    per batch; use :meth:`decode` to turn one into ``Prediction``s). When
    the model is rank-wire eligible (``use_quantized``, the default) the
    scoring hop is the quantized path of compile/qtrees.py: the drained f32
    block is encoded to threshold ranks by the multithreaded C++ bucketizer
    and ``out`` is the QuantizedScorer output; otherwise ``out`` is a
    :class:`ModelOutput` from the f32 path. ``backend`` says which engaged
    and is also recorded in metrics as ``scorer_backend_*``.
    """

    def __init__(
        self,
        source: BlockSource,
        model: CompiledModel,
        sink: Callable,
        config: Optional[RuntimeConfig] = None,
        metrics: Optional[MetricsRegistry] = None,
        use_native: bool = True,
        in_flight: int = 2,
        use_quantized: bool = True,
        checkpoint=None,
        max_dispatch_chunks: int = 8,
        donate: Optional[bool] = None,
        slo=None,
        batcher=None,
        admission=None,
        shed_lane: str = "block",
        dlq=None,
        prefetch: Optional[bool] = None,
        failover=None,
        mesh=None,
        state=None,
    ):
        if model.batch_size is None:
            raise InputValidationException(
                "BlockPipeline needs a fixed-batch compiled model "
                "(compile_pmml(batch_size=...))"
            )
        if mesh is not None and not hasattr(model, "without_devices"):
            # promote the compiled model onto the mesh (ROADMAP item
            # 1): batch sharded over the data axis, wide params TP-
            # sharded over the model axis — the scoring contract and
            # the sink shape are unchanged (ShardedModel proxies the
            # CompiledModel surface). An already-sharded model passes
            # through untouched.
            from flink_jpmml_tpu.parallel.mesh import DATA_AXIS
            from flink_jpmml_tpu.parallel.sharding import mesh_sharded

            n_data = int(mesh.shape.get(DATA_AXIS, 1))
            if model.batch_size % max(n_data, 1) != 0:
                raise InputValidationException(
                    f"batch_size {model.batch_size} must divide by the "
                    f"mesh data-axis size {n_data}"
                )
            model = mesh_sharded(model, mesh)
        if hasattr(model, "in_flight_depth"):
            # mesh-aware in-flight window: deep enough to cover the
            # data rows (parallel/assignment.mesh_in_flight), recorded
            # as carried dispatch state so a degraded-mesh rebuild
            # keeps the window geometry without re-derivation
            in_flight = model.in_flight_depth(in_flight)
            model.with_dispatch_state(in_flight=in_flight)
            if getattr(model, "assignment", None) is None:
                from flink_jpmml_tpu.parallel.assignment import (
                    assignment_for,
                )

                model.assignment = assignment_for(
                    model.mesh, getattr(source, "partitions", ()) or ()
                )
        super().__init__(
            source=source,
            sink=sink,
            arity=model.field_space.arity,
            batch_size=model.batch_size,
            config=config,
            metrics=metrics,
            use_native=use_native,
            in_flight=in_flight,
            checkpoint=checkpoint,
            max_dispatch_chunks=max_dispatch_chunks,
            donate=donate,
            slo=slo,
            batcher=batcher,
            admission=admission,
            shed_lane=shed_lane,
            dlq=dlq,
            prefetch=prefetch,
            failover=failover,
            state=state,
        )
        self._bound = BoundScorer("static", model, use_quantized)
        self.backend = self._bound.backend
        self.metrics.counter(f"scorer_backend_{self.backend}").inc()
        if self._state is not None:
            if self._bound.q is None:
                raise InputValidationException(
                    "stateful scoring requires the rank-wire scorer: "
                    "this model is not quantized-eligible (or "
                    "use_quantized=False)"
                )
            model_mesh = getattr(model, "mesh", None)
            if model_mesh is not None:
                # shard the table over the mesh data axis alongside
                # the model it rides with
                self._state.shard(model_mesh)
        if hasattr(model, "batch_divisor"):
            from flink_jpmml_tpu.obs import mesh as mesh_obs

            self._mesh_obs = mesh_obs.telemetry_for(self.metrics, model)

    def decode(self, out, n: int):
        """Sink-received raw output → ``Prediction`` list (host-side).
        A state-armed pipeline's sink still receives the stateless
        output shape (the pipeline unwraps the derived features before
        the sink), but decode also tolerates a raw fused pair."""
        out, _ = state_mod.split_output(out)
        return self._bound.decode(out, n)

    def _acquire(self, finish_one):
        return self._bound  # one static model: nothing to resolve

    def _dispatch(self, bound, X, n):
        return self._dispatch_bound(bound, X, n), None


class _ZerosMCache:
    """Reused all-False missing masks (avoid reallocating 256KB per batch)."""

    def __init__(self):
        self._cache = {}

    def get(self, b: int, f: int) -> np.ndarray:
        key = (b, f)
        m = self._cache.get(key)
        if m is None:
            m = np.zeros((b, f), bool)
            self._cache[key] = m
        return m


_ZEROS_M = _ZerosMCache()
