"""Stream sources: pluggable record producers with restartable offsets.

Reference parity: Flink sources (``fromCollection``, Kafka connectors, …)
feeding the evaluation operator (SURVEY.md §4.1, §8 step 3). Every source
exposes a monotonically increasing *offset* so checkpoints can record "scored
up to here" and resume exactly (capability C7 — the reference inherited this
from Flink's source-offset checkpoints).

A record can be anything the pipeline's extractor understands: a dict of
field→value, a numpy vector, or an arbitrary event object.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, List, Sequence, Tuple

Record = Any
# poll() → list of (offset, record); offset is the position *after* the record
Polled = List[Tuple[int, Record]]


class Source:
    """Protocol: poll records in offset order; seek for resume.

    ``event_time_fn`` (optional): ``record -> unix seconds`` (or None
    for a record with no event time). Sources that know their records'
    *event* time set it so the engine can stamp batches for the
    freshness plane (obs/freshness.py) — watermarks and the
    ``record_staleness_s`` books; the Kafka sources carry event time in
    the wire batches themselves and need no extractor."""

    event_time_fn = None

    def poll(self, max_n: int) -> Polled:
        raise NotImplementedError

    def seek(self, offset: int) -> None:
        raise NotImplementedError

    @property
    def exhausted(self) -> bool:
        return False

    def close(self) -> None:
        pass


def batch_event_range(records, event_time_fn):
    """min/max event time over a batch of records → (min_ts, max_ts) or
    None when no record carries one. Out-of-order event times within
    the batch are exactly what the min/max pair absorbs — the watermark
    consumer (``FreshnessTracker``) only ever advances monotonically."""
    if event_time_fn is None:
        return None
    lo = hi = None
    for rec in records:
        try:
            ts = event_time_fn(rec)
        except Exception:
            continue  # a malformed record never poisons the stamp
        if ts is None or ts <= 0:
            continue
        ts = float(ts)
        lo = ts if lo is None else min(lo, ts)
        hi = ts if hi is None else max(hi, ts)
    return None if hi is None else (lo, hi)


class InMemorySource(Source):
    """Replayable in-memory record list (the MiniCluster-test equivalent,
    SURVEY.md §5); optionally cycles forever for throughput benchmarking."""

    def __init__(self, records: Sequence[Record], cycle: bool = False,
                 event_time_fn=None):
        self._records = list(records)
        self._pos = 0
        self._cycle = cycle
        self.event_time_fn = event_time_fn

    def poll(self, max_n: int) -> Polled:
        n = len(self._records)
        if n == 0:
            return []
        out: Polled = []
        while len(out) < max_n:
            if self._pos >= n:
                if not self._cycle:
                    break
                self._pos = 0
            out.append((self._pos + 1, self._records[self._pos]))
            self._pos += 1
        return out

    def seek(self, offset: int) -> None:
        self._pos = offset % max(len(self._records), 1) if self._cycle else offset

    @property
    def exhausted(self) -> bool:
        return not self._cycle and self._pos >= len(self._records)


class GeneratorSource(Source):
    """Wraps a callable ``f(n) -> list[Record]`` (unbounded synthetic load).

    Offsets count records produced; ``seek`` just fast-forwards the counter
    (synthetic sources are stateless by construction).
    """

    def __init__(self, fn: Callable[[int], Sequence[Record]],
                 event_time_fn=None):
        self._fn = fn
        self._offset = 0
        self.event_time_fn = event_time_fn

    def poll(self, max_n: int) -> Polled:
        recs = self._fn(max_n)
        out = []
        for r in recs:
            self._offset += 1
            out.append((self._offset, r))
        return out

    def seek(self, offset: int) -> None:
        self._offset = offset


class JsonlFileSource(Source):
    """Tails a JSONL file: each line is one dict record; offset = byte
    position after the last consumed line (exact resume after restart).

    ``follow=True`` keeps polling for appended lines (Kafka-less streaming
    ingestion for a single-host deployment)."""

    def __init__(self, path: str, follow: bool = False,
                 event_time_fn=None):
        self._path = path
        self._f = open(path, "r", encoding="utf-8")
        self._follow = follow
        self._eof = False
        self.event_time_fn = event_time_fn

    def poll(self, max_n: int) -> Polled:
        out: Polled = []
        for _ in range(max_n):
            pos = self._f.tell()
            line = self._f.readline()
            if not line or not line.endswith("\n"):
                # partial line: rewind and wait for the writer to finish it
                self._f.seek(pos)
                self._eof = not self._follow
                break
            line = line.strip()
            if line:
                out.append((self._f.tell(), json.loads(line)))
        return out

    def seek(self, offset: int) -> None:
        self._f.seek(offset)
        self._eof = False

    @property
    def exhausted(self) -> bool:
        return self._eof

    def close(self) -> None:
        self._f.close()


class ControlSource(Source):
    """Thread-safe in-process control-message feed (capability C6): test and
    application code pushes ``AddMessage``/``DelMessage`` while the engine
    polls. Offsets count consumed messages."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._buf: List[Record] = []
        self._offset = 0

    def push(self, message: Record) -> None:
        with self._lock:
            self._buf.append(message)

    def poll(self, max_n: int) -> Polled:
        with self._lock:
            take = self._buf[:max_n]
            del self._buf[:max_n]
        out = []
        for m in take:
            self._offset += 1
            out.append((self._offset, m))
        return out

    def seek(self, offset: int) -> None:
        self._offset = offset


class FaultInjectionSource(Source):
    """Wraps a source and raises after N polled records (SURVEY.md §6 row
    "failure detection / fault injection": the reference relies on Flink's
    restart strategies; here recovery = a fresh pipeline restoring the
    checkpointed source offset, and this wrapper is how tests kill the
    first attempt mid-stream deterministically)."""

    def __init__(self, inner: Source, fail_after: int,
                 exc: type = RuntimeError):
        self._inner = inner
        self._fail_after = fail_after
        self._exc = exc
        self._polled = 0
        self.armed = True

    def poll(self, max_n: int):
        if self.armed and self._polled >= self._fail_after:
            raise self._exc(
                f"injected fault after {self._polled} records"
            )
        out = self._inner.poll(max_n)
        self._polled += len(out)
        return out

    def seek(self, offset: int) -> None:
        self._inner.seek(offset)

    @property
    def exhausted(self) -> bool:
        return self._inner.exhausted
