"""Dead-letter queue: durable quarantine for poison records.

At-least-once replay (C7) is also the runtime's sharpest failure
amplifier: a single record that crashes decode or scoring is replayed
from the committed offset on every restart, exhausts the supervisor's
restart budget, and turns one bad byte into a whole-job ``on_give_up``
outage. The delivery-correctness fix is record-level: the hot paths
isolate the offending record (bisection "suspect mode" in
runtime/block.py and runtime/engine.py, crash-loop fingerprinting for
records that kill the process outright), quarantine it HERE, and let
the rest of the stream proceed. A quarantined record never reaches the
sink, the shadow diff, or the watermarks — it is an explicit, bounded,
inspectable drop, not a silent one.

Storage: JSONL segment files (``dlq-<seq>.jsonl``) in a directory that
conventionally sits beside the checkpoints (``<ckpt_dir>/dlq`` — the
pipelines create it there automatically when checkpointing is on).
Durability per append is one line + ``fsync`` on an append-only
segment handle (the directory is fsync'd once per segment creation,
the PR 8 pattern's durable-name half): a SIGKILL mid-append can tear
at most the LAST line of the newest segment, which :meth:`scan` skips
— every fsync'd envelope survives, and a decode-poison flood costs
one fsync per record instead of a whole-segment rewrite plus two
(which would cap the ingest thread at a few hundred records/s exactly
when a poisoned producer floods it). Writes are lock-serialized: the
default wiring shares one DLQ between the ingest thread (decode
quarantine) and the score thread (suspect-mode quarantine).

Envelope per quarantined record::

    {"offset": int, "partition": int|None, "payload_b64": str,
     "reason": "score"|"decode"|"crash_loop", "exception": str|None,
     "attempts": int, "fingerprint": sha256-hex-16, "t": unix-s,
     "pid": int, ...extra}

The quarantine paths additionally stamp ``trace_id``/``span_id`` (the
record's journey context, obs/trace.py) into ``extra``: the envelope
is the journey's terminal hop, and ``fjt-dlq redrive`` carries those
ids back into the topic as a ``traceparent`` record header so the
redriven record's new journey segment links the original.

Bounded: at most ``max_records`` envelopes are retained; when a
rotation overflows the budget the OLDEST segments are dropped, counted
in ``dlq_dropped`` and marked with one ``dlq_truncated`` flight event —
a DLQ that silently eats its own tail is a data-loss bug, a DLQ that
grows without bound is a disk-full outage.

Operator surface: the ``fjt-dlq`` CLI (list / inspect / redrive) reads
this layout; redrive produces the payload bytes back into a Kafka topic
(``KafkaClient.produce``) so a corrected pipeline re-scores them
through the live path.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import threading
import time
from typing import Dict, Iterator, List, Optional

from flink_jpmml_tpu.obs import recorder as flight
from flink_jpmml_tpu.utils.diskio import atomic_write_json

_SEG_PREFIX = "dlq-"

#: the quarantine reasons the runtime emits (the ``reason`` label on
#: ``dlq_records``); free-form reasons are allowed but these are the
#: documented lifecycle (docs/operations.md "Poison records & DLQ")
REASON_SCORE = "score"        # a scoring/dispatch exception isolated it
REASON_DECODE = "decode"      # wire/record decode rejected the bytes
REASON_CRASH_LOOP = "crash_loop"  # it killed the process; fingerprinted


class PoisonIsolationOverflow(RuntimeError):
    """Suspect-mode isolation found MORE failing records than the
    per-batch quarantine budget (``FJT_DLQ_MAX_PER_BATCH``): that is a
    model- or deployment-level failure wearing a poison-record costume,
    and quarantining a whole stream record-by-record would convert an
    outage into silent mass data loss. The isolation aborts and the
    original error propagates — the worker dies honestly and the
    supervisor's restart/give-up policy takes over."""

    def __init__(self, quarantined: int, original: BaseException):
        super().__init__(
            f"isolation abandoned after {quarantined} quarantines in "
            f"one batch (FJT_DLQ_MAX_PER_BATCH): {original!r}"
        )
        self.original = original


def env_count(name: str, fallback: int) -> int:
    """Non-negative-int env knob (0 allowed — unlike retry.env_int,
    which treats 0 as 'use the fallback')."""
    raw = os.environ.get(name)
    if not raw:
        return fallback
    try:
        v = int(raw)
    except ValueError:
        return fallback
    return v if v >= 0 else fallback


def fingerprint(payload: bytes) -> str:
    """Stable 16-hex-char content fingerprint: the SAME bad bytes
    replayed across restarts land as recognizably the SAME poison
    record, whatever offset or attempt count they carry."""
    return hashlib.sha256(payload).hexdigest()[:16]


def make_envelope(
    payload: bytes,
    offset: int,
    reason: str,
    partition: Optional[int] = None,
    error: Optional[BaseException] = None,
    attempts: int = 1,
    **extra,
) -> dict:
    env = {
        "offset": int(offset),
        "partition": None if partition is None else int(partition),
        "payload_b64": base64.b64encode(bytes(payload)).decode("ascii"),
        "reason": str(reason),
        "exception": (
            f"{type(error).__name__}: {error}" if error is not None
            else None
        ),
        "attempts": int(attempts),
        "fingerprint": fingerprint(bytes(payload)),
        "t": time.time(),
        "pid": os.getpid(),
    }
    env.update(extra)
    return env


def payload_bytes(envelope: dict) -> bytes:
    return base64.b64decode(envelope.get("payload_b64", ""))


def serialize_record(record) -> bytes:
    """Record-object → quarantine payload bytes: JSON when the record
    is JSON-shaped (the engine's dict/list records — redrivable), repr
    otherwise (still inspectable, still fingerprintable)."""
    try:
        return json.dumps(record, sort_keys=True, default=str).encode()
    except (TypeError, ValueError):
        return repr(record).encode()


class DeadLetterQueue:
    """Bounded, durably-persisted quarantine (see module docstring).

    ``metrics`` (optional ``MetricsRegistry``) books one
    ``dlq_records{reason=...}`` count per envelope (fleet merge SUM —
    the aggregate quarantine volume is a real total) and ``dlq_dropped``
    when the retention bound evicts old segments. :meth:`put` is
    thread-safe (one lock): the ingest thread quarantines decode
    poison while the score thread quarantines scoring poison into the
    SAME queue. Two *processes* sharing one directory remain a
    deployment error the segment sequence numbers make visible
    (colliding names), not a supported topology."""

    def __init__(
        self,
        directory: str,
        max_records: int = 65536,
        segment_records: int = 64,
        metrics=None,
    ):
        self._dir = str(directory)
        self._max_records = max(1, int(max_records))
        self._seg_records = max(1, int(segment_records))
        self._metrics = metrics
        os.makedirs(self._dir, exist_ok=True)
        segs = self._segments()
        self._seq = (self._seg_seq(segs[-1]) + 1) if segs else 0
        self._mu = threading.Lock()
        # the open segment's append handle + envelope count
        self._open_f = None
        self._open_n = 0
        self._last_event = 0.0  # flight-event rate limit (1/s)

    @property
    def directory(self) -> str:
        return self._dir

    # -- write side --------------------------------------------------------

    def put(self, envelope: dict) -> dict:
        """Durably quarantine one envelope; → the envelope. Raises
        OSError when the directory cannot be written — a quarantine
        that silently vanishes would let the caller drop the record
        as if it were safely parked."""
        with self._mu:
            self._append_locked(envelope)
            rotated = self._open_n >= self._seg_records
            if rotated:
                try:
                    self._open_f.close()
                except OSError:
                    pass
                self._open_f = None
                self._open_n = 0
                self._seq += 1
        if self._metrics is not None:
            reason = envelope.get("reason", "unknown")
            self._metrics.counter(f'dlq_records{{reason="{reason}"}}').inc()
        # rate-limited (≥1 s apart): a poisoned PRODUCER floods decode
        # errors by the thousand, and the flight ring is a story, not a
        # firehose — exact volume lives in the dlq_records counters
        now = time.monotonic()
        if now - self._last_event >= 1.0:
            self._last_event = now
            flight.record(
                "poison_quarantined",
                offset=envelope.get("offset"),
                partition=envelope.get("partition"),
                reason=envelope.get("reason"),
                fingerprint=envelope.get("fingerprint"),
                exception=envelope.get("exception"),
                # the journey handle (obs/trace.py): callers stamp the
                # record's trace context into the envelope so the
                # quarantine links its journey — and fjt-dlq redrive
                # carries it back into the topic as a traceparent header
                trace_id=envelope.get("trace_id"),
            )
        if rotated:
            self._gc()
        return envelope

    def _append_locked(self, envelope: dict) -> None:
        """One fsync'd line on the append-only open segment (opened —
        and its directory entry fsync'd — on first use)."""
        if self._open_f is None:
            path = self._open_path()
            self._open_f = open(path, "a", encoding="utf-8")
            try:
                dfd = os.open(self._dir, os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            except OSError:
                pass
        self._open_f.write(json.dumps(envelope, sort_keys=True) + "\n")
        self._open_f.flush()
        os.fsync(self._open_f.fileno())
        self._open_n += 1

    def quarantine(
        self,
        payload: bytes,
        offset: int,
        reason: str,
        partition: Optional[int] = None,
        error: Optional[BaseException] = None,
        attempts: int = 1,
        **extra,
    ) -> dict:
        """Convenience: build the envelope and :meth:`put` it."""
        return self.put(make_envelope(
            payload, offset, reason, partition=partition, error=error,
            attempts=attempts, **extra,
        ))

    def _open_path(self) -> str:
        return os.path.join(
            self._dir, f"{_SEG_PREFIX}{self._seq:012d}.jsonl"
        )

    # -- read side ---------------------------------------------------------

    def scan(self) -> Iterator[dict]:
        """Yield every retained envelope, oldest first. Unparseable
        lines (a SIGKILL-torn trailing append, disk damage) are
        skipped — a corrupt neighbor must not hide the rest."""
        for path in self._segments():
            try:
                with open(path, "r", encoding="utf-8") as f:
                    raw_lines = f.readlines()
            except OSError:
                continue
            for ln in raw_lines:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    env = json.loads(ln)
                except ValueError:
                    continue
                if isinstance(env, dict):
                    yield env

    def count(self) -> int:
        return sum(1 for _ in self.scan())

    def offsets(self) -> List[int]:
        return [
            int(e["offset"]) for e in self.scan()
            if e.get("offset") is not None
        ]

    # -- internals ---------------------------------------------------------

    def _segments(self) -> List[str]:
        try:
            names = sorted(
                n for n in os.listdir(self._dir)
                if n.startswith(_SEG_PREFIX) and n.endswith(".jsonl")
            )
        except OSError:
            return []
        return [os.path.join(self._dir, n) for n in names]

    @staticmethod
    def _seg_seq(path: str) -> int:
        name = os.path.basename(path)
        try:
            return int(name[len(_SEG_PREFIX):-len(".jsonl")])
        except ValueError:
            return 0

    def _gc(self) -> None:
        """Enforce the retention bound at segment granularity: drop the
        OLDEST whole segments once the total would exceed the budget."""
        max_segments = max(1, self._max_records // self._seg_records)
        segs = self._segments()
        drop = segs[:-max_segments] if len(segs) > max_segments else []
        dropped = 0
        for p in drop:
            try:
                with open(p, "r", encoding="utf-8") as f:
                    dropped += sum(1 for ln in f if ln.strip())
            except OSError:
                pass
            try:
                os.unlink(p)
            except OSError:
                pass
        if dropped:
            if self._metrics is not None:
                self._metrics.counter("dlq_dropped").inc(dropped)
            flight.record(
                "dlq_truncated", dropped=dropped,
                max_records=self._max_records,
            )


def dlq_for_checkpoint(checkpoint, metrics=None) -> Optional["DeadLetterQueue"]:
    """The default wiring: a DLQ living BESIDE the checkpoints
    (``<ckpt_dir>/dlq``), so the quarantine survives exactly as long as
    the resume state it protects. → None when ``checkpoint`` is None
    (no durable state → nowhere durable to park poison)."""
    if checkpoint is None:
        return None
    directory = getattr(checkpoint, "directory", None)
    if directory is None:
        return None
    return DeadLetterQueue(os.path.join(directory, "dlq"), metrics=metrics)


# ---------------------------------------------------------------------------
# Crash-loop fingerprint state (suspect markers), shared by the pipelines
# ---------------------------------------------------------------------------

_CRASH_FILE = "crashes.json"
_MARKER_FILE = "suspect-marker.json"


class CrashFingerprint:
    """Worker-side crash-loop bookkeeping in the checkpoint directory.

    Two small atomic files:

    - ``crashes.json`` — ``{"committed": O, "count": k}``: how many
      consecutive incarnations restored at the SAME committed offset.
      ``note_restore(O)`` bumps the count when O is unchanged (the
      previous incarnation died without making progress) and resets it
      otherwise. Together with the supervisor's ``FJT_RESTART_STREAK``
      env (either signal suffices), a count ≥ ``FJT_POISON_RESTARTS``
      flips the pipeline into suspect mode over the checkpoint's
      in-flight offset range.
    - ``suspect-marker.json`` — ``{"lo": o, "hi": o2, "attempts": k}``:
      written BEFORE each suspect-mode dispatch, cleared after it
      completes. An incarnation that finds a marker knows the previous
      one died mid-dispatch of exactly that offset range: the range is
      never re-dispatched whole — it is bisected (one narrowing per
      death), and a single-record marker is quarantined WITHOUT being
      dispatched at all, converting a process-killing record into a DLQ
      entry in O(log batch) restarts.
    """

    def __init__(self, directory: str):
        self._dir = str(directory)
        os.makedirs(self._dir, exist_ok=True)

    # -- crash counting ----------------------------------------------------

    def note_restore(self, committed: int) -> int:
        """Record one restore at ``committed``; → the consecutive count
        of restores stuck at this offset (1 = first)."""
        st = self._read(_CRASH_FILE)
        if st is not None and int(st.get("committed", -1)) == int(committed):
            count = int(st.get("count", 0)) + 1
        else:
            count = 1
        atomic_write_json(
            os.path.join(self._dir, _CRASH_FILE),
            {"committed": int(committed), "count": count},
        )
        return count

    # -- suspect markers ---------------------------------------------------

    def read_marker(self) -> Optional[Dict[str, int]]:
        m = self._read(_MARKER_FILE)
        if (
            isinstance(m, dict)
            and "lo" in m and "hi" in m
            and int(m["hi"]) > int(m["lo"])
        ):
            return {
                "lo": int(m["lo"]), "hi": int(m["hi"]),
                "attempts": int(m.get("attempts", 1)),
            }
        return None

    def write_marker(self, lo: int, hi: int, attempts: int = 1) -> None:
        atomic_write_json(
            os.path.join(self._dir, _MARKER_FILE),
            {"lo": int(lo), "hi": int(hi), "attempts": int(attempts)},
        )

    def clear_marker(self) -> None:
        try:
            os.unlink(os.path.join(self._dir, _MARKER_FILE))
        except OSError:
            pass

    def _read(self, name: str) -> Optional[dict]:
        try:
            with open(
                os.path.join(self._dir, name), "r", encoding="utf-8"
            ) as f:
                obj = json.load(f)
        except (OSError, ValueError):
            return None
        return obj if isinstance(obj, dict) else None
