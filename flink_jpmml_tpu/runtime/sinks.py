"""Stream sinks: where scored predictions land.

Reference parity: Flink sinks; tests used "sink into a static concurrent
collection, assert collected predictions" (SURVEY.md §5) — that's
:class:`CollectSink` here.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Callable, List, Sequence


class Sink:
    def emit(self, items: Sequence[Any]) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class CollectSink(Sink):
    """Thread-safe in-memory collector (the test harness sink)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._items: List[Any] = []

    def emit(self, items: Sequence[Any]) -> None:
        with self._lock:
            self._items.extend(items)

    @property
    def items(self) -> List[Any]:
        with self._lock:
            return list(self._items)

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


class CallbackSink(Sink):
    def __init__(self, fn: Callable[[Sequence[Any]], None]):
        self._fn = fn

    def emit(self, items: Sequence[Any]) -> None:
        self._fn(items)


class NullSink(Sink):
    """Discards everything (benchmark mode: measures the scoring path only)."""

    def __init__(self) -> None:
        self.count = 0

    def emit(self, items: Sequence[Any]) -> None:
        self.count += len(items)


class JsonlFileSink(Sink):
    def __init__(self, path: str):
        self._f = open(path, "a", encoding="utf-8")

    def emit(self, items: Sequence[Any]) -> None:
        for it in items:
            self._f.write(json.dumps(it, default=_jsonify) + "\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()


def _jsonify(o: Any):
    from flink_jpmml_tpu.models.prediction import EmptyScore, Prediction, Score

    if isinstance(o, Prediction):
        return {
            "empty": o.is_empty,
            "value": None if o.is_empty else o.score.value,
            "label": o.target.label if o.target else None,
        }
    if isinstance(o, Score):
        return o.value
    if isinstance(o, EmptyScore):
        return None
    return str(o)
