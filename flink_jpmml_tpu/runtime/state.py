"""Keyed per-record session state, device-resident and dispatch-fused.

ROADMAP item 3: real per-user serving (sessionization, decayed
counters, frequency capping) needs temporal context per key, and a
host-side dict lookup per record would crater the ~1M rec/s hot path
by orders of magnitude. The state plane keeps the per-key state vector
in ONE device buffer and fuses lookup → derive-features → score →
state-update into the existing scoring dispatch
(compile/statekernel.py): zero per-record host round-trips, one
dispatch per batch, O(1) memory per key.

Division of labor — host routes, device accumulates:

- **Host mirror (this module).** Slot assignment is open addressing
  over a fixed-capacity table, keyed by the SAME ``stable_hash`` the
  rollout split and lane routing use (``partitioner.stable_hash_vec``
  is its bit-identical vectorized twin), so canary/shard routing and
  state routing agree on every key by construction. The key → slot
  map (hashes, occupancy, LRU touch) lives in host numpy — it is
  metadata exactly like the ring's offsets — and ``assign_slots``
  resolves a whole batch with vectorized probing: dedupe the batch's
  keys, probe a bounded linear window, claim empties, evict the
  least-recently-touched slot when the window is full. No device
  round trip is involved in routing.
- **Device values.** The table's VALUES — one fixed-width f32 vector
  per slot (counts, sums, decayed counters in product form, last-seen
  stride, min/max) — live in a single ``[rows, STATE_WIDTH]`` device
  buffer that only the fused kernel reads or writes, via gather +
  scatter-add/min/max over the batch's slot vector (O(batch), never
  O(capacity)). The buffer is DONATED to each dispatch, so the update
  is in-place: steady-state state memory is one buffer, not one per
  in-flight batch.

Decayed counters ride in **product form**: a record at stride
``t = offset // stride`` contributes ``λ^(epoch - t)`` (≥ 1) to the
decayed count column, and the decayed value *as of* stride ``t`` is
``column · λ^(t - epoch)`` — a pure scatter-ADD per record, so updates
are order-independent and replay-exact, with a rare O(capacity)
renormalization sweep when the exponent range grows (``maybe_renorm``)
instead of an O(capacity) decay multiply per batch. Time is a pure
function of the record OFFSET, never of wall clock or batch shape, so
a checkpoint-restored replay derives byte-identical state.

Exactly-once state under at-least-once delivery: the snapshot records
``applied_hi`` (the highest offset folded into the table). On restore,
replayed records below it route to the scratch slot (read zeros, write
nothing) — state updates apply exactly once per offset even though the
sink may see the records twice. Shed batches never dispatch; DLQ'd /
recovery-path records score through the stateless entries — neither
ever mutates the table (the PR 8/12 never-delivered contract extended
to state).

Snapshots ride the PR 8 atomic-writer discipline: values + host mirror
in one ``.npz`` sidecar beside the checkpoints (tmp → fsync →
``os.replace`` → dir fsync), referenced by name from the checkpoint
JSON; the record path inlines a base64 payload for small tables. The
last snapshot is also kept in memory: a dispatch error with a donated
state buffer poisons the buffer, and ``rollback()`` restores the
snapshot (bounded, counted loss — ``state_rollbacks``) so the ladder
can keep serving statelessly.

Sharding: rows are padded to a multiple of 256 and the buffer shards
over the mesh data axis (``NamedSharding``). Slot = hash % capacity
never changes, so a degraded-mesh rebuild (``migrate``) only re-places
rows across the survivors — every key keeps its slot and its state.
"""

from __future__ import annotations

import base64
import contextlib
import io
import math
import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

import numpy as np

from flink_jpmml_tpu.obs import recorder as flight
from flink_jpmml_tpu.parallel.partitioner import stable_hash, stable_hash_vec
from flink_jpmml_tpu.utils.exceptions import InputValidationException
from flink_jpmml_tpu.utils.metrics import MetricsRegistry

# one fixed-width state vector per key; the column layout is the
# kernel ABI (compile/statekernel.py) and the snapshot format
STATE_WIDTH = 8
COL_COUNT = 0      # records seen (scatter-add 1)
COL_SUM = 1        # sum of scores
COL_SQSUM = 2      # sum of score^2
COL_DCOUNT = 3     # decayed count, product form (scatter-add λ^-rel)
COL_DSUM = 4       # decayed score sum, product form
COL_LAST_T = 5     # last-seen stride relative to epoch (scatter-max)
COL_MIN = 6        # min score (+inf until first)
COL_MAX = 7        # max score (-inf until first)

# names of the DERIVED feature vector the fused kernel returns per
# record (the drift plane baselines these — state corruption is a
# drift alarm on the derived stream)
DERIVED_FIELDS = (
    "state_count", "state_mean", "state_var", "state_decayed_count",
    "state_decayed_mean", "state_gap", "state_min", "state_max",
)

# sharding-friendly row padding: rows % 256 == 0 keeps the buffer
# divisible by any data-axis width the meshes use (and any degraded
# rebuild of them), so migrate() never has to reshape
_ROW_PAD = 256

_SNAPSHOT_VERSION = 1
_SNAPSHOT_KEEP = 3  # sidecar retention (the checkpoint writer keeps 3)
# payload-inline ceiling for the record path's checkpoint JSON: beyond
# this the table must snapshot to a sidecar file
_INLINE_CAP = 1 << 16


@dataclass(frozen=True)
class StateSpec:
    """Configuration of one keyed state table.

    ``key_col`` is the raw-batch column carrying the key on the block
    path (values are int-valued f32 — user/session ids); ``key_fn``
    extracts the key from a record on the record path (default: the
    ``key_field`` entry of a dict record). ``decay`` is the per-stride
    retention λ of the decayed counters — a record ``stride`` strides
    old weighs ``decay**strides``; one stride is ``stride`` record
    offsets, so decay half-lives are offset-denominated and replay
    deterministically."""

    capacity: int
    key_col: int = 0
    key_field: str = "key"
    key_fn: Optional[Callable[[Any], Any]] = None
    probe: int = 8
    decay: float = 0.999
    stride: int = 256

    def __post_init__(self):
        if self.capacity < 2:
            raise InputValidationException(
                f"state capacity must be >= 2: {self.capacity}"
            )
        if not (0.0 < self.decay < 1.0):
            raise InputValidationException(
                f"state decay must be in (0, 1): {self.decay}"
            )
        if self.probe < 1 or self.stride < 1:
            raise InputValidationException(
                "state probe and stride must be >= 1"
            )


_CAPACITY_ENV = "FJT_STATE_CAPACITY"
_PROBE_ENV = "FJT_STATE_PROBE"
_DECAY_ENV = "FJT_STATE_DECAY"
_STRIDE_ENV = "FJT_STATE_STRIDE"


def spec_from_env(capacity: int = 1 << 20, **overrides) -> StateSpec:
    """Build a :class:`StateSpec` from the ``FJT_STATE_*`` environment
    (bench/perf-smoke/fuzz sizing knobs; malformed values fall back to
    the defaults — tooling must not die on a typo'd env). Keyword
    overrides win over both."""

    def _env(name, cast, default):
        raw = os.environ.get(name)
        if raw:
            try:
                return cast(raw)
            except ValueError:
                pass
        return default

    kw = {
        "capacity": _env(_CAPACITY_ENV, int, capacity),
        "probe": _env(_PROBE_ENV, int, 8),
        "decay": _env(_DECAY_ENV, float, 0.999),
        "stride": _env(_STRIDE_ENV, int, 256),
    }
    kw.update(overrides)
    return StateSpec(**kw)


class KeyedStateTable:
    """Open-addressed device-resident per-key state (module docstring).

    One instance per pipeline; the score thread owns every call —
    single-threaded by the same contract as the ring."""

    def __init__(self, spec: StateSpec,
                 metrics: Optional[MetricsRegistry] = None):
        self.spec = spec
        self.capacity = int(spec.capacity)
        self.rows = -(-(self.capacity + 1) // _ROW_PAD) * _ROW_PAD
        self.scratch = self.capacity  # the bypass/padding slot
        # renorm trigger: keep λ^rel comfortably inside f32 —
        # exp(30) ≈ 1e13 of headroom against ~1e38
        self._renorm_every = max(
            16, min(4096, int(30.0 / -math.log(spec.decay)))
        )
        self.metrics = metrics or MetricsRegistry()
        m = self.metrics
        self._c_records = m.counter("state_records")
        self._c_hits = m.counter("state_hits")
        self._c_inserts = m.counter("state_inserts")
        self._c_evictions = m.counter("state_evictions")
        self._c_collisions = m.counter("state_collisions")
        self._c_overflow = m.counter("state_overflow")
        self._c_bypass = m.counter("state_bypass_records")
        self._c_rollbacks = m.counter("state_rollbacks")
        self._g_resident = m.gauge("state_resident_keys")
        self._g_occupancy = m.gauge("state_occupancy_frac")
        self._g_hit_ratio = m.gauge("state_hit_ratio")
        # host mirror (routing metadata; never shipped per batch)
        self._keys = np.zeros(self.capacity, np.uint32)
        self._occ = np.zeros(self.capacity, bool)
        self._touch = np.zeros(self.capacity, np.int64)
        self._seq = 0
        self.resident = 0
        self.epoch = 0          # decay epoch, in strides
        self.applied_hi = 0     # exactly-once high-water (offsets)
        self.skip_until = 0     # restore sets: replayed offsets below
        # bypass the table (their updates already applied pre-crash)
        # device values (numpy until first dispatch / shard())
        self.values = np.zeros((self.rows, STATE_WIDTH), np.float32)
        self._mesh = None
        self._bypass_depth = 0
        # in-memory rollback point (init = empty table)
        self._snap: Dict[str, Any] = self._host_snapshot()
        # drift shims per model label (one handle set per model+table)
        self._shims: Dict[str, Any] = {}

    # -- bypass ------------------------------------------------------------

    @property
    def bypassed(self) -> bool:
        """Is the table inside a stateless-scoring window (recovery
        redispatch, poison bisection)? Armed call sites check this and
        score through the stateless entries instead."""
        return self._bypass_depth > 0

    @contextlib.contextmanager
    def bypass(self):
        """Scope a stateless-scoring window: dispatches inside never
        touch the table (the recovery ladder and poison bisection both
        replay records — their scores must not double-apply state)."""
        self._bypass_depth += 1
        try:
            yield
        finally:
            self._bypass_depth -= 1

    # -- routing -----------------------------------------------------------

    def hash_keys(self, keys: np.ndarray) -> np.ndarray:
        """int64 keys → uint32 stable hashes (the lane-routing hash)."""
        return stable_hash_vec(np.asarray(keys, np.int64))

    def hash_records(self, records) -> np.ndarray:
        """Record-path twin: ``spec.key_fn`` (or the ``key_field`` of
        dict records) per record → uint32 stable hashes."""
        fn = self.spec.key_fn
        if fn is None:
            f = self.spec.key_field
            fn = lambda r: r.get(f, 0) if isinstance(r, dict) else r
        out = np.empty(len(records), np.uint32)
        for i, r in enumerate(records):
            out[i] = stable_hash(fn(r)) & 0xFFFFFFFF
        return out

    def extract_keys(self, X: np.ndarray) -> np.ndarray:
        """Block-path key column of a raw f32 batch → int64 keys."""
        col = np.asarray(X)[:, self.spec.key_col]
        return col.astype(np.int64)

    def assign_slots(self, khash: np.ndarray, offsets=None):
        """Resolve one batch of key hashes to table slots (host-side,
        vectorized — the only per-batch routing cost).

        → ``(slots int32[B], reset bool[B], rel f32[B], w f32[B])``:
        ``slots`` are value-buffer rows (``scratch`` for bypassed
        records), ``reset`` marks slots whose key is fresh this batch
        (the kernel re-initializes them before the gather), ``rel`` is
        the record's decay stride relative to the epoch and ``w`` its
        product-form weight λ^-rel. Replayed offsets below
        ``skip_until`` bypass (exactly-once state)."""
        khash = np.asarray(khash, np.uint32)
        B = khash.shape[0]
        self._seq += 1
        seq = self._seq
        if offsets is None:
            offs = np.arange(self.applied_hi, self.applied_hi + B,
                             dtype=np.int64)
        else:
            offs = np.asarray(offsets, np.int64)
        apply = offs >= self.skip_until
        n_bypass = int(B - apply.sum())
        rel_t = (offs // self.spec.stride) - self.epoch
        slots = np.full(B, self.scratch, np.int32)
        reset = np.zeros(B, bool)
        if apply.any():
            uk, inv = np.unique(khash[apply], return_inverse=True)
            nu = uk.shape[0]
            base = uk.astype(np.int64) % self.capacity
            slot_u = np.full(nu, -1, np.int64)
            reset_u = np.zeros(nu, bool)
            keys_h, occ, touch = self._keys, self._occ, self._touch
            collided = 0
            for p in range(self.spec.probe):
                pending = slot_u < 0
                if not pending.any():
                    break
                cand = (base + p) % self.capacity
                hit = pending & occ[cand] & (keys_h[cand] == uk)
                slot_u[hit] = cand[hit]
                # stamp at hit/claim time, not batch end: the evict
                # round must see THIS batch's slots as untouchable
                touch[cand[hit]] = seq
                pending &= ~hit
                empty = pending & ~occ[cand]
                idx = np.flatnonzero(empty)
                if idx.size:
                    # one claimant per empty slot per round (np.unique
                    # keeps the first); losers keep probing
                    _, first = np.unique(cand[idx], return_index=True)
                    win = idx[first]
                    c = cand[win]
                    slot_u[win] = c
                    occ[c] = True
                    keys_h[c] = uk[win]
                    touch[c] = seq
                    reset_u[win] = True
                    self.resident += win.size
                    self._c_inserts.inc(win.size)
                if p == 0:
                    # catalogue semantic: home slot held by a DIFFERENT
                    # key — a fresh key claiming its empty home slot is
                    # not a collision, so count after the claim round
                    collided = int((slot_u < 0).sum())
            pend = np.flatnonzero(slot_u < 0)
            if pend.size:
                # probe window exhausted: evict the least-recently-
                # touched slot in each key's window — but never one
                # touched THIS batch (another key just landed there);
                # keys that lose the eviction race overflow to scratch
                W = (base[pend, None]
                     + np.arange(self.spec.probe)[None, :]) % self.capacity
                t = touch[W]
                vic = W[np.arange(pend.size), np.argmin(t, axis=1)]
                fresh_vic = touch[vic] < seq
                _, first = np.unique(vic, return_index=True)
                winner = np.zeros(pend.size, bool)
                winner[first] = True
                winner &= fresh_vic
                win = pend[winner]
                c = vic[winner]
                if win.size:
                    keys_h[c] = uk[win]
                    touch[c] = seq
                    slot_u[win] = c
                    reset_u[win] = True
                    self._c_evictions.inc(win.size)
                lost = int(pend.size - win.size)
                if lost:
                    self._c_overflow.inc(lost)
            assigned = slot_u >= 0
            slot_r = np.where(assigned, slot_u, np.int64(self.scratch))
            slots[apply] = slot_r[inv].astype(np.int32)
            reset[apply] = reset_u[inv]
            hits = int(
                (apply & (slots != self.scratch) & ~reset).sum()
            )
            self._c_hits.inc(hits)
            self._c_collisions.inc(collided)
            hi = int(offs[apply].max()) + 1
            if hi > self.applied_hi:
                self.applied_hi = hi
        self._c_records.inc(B)
        if n_bypass:
            self._c_bypass.inc(n_bypass)
        self._g_resident.set(float(self.resident))
        self._g_occupancy.set(self.resident / float(self.capacity))
        rec = self._c_records.value
        self._g_hit_ratio.set(
            self._c_hits.value / rec if rec else 0.0
        )
        rel = np.where(apply, rel_t, 0).astype(np.float32)
        w = np.power(
            np.float32(self.spec.decay), -rel, dtype=np.float32
        )
        w = np.where(apply, w, np.float32(0.0)).astype(np.float32)
        return slots, reset, rel, w

    def maybe_renorm(self, first_off: int) -> None:
        """Advance the decay epoch when the product-form exponents
        approach f32 range: multiply the decayed columns by λ^Δ and
        shift the last-seen strides by Δ (one O(capacity) device op,
        once per ``renorm_every`` strides — never per batch)."""
        t_first = int(first_off) // self.spec.stride
        delta = t_first - self.epoch
        if delta < self._renorm_every:
            return
        mul = np.ones(STATE_WIDTH, np.float32)
        mul[COL_DCOUNT] = mul[COL_DSUM] = np.float32(
            self.spec.decay
        ) ** np.float32(delta)
        add = np.zeros(STATE_WIDTH, np.float32)
        add[COL_LAST_T] = -np.float32(delta)
        from flink_jpmml_tpu.compile import statekernel

        self.values = statekernel.renorm(self.values, mul, add)
        self.epoch = t_first
        flight.record(
            "state_renorm", epoch=self.epoch, delta=delta,
        )

    # -- dispatch plumbing -------------------------------------------------

    def commit(self, new_values) -> None:
        """Adopt the fused dispatch's updated (donated-in-place) state
        buffer. The array may still be computing — the next dispatch
        chains on it device-side."""
        self.values = new_values

    def rollback(self) -> None:
        """Restore the last snapshot after a dispatch error poisoned
        the donated state buffer (bounded loss back to the snapshot;
        subsequent records re-enter cleanly)."""
        self._c_rollbacks.inc()
        snap = self._snap
        self._keys = snap["keys"].copy()
        self._occ = snap["occ"].copy()
        self._touch = snap["touch"].copy()
        self.resident = int(snap["resident"])
        self.epoch = int(snap["epoch"])
        self.applied_hi = int(snap["applied_hi"])
        self.skip_until = max(self.skip_until, self.applied_hi)
        self.values = snap["values"].copy()
        if self._mesh is not None:
            self.shard(self._mesh)
        flight.record(
            "state_rollback", applied_hi=self.applied_hi,
            resident=self.resident,
        )

    # -- sharding / migration ---------------------------------------------

    def shard(self, mesh) -> None:
        """Place the value buffer sharded over the mesh data axis (rows
        are padded to a multiple of 256, so any data width divides)."""
        if mesh is None:
            return
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from flink_jpmml_tpu.parallel.mesh import DATA_AXIS

        self._mesh = mesh
        self.values = jax.device_put(
            np.asarray(self.values),
            NamedSharding(mesh, P(DATA_AXIS, None)),
        )

    def migrate(self, new_mesh) -> None:
        """Degraded-rebuild hook: re-place every row across the
        surviving chips. Slot = hash % capacity is mesh-independent,
        so chip loss moves state WITH its keys — no key loses its
        state vector (pinned in tests)."""
        if new_mesh is None:
            return
        host = np.asarray(self.values)
        self.shard(new_mesh)
        # force the re-placement from the host copy (shard() re-placed
        # self.values, which may still reference lost devices)
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from flink_jpmml_tpu.parallel.mesh import DATA_AXIS

        self.values = jax.device_put(
            host, NamedSharding(new_mesh, P(DATA_AXIS, None))
        )
        flight.record(
            "state_migrate",
            data=int(new_mesh.shape.get(DATA_AXIS, 1)),
            resident=self.resident,
        )

    # -- snapshots ---------------------------------------------------------

    def _host_snapshot(self) -> Dict[str, Any]:
        return {
            "version": _SNAPSHOT_VERSION,
            "capacity": self.capacity,
            "keys": self._keys.copy(),
            "occ": self._occ.copy(),
            "touch": self._touch.copy(),
            "resident": self.resident,
            "epoch": self.epoch,
            "applied_hi": self.applied_hi,
            "seq": self._seq,
            "values": np.asarray(self.values).copy(),
        }

    def snapshot(self) -> Dict[str, Any]:
        """Materialize a consistent host snapshot (blocks on in-flight
        device updates — called on the score thread between batches)
        and pin it as the in-memory rollback point."""
        snap = self._host_snapshot()
        self._snap = snap
        return snap

    def save_sidecar(self, directory: str) -> Optional[str]:
        """Write the snapshot beside the checkpoints with the atomic-
        writer discipline (tmp → fsync → replace → dir fsync) →
        sidecar filename, or None when the write failed (checkpointing
        must degrade, not kill serving)."""
        snap = self.snapshot()
        name = f"state-{snap['applied_hi']:020d}.npz"
        path = os.path.join(directory, name)
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            os.makedirs(directory, exist_ok=True)
            with open(tmp, "wb") as f:
                np.savez(f, **_npz_payload(snap))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            dfd = os.open(directory, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        self._gc_sidecars(directory, keep=name)
        return name

    @staticmethod
    def _gc_sidecars(directory: str, keep: str) -> None:
        try:
            snaps = sorted(
                f for f in os.listdir(directory)
                if f.startswith("state-") and f.endswith(".npz")
            )
        except OSError:
            return
        for f in snaps[:-_SNAPSHOT_KEEP]:
            if f != keep:
                try:
                    os.unlink(os.path.join(directory, f))
                except OSError:
                    pass

    def restore_sidecar(self, directory: str, name: str) -> bool:
        path = os.path.join(directory, name)
        try:
            with np.load(path) as z:
                snap = _from_npz(z)
        except (OSError, ValueError, KeyError):
            flight.record("state_restore_missing", file=name)
            return False
        return self._adopt_snapshot(snap)

    def to_payload(self) -> Dict[str, Any]:
        """Inline base64 snapshot for the record path's checkpoint
        JSON (small tables only — the block path uses sidecar files)."""
        if self.capacity > _INLINE_CAP:
            raise InputValidationException(
                f"state capacity {self.capacity} too large to inline "
                f"in a checkpoint (cap {_INLINE_CAP}); use a sidecar"
            )
        buf = io.BytesIO()
        np.savez(buf, **_npz_payload(self.snapshot()))
        return {
            "version": _SNAPSHOT_VERSION,
            "npz_b64": base64.b64encode(buf.getvalue()).decode("ascii"),
        }

    def from_payload(self, payload: Dict[str, Any]) -> bool:
        raw = payload.get("npz_b64")
        if not raw:
            return False
        try:
            with np.load(io.BytesIO(base64.b64decode(raw))) as z:
                snap = _from_npz(z)
        except (ValueError, KeyError):
            return False
        return self._adopt_snapshot(snap)

    def _adopt_snapshot(self, snap: Dict[str, Any]) -> bool:
        """→ False when the snapshot is refused (geometry mismatch):
        the caller must know the table stayed as it was — a True from
        a restore that silently no-opped would let replay double-fold
        decisions ride an empty table unnoticed."""
        if int(snap["capacity"]) != self.capacity:
            flight.record(
                "state_restore_mismatch",
                snapshot=int(snap["capacity"]), table=self.capacity,
            )
            return False
        self._keys = snap["keys"].astype(np.uint32)
        self._occ = snap["occ"].astype(bool)
        self._touch = snap["touch"].astype(np.int64)
        self.resident = int(snap["resident"])
        self.epoch = int(snap["epoch"])
        self._seq = int(snap.get("seq", 0))
        self.applied_hi = int(snap["applied_hi"])
        # exactly-once: replayed offsets below the snapshot's
        # high-water were already folded in — bypass them
        self.skip_until = self.applied_hi
        self.values = snap["values"].astype(np.float32)
        if self.values.shape != (self.rows, STATE_WIDTH):
            # snapshot from a different row padding: re-pad
            v = np.zeros((self.rows, STATE_WIDTH), np.float32)
            n = min(self.values.shape[0], self.rows)
            v[:n] = self.values[:n]
            self.values = v
        self._snap = self._host_snapshot()
        self._g_resident.set(float(self.resident))
        self._g_occupancy.set(self.resident / float(self.capacity))
        if self._mesh is not None:
            self.shard(self._mesh)
        flight.record(
            "state_restore", applied_hi=self.applied_hi,
            resident=self.resident,
        )
        return True

    # -- drift on derived features ----------------------------------------

    def drift_shim(self, model_hash: Optional[str]):
        """A ``record_features``-compatible handle for the DERIVED
        feature stream: ``<model_hash>#state`` shares the model's
        content addressing, so a recompile keeps the same baseline
        and state corruption surfaces as feature drift."""
        label = f"{model_hash or 'state'}#state"
        shim = self._shims.get(label)
        if shim is None:
            shim = _DriftShim(label)
            self._shims[label] = shim
        return shim


class _DerivedWire:
    """Minimal wire facade over the derived feature vector: names for
    the drift handles, cut-less domains (derived features have no
    threshold tables — out-of-domain never fires)."""

    fields = DERIVED_FIELDS
    cuts = [[] for _ in DERIVED_FIELDS]


class _DriftShim:
    __slots__ = ("model_hash", "wire")

    def __init__(self, label: str):
        self.model_hash = label
        self.wire = _DerivedWire()


def _npz_payload(snap: Dict[str, Any]) -> Dict[str, np.ndarray]:
    return {
        "version": np.int64(snap["version"]),
        "capacity": np.int64(snap["capacity"]),
        "keys": snap["keys"],
        "occ": snap["occ"],
        "touch": snap["touch"],
        "resident": np.int64(snap["resident"]),
        "epoch": np.int64(snap["epoch"]),
        "applied_hi": np.int64(snap["applied_hi"]),
        "seq": np.int64(snap["seq"]),
        "values": snap["values"],
    }


def _from_npz(z) -> Dict[str, Any]:
    return {
        "version": int(z["version"]),
        "capacity": int(z["capacity"]),
        "keys": z["keys"],
        "occ": z["occ"],
        "touch": z["touch"],
        "resident": int(z["resident"]),
        "epoch": int(z["epoch"]),
        "applied_hi": int(z["applied_hi"]),
        "seq": int(z["seq"]),
        "values": z["values"],
    }


def is_state_output(out) -> bool:
    """Is ``out`` a fused-state dispatch result ``(score_out,
    derived)``? Unambiguous: a regression score is 1-D, a
    classification output is a 3-tuple — never a 2-tuple whose second
    element is a ``[B, STATE_WIDTH]`` matrix."""
    return (
        type(out) is tuple
        and len(out) == 2
        and getattr(out[1], "ndim", 0) == 2
        and out[1].shape[-1] == STATE_WIDTH
        and (type(out[0]) is tuple or getattr(out[0], "ndim", 0) == 1)
    )


def split_output(out):
    """→ ``(score_out, derived_or_None)``."""
    if is_state_output(out):
        return out[0], out[1]
    return out, None


def record_derived(dplane, table: KeyedStateTable,
                   model_hash: Optional[str], derived, n: int) -> None:
    """Feed one batch's derived session features to the drift plane
    (sampled + budgeted inside ``record_features`` — the D2H fetch
    happens only for claimed batches)."""
    if dplane is None or derived is None or not n:
        return
    shim = table.drift_shim(model_hash)
    try:
        dplane.record_features(shim, np.asarray(derived)[:n], None)
    except Exception:
        pass  # observability must never kill delivery
