"""Kafka wire-protocol streaming: real binary-protocol consumer + broker.

BASELINE config 2 puts the north-star GBM on a "Kafka tabular stream"; the
reference rode Flink's Kafka connector (SURVEY.md §2 EXT-A). Round 2
shipped a bespoke framed-TCP stand-in (runtime/net.py, honest about not
being Kafka). This module closes the wire-compatibility gap: a consumer
speaking the actual Kafka binary protocol — ApiVersions v0, Metadata v1,
ListOffsets v1, Fetch v4 with magic-v2 record batches (CRC32C, zigzag
varints) — behind the same ``Source``/``BlockSource`` interfaces, plus an
in-process ``MiniKafkaBroker`` serving the identical protocol for tests
and kill/resume drills (the same pattern the FJT1 server plays for the
bespoke protocol).

Offset domain: Kafka partition offsets ARE record indices, so the engine
convention (offset k = "k records consumed" = next record index) maps
1:1 — ``seek(k)`` fetches from Kafka offset ``k`` with no bridging
arithmetic, and the offset checkpointed after scoring record ``i`` is
``i + 1`` (see runtime/net.py's domain note; both sources share it).

Scope: consumption without consumer groups — the framework's keyed
partitioner (parallel/partitioner.py) routes records to workers, so
group coordination (JoinGroup/SyncGroup/OffsetCommit) is not needed;
checkpoints own the offsets (capability C7), which is also the
exactly-once-correct place for them. Multi-partition topics are
consumed via ``partitions=[...]`` in one of two interleave modes (see
``_KafkaSourceBase``): the default ``"auto"`` tolerates what real
brokers serve — keyed producers, uneven partition fill, compaction
gaps — and checkpoints a per-partition OFFSET VECTOR through the
engine's ``checkpoint_state``/``restore_state`` hooks; ``"strict"`` is
the round-robin-bijection fast path whose single scalar offset encodes
every cursor and reconstructs the producer's global order (requires a
round-robin producer and gapless partitions).

All integers big-endian per the Kafka protocol; record-batch varints are
protobuf zigzag.
"""

from __future__ import annotations

import collections
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from flink_jpmml_tpu.obs import attr
from flink_jpmml_tpu.obs import freshness as fresh_mod
from flink_jpmml_tpu.obs import recorder as flight
from flink_jpmml_tpu.obs import trace as trace_mod
from flink_jpmml_tpu.runtime import faults
from flink_jpmml_tpu.runtime.block import BlockSource
from flink_jpmml_tpu.runtime.sources import Polled, Record, Source
from flink_jpmml_tpu.utils.retry import Backoff

API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3
API_VERSIONS = 18

_I8 = struct.Struct(">b")
_I16 = struct.Struct(">h")
_I32 = struct.Struct(">i")
_I64 = struct.Struct(">q")
_U32 = struct.Struct(">I")

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli) — record-batch checksum. Table-driven; the table is
# built once at import.
# ---------------------------------------------------------------------------

_CRC32C_POLY = 0x82F63B78
_CRC32C_TABLE: List[int] = []
for _n in range(256):
    _c = _n
    for _ in range(8):
        _c = (_c >> 1) ^ _CRC32C_POLY if _c & 1 else _c >> 1
    _CRC32C_TABLE.append(_c)


def crc32c(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC32C_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


class _Crc32cVec:
    """Vectorized CRC32C over GF(2) — the checksum half of the numpy
    batch decoder (:func:`decode_record_batches_rows_vec`).

    CRC is bit-linear: with ``F(s, M)`` the raw table fold of message
    ``M`` from state ``s``, ``F(s, M) = F(0, M) ^ Z_len(M)(s)`` where
    ``Z_k`` is the linear "advance past k zero bytes" operator, and
    ``F(0, A||B) = Z_len(B)(F(0, A)) ^ F(0, B)`` (the ``crc32_combine``
    identity). So the serial byte loop decomposes into (1) per-8-byte-
    word raw CRCs — eight table gathers over the whole buffer at once —
    and (2) a log-depth tree of pairwise combines, each level one
    fixed-shift operator applied via four byte-indexed lookup tables.
    Leading zero bytes are no-ops from state 0, so the word array is
    zero-PADDED AT THE FRONT to a power of two and every tree level
    stays uniform. Operators and their tables are cached per level
    (they depend only on the shift length); the ≤7 tail bytes and the
    init/final conditioning fold in scalar.
    """

    def __init__(self) -> None:
        self.T = np.array(_CRC32C_TABLE, np.uint32)
        # word tables: W[j][b] = F(0, byte b followed by (7-j) zeros)
        W = [self.T] * 8
        for j in range(6, -1, -1):
            p = W[j + 1]
            W[j] = (p >> np.uint32(8)) ^ self.T[p & np.uint32(0xFF)]
        self.W = W
        # squaring chain: _sq[m] = columns of Z1^(2^m) (Z1 = one zero
        # byte); column i is the operator's image of bit i. Built
        # EAGERLY and in full (2^35-byte messages dwarf any fetch):
        # the engine is shared process-wide across decode sidecars and
        # broker handler threads, and a lazily-extended list raced —
        # interleaved append/read inserted duplicate entries whose
        # wrong operators then got baked into the level-table cache,
        # permanently mis-CRCing every batch after a cold concurrent
        # start. Frozen-at-init data needs no locks.
        basis = np.uint32(1) << np.arange(32, dtype=np.uint32)
        sqs = [(basis >> np.uint32(8)) ^ self.T[basis & np.uint32(0xFF)]]
        for _ in range(34):
            sqs.append(self._mat_mul(sqs[-1], sqs[-1]))
        self._sqs = tuple(sqs)
        # level-table cache: misses recompute from the frozen chain, so
        # a concurrent double-compute stores equal values (benign)
        self._lvl_tables: Dict[int, list] = {}

    @staticmethod
    def _mat_mul(A: np.ndarray, B: np.ndarray) -> np.ndarray:
        out = np.zeros(32, np.uint32)
        for i in range(32):
            out ^= np.where(
                (B >> np.uint32(i)) & np.uint32(1), A[i], np.uint32(0)
            )
        return out

    def _sq(self, m: int) -> np.ndarray:
        return self._sqs[m]

    def _shift_scalar(self, x: int, n_bytes: int) -> int:
        """Z_{n_bytes}(x) for one state (binary decomposition)."""
        m = 0
        while n_bytes:
            if n_bytes & 1:
                cols = self._sq(m)
                acc = 0
                for i in range(32):
                    if (x >> i) & 1:
                        acc ^= int(cols[i])
                x = acc
            n_bytes >>= 1
            m += 1
        return x

    def _level(self, k: int) -> list:
        """Byte-lookup tables for Z_{8·2^k} (= Z1^(2^(3+k)))."""
        tbls = self._lvl_tables.get(k)
        if tbls is None:
            cols = self._sq(3 + k)
            idx = np.arange(256, dtype=np.uint32)
            tbls = []
            for p in range(4):
                t = np.zeros(256, np.uint32)
                for j in range(8):
                    t ^= np.where(
                        (idx >> np.uint32(j)) & np.uint32(1),
                        cols[8 * p + j], np.uint32(0),
                    )
                tbls.append(t)
            self._lvl_tables[k] = tbls
        return tbls

    def crc(self, data) -> int:
        a = np.frombuffer(data, np.uint8)
        n = a.shape[0]
        if n < 64:  # the numpy setup outweighs tiny bodies
            return crc32c(bytes(data))
        nw = n >> 3
        words = a[: nw * 8].reshape(nw, 8)
        c = self.W[0][words[:, 0]]
        for j in range(1, 8):
            c ^= self.W[j][words[:, j]]
        pad = (1 << (nw - 1).bit_length()) - nw
        if pad:
            c = np.concatenate([np.zeros(pad, np.uint32), c])
        k = 0
        while c.shape[0] > 1:
            t0, t1, t2, t3 = self._level(k)
            left, right = c[0::2], c[1::2]
            c = (
                t0[left & np.uint32(0xFF)]
                ^ t1[(left >> np.uint32(8)) & np.uint32(0xFF)]
                ^ t2[(left >> np.uint32(16)) & np.uint32(0xFF)]
                ^ t3[(left >> np.uint32(24)) & np.uint32(0xFF)]
                ^ right
            )
            k += 1
        raw = int(c[0])
        for b in a[nw * 8 :]:  # ≤ 7 tail bytes
            raw = (raw >> 8) ^ _CRC32C_TABLE[(raw ^ int(b)) & 0xFF]
        return raw ^ self._shift_scalar(0xFFFFFFFF, n) ^ 0xFFFFFFFF


_CRC_VEC: Optional[_Crc32cVec] = None


def crc32c_vec(data) -> int:
    """CRC32C via the vectorized engine (lazily built; parity with
    :func:`crc32c` is pinned by tests/test_prefetch.py)."""
    global _CRC_VEC
    if _CRC_VEC is None:
        _CRC_VEC = _Crc32cVec()
    return _CRC_VEC.crc(data)


# ---------------------------------------------------------------------------
# Zigzag varints (record encoding)
# ---------------------------------------------------------------------------


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_varint(out: bytearray, n: int) -> None:
    v = _zigzag(n) & 0xFFFFFFFFFFFFFFFF
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    shift = 0
    acc = 0
    while True:
        b = buf[pos]
        pos += 1
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            return _unzigzag(acc), pos
        shift += 7


# ---------------------------------------------------------------------------
# Primitive readers/writers (big-endian, Kafka classic encoding)
# ---------------------------------------------------------------------------


class _Writer:
    def __init__(self) -> None:
        self.b = bytearray()

    def i8(self, v: int) -> "_Writer":
        self.b += _I8.pack(v)
        return self

    def i16(self, v: int) -> "_Writer":
        self.b += _I16.pack(v)
        return self

    def i32(self, v: int) -> "_Writer":
        self.b += _I32.pack(v)
        return self

    def i64(self, v: int) -> "_Writer":
        self.b += _I64.pack(v)
        return self

    def string(self, s: Optional[str]) -> "_Writer":
        if s is None:
            return self.i16(-1)
        raw = s.encode()
        self.i16(len(raw))
        self.b += raw
        return self

    def bytes_(self, raw: Optional[bytes]) -> "_Writer":
        if raw is None:
            return self.i32(-1)
        self.i32(len(raw))
        self.b += raw
        return self

    def raw(self, raw: bytes) -> "_Writer":
        self.b += raw
        return self


class _Reader:
    def __init__(self, buf: bytes) -> None:
        self.buf = buf
        self.pos = 0

    def i8(self) -> int:
        (v,) = _I8.unpack_from(self.buf, self.pos)
        self.pos += 1
        return v

    def i16(self) -> int:
        (v,) = _I16.unpack_from(self.buf, self.pos)
        self.pos += 2
        return v

    def i32(self) -> int:
        (v,) = _I32.unpack_from(self.buf, self.pos)
        self.pos += 4
        return v

    def i64(self) -> int:
        (v,) = _I64.unpack_from(self.buf, self.pos)
        self.pos += 8
        return v

    def string(self) -> Optional[str]:
        n = self.i16()
        if n < 0:
            return None
        s = self.buf[self.pos : self.pos + n].decode()
        self.pos += n
        return s

    def bytes_(self) -> Optional[bytes]:
        n = self.i32()
        if n < 0:
            return None
        raw = bytes(self.buf[self.pos : self.pos + n])
        self.pos += n
        return raw

    def bytes_view(self) -> Optional[memoryview]:
        """Like :meth:`bytes_` but ZERO-COPY: a memoryview into the
        response payload (which the view keeps alive). The fetch path
        hands these straight to the record-batch decoders, so a 4MB
        record set is never duplicated between socket and decode."""
        n = self.i32()
        if n < 0:
            return None
        raw = memoryview(self.buf)[self.pos : self.pos + n]
        self.pos += n
        return raw


# ---------------------------------------------------------------------------
# Record batches (magic v2)
# ---------------------------------------------------------------------------


def encode_record_batch(
    base_offset: int,
    values: Sequence[bytes],
    timestamp_ms: int = 0,
    headers: Optional[Sequence[Optional[Sequence[Tuple[str, bytes]]]]] = None,
) -> bytes:
    """values → one magic-2 record batch (null keys). ``headers`` is an
    optional per-record list aligned with ``values``: each entry None
    (no headers) or ``[(key, value_bytes), ...]`` — the carrier the
    record-journey tracing plane uses for ``traceparent`` propagation
    (obs/trace.py; ``fjt-dlq redrive`` stamps one so a redriven
    record's journey links its original)."""
    recs = bytearray()
    for i, v in enumerate(values):
        body = bytearray()
        body += _I8.pack(0)  # record attributes
        write_varint(body, 0)  # timestamp delta
        write_varint(body, i)  # offset delta
        write_varint(body, -1)  # null key
        write_varint(body, len(v))
        body += v
        hdrs = headers[i] if headers is not None else None
        if hdrs:
            write_varint(body, len(hdrs))
            for hk, hv in hdrs:
                hk_raw = hk.encode() if isinstance(hk, str) else bytes(hk)
                hv_raw = bytes(hv)
                write_varint(body, len(hk_raw))
                body += hk_raw
                write_varint(body, len(hv_raw))
                body += hv_raw
        else:
            write_varint(body, 0)  # headers count
        rec = bytearray()
        write_varint(rec, len(body))
        rec += body
        recs += rec

    n = len(values)
    # the crc covers everything AFTER the crc field
    post = _Writer()
    post.i16(0)  # attributes: no compression, CreateTime
    post.i32(n - 1)  # last offset delta
    post.i64(timestamp_ms)  # first timestamp
    post.i64(timestamp_ms)  # max timestamp
    post.i64(-1)  # producer id
    post.i16(-1)  # producer epoch
    post.i32(-1)  # base sequence
    post.i32(n)
    post.raw(bytes(recs))
    crc = crc32c_vec(bytes(post.b))

    w = _Writer()
    w.i64(base_offset)
    w.i32(4 + 1 + 4 + len(post.b))  # batch length (after this field)
    w.i32(-1)  # partition leader epoch
    w.i8(2)  # magic
    w.raw(_U32.pack(crc))
    w.raw(bytes(post.b))
    return bytes(w.b)


def decode_record_batches(buf) -> List[Tuple[int, bytes]]:
    """record-set bytes (or memoryview — the zero-copy fetch path) →
    [(absolute offset, value)] across all batches.

    Tolerates a trailing partial batch (Kafka may truncate at max_bytes)."""
    out: List[Tuple[int, bytes]] = []
    mv = memoryview(buf)  # batch bodies slice zero-copy below
    pos = 0
    while pos + 12 <= len(buf):
        (base_offset,) = _I64.unpack_from(buf, pos)
        (batch_len,) = _I32.unpack_from(buf, pos + 8)
        end = pos + 12 + batch_len
        # 49 = minimum v2 batch body (partitionLeaderEpoch..records count);
        # anything shorter cannot hold the magic/CRC we read below, so treat
        # it as a truncated trailing batch rather than indexing past it.
        if batch_len < 49 or end > len(buf):
            break  # partial trailing batch
        magic = buf[pos + 16]
        if magic != 2:
            raise ValueError(f"unsupported record-batch magic {magic}")
        (crc_stored,) = _U32.unpack_from(buf, pos + 17)
        body = mv[pos + 21 : end]
        if crc32c_vec(body) != crc_stored:
            raise ValueError("record batch CRC32C mismatch")
        r = _Reader(body)
        r.i16()  # attributes (compression unsupported: we never emit it)
        r.i32()  # last offset delta
        r.i64()  # first ts
        r.i64()  # max ts
        r.i64()  # producer id
        r.i16()  # producer epoch
        r.i32()  # base sequence
        count = r.i32()
        p = r.pos
        for _ in range(count):
            rec_len, p = read_varint(body, p)
            rec_end = p + rec_len
            p += 1  # record attributes
            _, p = read_varint(body, p)  # timestamp delta
            off_delta, p = read_varint(body, p)
            klen, p = read_varint(body, p)
            if klen > 0:
                p += klen
            vlen, p = read_varint(body, p)
            value = body[p : p + vlen] if vlen >= 0 else b""
            out.append((base_offset + off_delta, bytes(value)))
            p = rec_end
        pos = end
    return out


def decode_record_batches_h(
    buf,
) -> List[Tuple[int, bytes, Optional[List[Tuple[str, bytes]]]]]:
    """record-set bytes → [(absolute offset, value, headers)] across
    all whole batches — the header-aware decoder shape (headers is
    None when a record carries none). :func:`decode_record_batches`
    stays the fast header-skipping path; this one exists for the
    consumers that NEED headers: traceparent pickup (record-journey
    tracing) and the MiniKafkaBroker's Produce handler (headers must
    survive a redrive round-trip)."""
    out: List[Tuple[int, bytes, Optional[List[Tuple[str, bytes]]]]] = []
    mv = memoryview(buf)
    pos = 0
    while pos + 12 <= len(buf):
        (base_offset,) = _I64.unpack_from(buf, pos)
        (batch_len,) = _I32.unpack_from(buf, pos + 8)
        end = pos + 12 + batch_len
        if batch_len < 49 or end > len(buf):
            break  # partial trailing batch
        magic = buf[pos + 16]
        if magic != 2:
            raise ValueError(f"unsupported record-batch magic {magic}")
        (crc_stored,) = _U32.unpack_from(buf, pos + 17)
        body = mv[pos + 21 : end]
        if crc32c_vec(body) != crc_stored:
            raise ValueError("record batch CRC32C mismatch")
        r = _Reader(body)
        r.i16()  # attributes
        r.i32()  # last offset delta
        r.i64()  # first ts
        r.i64()  # max ts
        r.i64()  # producer id
        r.i16()  # producer epoch
        r.i32()  # base sequence
        count = r.i32()
        p = r.pos
        for _ in range(count):
            rec_len, p = read_varint(body, p)
            rec_end = p + rec_len
            p += 1  # record attributes
            _, p = read_varint(body, p)  # timestamp delta
            off_delta, p = read_varint(body, p)
            klen, p = read_varint(body, p)
            if klen > 0:
                p += klen
            vlen, p = read_varint(body, p)
            value = body[p : p + vlen] if vlen >= 0 else b""
            p += max(vlen, 0)
            n_hdrs, p = read_varint(body, p)
            hdrs: Optional[List[Tuple[str, bytes]]] = None
            if n_hdrs > 0:
                hdrs = []
                for _h in range(n_hdrs):
                    hklen, p = read_varint(body, p)
                    hkey = bytes(body[p : p + hklen]).decode(
                        "utf-8", "replace"
                    )
                    p += hklen
                    hvlen, p = read_varint(body, p)
                    hval = bytes(body[p : p + max(hvlen, 0)])
                    p += max(hvlen, 0)
                    hdrs.append((hkey, hval))
            out.append((base_offset + off_delta, bytes(value), hdrs))
            p = rec_end
        pos = end
    return out


def record_batch_traceparents(buf: bytes) -> Dict[int, str]:
    """record-set bytes → {absolute offset: traceparent string} for
    the records carrying a ``traceparent`` header. A HEADER-ONLY walk:
    no CRC pass (the real decode path already verified it, or will),
    no value copies — key/value payloads are skipped by length, and
    the common no-headers record costs the varint walk up to its zero
    headers-count. The sources run this at all only when the journey
    plane is armed (the PR 7 timestamp plumbing's gating template);
    malformed bytes return what was parsed so far — transport damage
    raises on the DECODE path, not here."""
    out: Dict[int, str] = {}
    try:
        pos = 0
        while pos + 12 <= len(buf):
            (base_offset,) = _I64.unpack_from(buf, pos)
            (batch_len,) = _I32.unpack_from(buf, pos + 8)
            end = pos + 12 + batch_len
            if batch_len < 49 or end > len(buf):
                break  # partial trailing batch
            if buf[pos + 16] != 2:
                break  # foreign magic: the decode path will raise
            body = memoryview(buf)[pos + 21 : end]
            count = _I32.unpack_from(body, 36)[0]
            p = 40  # first record (past the fixed batch header tail)
            for _ in range(count):
                rec_len, p = read_varint(body, p)
                rec_end = p + rec_len
                p += 1  # record attributes
                _, p = read_varint(body, p)  # timestamp delta
                off_delta, p = read_varint(body, p)
                klen, p = read_varint(body, p)
                if klen > 0:
                    p += klen
                vlen, p = read_varint(body, p)
                p += max(vlen, 0)  # skip the value, no copy
                n_hdrs, p = read_varint(body, p)
                for _h in range(n_hdrs):
                    hklen, p = read_varint(body, p)
                    hkey = bytes(body[p : p + hklen])
                    p += hklen
                    hvlen, p = read_varint(body, p)
                    if hkey == b"traceparent":
                        out[base_offset + off_delta] = bytes(
                            body[p : p + max(hvlen, 0)]
                        ).decode("ascii", "replace")
                    p += max(hvlen, 0)
                p = rec_end
            pos = end
    except (IndexError, ValueError, struct.error):
        return out
    return out


def decode_record_batches_rows(
    buf, n_cols: int
) -> Tuple[np.ndarray, np.ndarray]:
    """record-set bytes → (offsets int64 [n], rows f32 [n, n_cols]) for
    the tabular contract (every value one packed f32-LE feature row).

    Three tiers, fastest available wins: the C++ decoder
    (native.kafka_decode_fixed), then the vectorized numpy decoder
    (:func:`decode_record_batches_rows_vec` — one pass building the
    record offset table, then bulk gather), then the per-record Python
    walk (:func:`decode_record_batches_rows_py`, the parity oracle the
    other two are byte-pinned against — the pure-Python varint walk +
    CRC caps Kafka ingest at ~50k rec/s, two decades under the config-2
    north star). ``buf`` may be ``bytes`` or a ``memoryview`` (the
    zero-copy fetch path hands views of the response payload straight
    through). CRC and framing errors raise ValueError identically on
    every tier."""
    from flink_jpmml_tpu.runtime import native

    dec = native.kafka_decode_fixed(buf, 4 * n_cols)
    if dec is not None:
        offs, vals = dec
        return offs, vals.view(np.float32)
    return decode_record_batches_rows_vec(buf, n_cols)


def decode_record_batches_rows_py(
    buf, n_cols: int
) -> Tuple[np.ndarray, np.ndarray]:
    """The per-record Python walk — the PARITY ORACLE for the native
    and vectorized decoders (tools/decode_bench.py races all three;
    tests pin byte equality)."""
    recs = decode_record_batches(buf)
    offs = np.fromiter(
        (o for o, _ in recs), np.int64, count=len(recs)
    )
    rows = np.empty((len(recs), n_cols), np.float32)
    for i, (_, value) in enumerate(recs):
        if len(value) != 4 * n_cols:
            # exact-length contract, matching the C++ decoder (which
            # refuses non-fixed record sets): np.frombuffer(count=)
            # would silently TRUNCATE an over-long value into a
            # plausible-looking row — the worst kind of poison
            raise ValueError(
                f"record value length {len(value)} != {4 * n_cols} "
                f"(n_cols={n_cols})"
            )
        rows[i] = np.frombuffer(value, np.float32, count=n_cols)
    return offs, rows


def _vint_len_vec(u: np.ndarray) -> np.ndarray:
    """Varint byte length of (already-zigzagged) non-negative values."""
    w = np.ones_like(u)
    for k in (7, 14, 21, 28):
        w += u >= (1 << k)
    return w


def _vint_bytes(u: int) -> bytes:
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _vint_check(
    a: np.ndarray, pos: np.ndarray, u: np.ndarray, w: np.ndarray
) -> bool:
    """Do the bytes at ``pos`` encode varints of (zigzagged) ``u``
    with widths ``w``? Vectorized over records, one gather per byte
    position (width ≤ 2 in practice: off_delta < SEG_RECORDS)."""
    for j in range(int(w.max())):
        m = w > j
        exp = (u >> (7 * j)) & 0x7F
        exp = np.where(w > j + 1, exp | 0x80, exp)
        if not (a[pos[m] + j] == exp[m]).all():
            return False
    return True


def _vec_batch_rows(
    a: np.ndarray, rstart: int, rend: int, count: int, V: int
):
    """One batch's records region → uint8 rows [count, V], or None when
    the region is not the canonical tabular layout (then the Python
    walk decides — it handles headers, keys, gaps, and raises on
    wrong-length values).

    Canonical layout (what both our encoders and real round-robin
    producers of fixed-width values emit): per record ``varint(len)``,
    attributes 0, timestamp delta 0, offset delta == record index, null
    key, value length V, zero headers. Every field position is then
    CLOSED-FORM in the record index, so the decode is: build the offset
    table arithmetically, VERIFY the assumed framing bytes with a
    handful of vectorized gathers, and bulk-gather the values."""
    if count <= 0:
        return None
    d = np.arange(count, dtype=np.int64)
    w_od = _vint_len_vec(2 * d)
    vl_bytes = _vint_bytes(2 * V)  # zigzag(V), V ≥ 0
    w_vl = len(vl_bytes)
    body_len = 4 + w_od + w_vl + V
    u_rl = 2 * body_len
    w_rl = _vint_len_vec(u_rl)
    tot = w_rl + body_len
    starts = rstart + np.concatenate(
        ([0], np.cumsum(tot[:-1]))
    )
    if int(starts[-1] + tot[-1]) != rend:
        return None
    p = starts + w_rl
    pk = p + 2 + w_od
    if not (
        _vint_check(a, starts, u_rl, w_rl)  # record length
        and bool((a[p] == 0).all())  # record attributes
        and bool((a[p + 1] == 0).all())  # timestamp delta 0
        and _vint_check(a, p + 2, 2 * d, w_od)  # offset delta == index
        and bool((a[pk] == 1).all())  # null key (zigzag −1)
        and bool((a[starts + tot - 1] == 0).all())  # zero headers
    ):
        return None
    for j, bv in enumerate(vl_bytes):  # value length == V, all records
        if not (a[pk + 1 + j] == bv).all():
            return None
    vpos = pk + 1 + w_vl
    return a[vpos[:, None] + np.arange(V)]


def decode_record_batches_rows_vec(
    buf, n_cols: int
) -> Tuple[np.ndarray, np.ndarray]:
    """The vectorized numpy decoder: record-set bytes → (offsets int64,
    rows f32 [n, n_cols]) in bulk array passes — the offset table
    first, then one fancy-index gather per batch slicing every value
    out of the buffer at once — with the CRC check riding the
    word-parallel engine (:class:`_Crc32cVec`). Anything off the
    canonical fixed-width layout (record headers — a traceparent
    redrive —, key'd records, offset-delta gaps, wrong-length values)
    falls back to :func:`decode_record_batches_rows_py` for the whole
    record set, which decodes-or-raises with oracle semantics. CRC,
    magic, and framing errors raise ValueError exactly like the oracle."""
    a = np.frombuffer(buf, np.uint8)
    ln = a.shape[0]
    out_offs: List[np.ndarray] = []
    out_rows: List[np.ndarray] = []
    V = 4 * n_cols
    pos = 0
    while pos + 12 <= ln:
        (base_offset,) = _I64.unpack_from(buf, pos)
        (batch_len,) = _I32.unpack_from(buf, pos + 8)
        end = pos + 12 + batch_len
        if batch_len < 49 or end > ln:
            break  # partial trailing batch
        magic = a[pos + 16]
        if magic != 2:
            raise ValueError(f"unsupported record-batch magic {magic}")
        (crc_stored,) = _U32.unpack_from(buf, pos + 17)
        if crc32c_vec(a[pos + 21 : end]) != crc_stored:
            raise ValueError("record batch CRC32C mismatch")
        (count,) = _I32.unpack_from(buf, pos + 21 + 36)
        rows = _vec_batch_rows(a, pos + 21 + 40, end, int(count), V)
        if rows is None:
            return decode_record_batches_rows_py(buf, n_cols)
        out_offs.append(base_offset + np.arange(count, dtype=np.int64))
        out_rows.append(rows)
        pos = end
    if not out_offs:
        return np.empty((0,), np.int64), np.empty((0, n_cols), np.float32)
    offs = np.concatenate(out_offs)
    rows = np.concatenate(out_rows).view(np.float32)
    return offs, rows


def record_batch_time_range(buf: bytes):
    """record-set bytes → (min_event_ts_s, max_event_ts_s) across all
    whole batches, from the magic-v2 batch headers' first/max timestamp
    fields — a header-only walk (no varint/CRC work), cheap enough to
    run per fetch on the hot path. → None when no batch carries a
    positive timestamp (the native encoder stamps 0 = "no event time";
    a 1970 watermark would poison every staleness histogram)."""
    lo = hi = None
    pos = 0
    while pos + 12 <= len(buf):
        (batch_len,) = _I32.unpack_from(buf, pos + 8)
        end = pos + 12 + batch_len
        if batch_len < 49 or end > len(buf):
            break  # truncated trailing batch (cf. decode_record_batches)
        # header layout after the CRC (pos+21): attributes i16, last
        # offset delta i32, first timestamp i64, max timestamp i64
        (first_ms,) = _I64.unpack_from(buf, pos + 27)
        (max_ms,) = _I64.unpack_from(buf, pos + 35)
        if max_ms > 0:
            f = (first_ms if first_ms > 0 else max_ms) / 1000.0
            m = max_ms / 1000.0
            lo = f if lo is None else min(lo, f)
            hi = m if hi is None else max(hi, m)
        pos = end
    return None if hi is None else (lo, hi)


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class KafkaProtocolError(RuntimeError):
    pass


class KafkaPartitionError(KafkaProtocolError):
    """UNKNOWN_TOPIC_OR_PARTITION (err 3): a misconfiguration, not a
    transient wire failure — sources re-raise it instead of entering
    the reconnect-and-retry loop (fail fast, don't poll a phantom
    partition forever)."""


class KafkaClient:
    """Minimal single-connection Kafka client (consumer side).

    Speaks classic (non-flexible) request versions so the framing works
    against any broker from 0.11 on: ApiVersions v0, Metadata v1,
    ListOffsets v1, Fetch v4.
    """

    def __init__(
        self,
        host: str,
        port: int,
        client_id: str = "fjt-consumer",
        timeout_s: float = 10.0,
    ):
        self.host = host
        self.port = port
        self.client_id = client_id
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None
        self._corr = 0

    # -- connection management ------------------------------------------

    def connect(self) -> None:
        self.close()
        s = socket.create_connection(
            (self.host, self.port), timeout=self.timeout_s
        )
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def _request(self, api_key: int, api_version: int, body: bytes) -> _Reader:
        if self._sock is None:
            self.connect()
        self._corr += 1
        hdr = _Writer()
        hdr.i16(api_key).i16(api_version).i32(self._corr).string(
            self.client_id
        )
        msg = bytes(hdr.b) + body
        self._sock.sendall(_I32.pack(len(msg)) + msg)
        raw = self._recv_exact(4)
        (size,) = _I32.unpack(raw)
        payload = self._recv_exact(size)
        r = _Reader(payload)
        corr = r.i32()
        if corr != self._corr:
            raise KafkaProtocolError(
                f"correlation id mismatch: {corr} != {self._corr}"
            )
        return r

    def _recv_exact(self, n: int) -> bytearray:
        # recv_into a preallocated buffer: no per-chunk bytes objects,
        # no append-resize churn, and no final whole-payload copy — the
        # returned bytearray IS what the fetch path's memoryews slice
        buf = bytearray(n)
        view = memoryview(buf)
        got = 0
        while got < n:
            r = self._sock.recv_into(view[got:])
            if not r:
                raise ConnectionError("kafka connection closed")
            got += r
        return buf

    # -- protocol calls --------------------------------------------------

    def api_versions(self) -> Dict[int, Tuple[int, int]]:
        r = self._request(API_VERSIONS, 0, b"")
        err = r.i16()
        if err:
            raise KafkaProtocolError(f"ApiVersions error {err}")
        out = {}
        for _ in range(r.i32()):
            k, lo, hi = r.i16(), r.i16(), r.i16()
            out[k] = (lo, hi)
        return out

    def metadata(self, topic: str):
        """→ (brokers {node: (host, port)}, partitions {index: leader})."""
        w = _Writer()
        w.i32(1).string(topic)
        r = self._request(API_METADATA, 1, bytes(w.b))
        brokers = {}
        for _ in range(r.i32()):
            node = r.i32()
            host = r.string()
            port = r.i32()
            r.string()  # rack
            brokers[node] = (host, port)
        r.i32()  # controller id
        partitions = {}
        for _ in range(r.i32()):
            terr = r.i16()
            name = r.string()
            r.i8()  # is_internal
            nparts = r.i32()
            for _ in range(nparts):
                perr = r.i16()
                idx = r.i32()
                leader = r.i32()
                for _ in range(r.i32()):
                    r.i32()  # replicas
                for _ in range(r.i32()):
                    r.i32()  # isr
                if name == topic and not perr:
                    partitions[idx] = leader
            if name == topic and terr:
                raise KafkaProtocolError(
                    f"Metadata error {terr} for topic {topic!r}"
                )
        return brokers, partitions

    def list_offset(
        self, topic: str, partition: int, timestamp: int
    ) -> int:
        """timestamp −2 = earliest, −1 = latest → partition offset."""
        w = _Writer()
        w.i32(-1)  # replica id
        w.i32(1).string(topic).i32(1).i32(partition).i64(timestamp)
        r = self._request(API_LIST_OFFSETS, 1, bytes(w.b))
        for _ in range(r.i32()):
            r.string()  # topic
            for _ in range(r.i32()):
                r.i32()  # partition
                err = r.i16()
                if err == 3:
                    raise KafkaPartitionError(
                        f"ListOffsets error 3 (unknown partition "
                        f"{partition} of topic {topic!r})"
                    )
                if err:
                    raise KafkaProtocolError(f"ListOffsets error {err}")
                r.i64()  # timestamp
                return r.i64()
        raise KafkaProtocolError("empty ListOffsets response")

    def fetch_raw(
        self,
        topic: str,
        partition: int,
        offset: int,
        max_wait_ms: int = 100,
        min_bytes: int = 1,
        max_bytes: int = 4 << 20,
    ) -> Tuple[int, "bytes | memoryview"]:
        """→ (high watermark, raw record-set bytes). The record set may
        contain whole batches starting before the requested offset —
        decoders filter, exactly like a real consumer.

        ZERO-COPY: the record set is a ``memoryview`` into the response
        payload (the single-partition response shape this client always
        requests), so the bytes travel socket → decoder with no
        intermediate copy; only a multi-chunk response (never produced
        by our requests) pays a join."""
        w = _Writer()
        w.i32(-1)  # replica id
        w.i32(max_wait_ms)
        w.i32(min_bytes)
        w.i32(max_bytes)
        w.i8(0)  # isolation level: read_uncommitted
        w.i32(1).string(topic)
        w.i32(1).i32(partition).i64(offset).i32(max_bytes)
        r = self._request(API_FETCH, 4, bytes(w.b))
        r.i32()  # throttle time
        high_watermark = 0
        chunks: List[memoryview] = []
        for _ in range(r.i32()):
            r.string()  # topic
            for _ in range(r.i32()):
                r.i32()  # partition
                err = r.i16()
                high_watermark = r.i64()
                r.i64()  # last stable offset
                for _ in range(r.i32()):  # aborted transactions
                    r.i64()
                    r.i64()
                chunk = r.bytes_view()
                if chunk is not None and len(chunk):
                    chunks.append(chunk)
                if err == 3:
                    raise KafkaPartitionError(
                        f"Fetch error 3 (unknown partition {partition} "
                        f"of topic {topic!r})"
                    )
                if err:
                    raise KafkaProtocolError(f"Fetch error {err}")
        if not chunks:
            return high_watermark, b""
        if len(chunks) == 1:
            return high_watermark, chunks[0]
        return high_watermark, b"".join(chunks)

    def produce(
        self,
        topic: str,
        partition: int,
        values: Sequence[bytes],
        timestamp_ms: int = 0,
        timeout_ms: int = 10_000,
        headers: Optional[Sequence] = None,
    ) -> int:
        """Produce ``values`` as one magic-2 record batch (Produce v3,
        acks=-1) → the base offset the broker assigned. The consumer
        side never needed this; the ``fjt-dlq redrive`` path does — a
        quarantined record goes back INTO the topic so the live
        pipeline re-scores it through the real consume path.
        ``headers`` (per-record, aligned with ``values``) carries the
        redrive's ``traceparent`` so the record's new journey segment
        links its original (obs/trace.py)."""
        record_set = encode_record_batch(
            0, list(values), timestamp_ms=timestamp_ms, headers=headers
        )
        w = _Writer()
        w.string(None)  # transactional id
        w.i16(-1)  # acks: full ISR
        w.i32(timeout_ms)
        w.i32(1).string(topic)
        w.i32(1).i32(partition).bytes_(record_set)
        r = self._request(API_PRODUCE, 3, bytes(w.b))
        base_offset = -1
        for _ in range(r.i32()):
            r.string()  # topic
            for _ in range(r.i32()):
                r.i32()  # partition
                err = r.i16()
                if err:
                    raise KafkaProtocolError(f"Produce error {err}")
                base_offset = r.i64()
                r.i64()  # log append time
        if base_offset < 0:
            raise KafkaProtocolError("empty Produce response")
        return base_offset

    def fetch(
        self,
        topic: str,
        partition: int,
        offset: int,
        max_wait_ms: int = 100,
        min_bytes: int = 1,
        max_bytes: int = 4 << 20,
    ) -> Tuple[int, List[Tuple[int, bytes]]]:
        """→ (high watermark, [(offset, value)] with offset ≥ requested)."""
        high_watermark, record_set = self.fetch_raw(
            topic, partition, offset, max_wait_ms, min_bytes, max_bytes
        )
        return high_watermark, [
            rec
            for rec in decode_record_batches(record_set)
            if rec[0] >= offset
        ]


# ---------------------------------------------------------------------------
# Sources (engine-facing)
# ---------------------------------------------------------------------------


class _KafkaSourceBase:
    """Shared fetch/reconnect/seek plumbing for both source shapes.

    Single-partition (default): engine offsets ARE Kafka offsets (the
    1:1 domain of the module header).

    Multi-partition (``partitions=[...]``), two interleave modes:

    - ``interleave="auto"`` (default): records are consumed from
      whichever partition has data, in round-robin *preference* but
      never stalling on an empty partition; per-partition cursors
      advance to the offsets actually observed, so keyed producers
      (uneven fill) and compacted logs (offset gaps) — what real
      brokers serve — both work. Resume state is a checkpointed
      per-partition OFFSET VECTOR: the source snapshots its cursor
      vector at every emission boundary, the engine checkpoint embeds
      the newest snapshot ≤ the committed offset
      (``checkpoint_state``), and ``restore_state`` resumes every
      partition from it exactly. A commit landing mid-emission resumes
      from the preceding boundary — strictly less replay than one
      batch, within the C7 at-least-once contract.
    - ``interleave="strict"``: the round-robin bijection fast path —
      global record index g maps to partition ``partitions[g % P]`` at
      partition offset ``g // P``, so the engine's single scalar offset
      encodes every cursor and ``seek(k)`` is exact at ANY k. Requires
      a round-robin producer and gapless partitions (the tabular-stream
      layout); a partition-offset gap raises ``KafkaProtocolError``
      rather than silently mis-aligning lanes. Also reconstructs the
      producer's global record order, which auto mode (arrival order)
      cannot."""

    def __init__(
        self,
        host: str,
        port: int,
        topic: str,
        partition: int = 0,
        partitions: Optional[Sequence[int]] = None,
        start_offset: int = 0,
        max_wait_ms: int = 50,
        reconnect_backoff_s: float = 0.05,
        interleave: str = "auto",
        metrics=None,
        max_bytes: int = 4 << 20,
        dlq=None,
    ):
        self._client = KafkaClient(host, port)
        # dead-letter queue (runtime/dlq.py): when installed, a record
        # whose VALUE doesn't decode is counted per partition
        # (decode_errors), quarantined with its raw bytes, and skipped —
        # one poisoned producer message stops killing the consumer.
        # Without one, decode errors raise exactly as before.
        self._dlq = dlq
        self._decode_err_counters: Dict[object, object] = {}
        self._last_decode_event = 0.0
        # observability (optional MetricsRegistry): fetch-RPC latency as
        # a mergeable histogram, and per-partition consumer lag gauges —
        # kafka_lag{partition="p"} = broker high-water mark minus this
        # consumer's fetch cursor at fetch time, the classic "how far
        # behind is this worker" signal the fleet /metrics view scrapes
        self._metrics = metrics
        self._fetch_hist = (
            metrics.histogram("kafka_fetch_s") if metrics is not None
            else None
        )
        # resolved once, like _fetch_hist: the per-registry lookup is a
        # lock + WeakKeyDictionary hit, too much for the per-fetch path
        self._ledger = attr.ledger_for(metrics)
        # event-time freshness (obs/freshness.py): the tracker is the
        # per-REGISTRY singleton — the pipeline sharing this registry
        # consumes at its sink the stamps this source writes at fetch —
        # while the lag forecaster is per-SOURCE (partition keys are
        # ours alone): produced/consumed rates, drain ETA, and the
        # kafka_lag age-stamping that keeps a stalled partition honest
        self._freshness = fresh_mod.freshness_for(metrics)
        self._forecaster = (
            fresh_mod.LagForecaster(metrics) if metrics is not None
            else None
        )
        # event-time range of the most recent successful fetch (set in
        # _fetch_raw_part/_fetch_part, read by the poll paths when they
        # know which global offsets the decoded rows landed on)
        self._last_trange = None
        # traceparent record headers awaiting their poll-path ingest
        # hop ({offset: str}; populated only when the journey plane is
        # armed). Keyed persistently — NOT per fetch — because the
        # record-source poll path buffers fetch surplus across polls,
        # and the next fetch must not clobber an unconsumed header
        # (the redrive-continuity contract). Bounded; consumed by
        # _journey_ingest, cleared with the buffers on seek/restore.
        self._tps_pending: Dict[int, str] = {}
        self._lag_gauges: Dict[int, object] = {}
        self._topic = topic
        self._parts = (
            tuple(partitions) if partitions is not None else (partition,)
        )
        if len(set(self._parts)) != len(self._parts) or not self._parts:
            raise ValueError(f"bad partition set {self._parts!r}")
        if interleave not in ("auto", "strict"):
            raise ValueError(f"bad interleave mode {interleave!r}")
        self._partition = self._parts[0]
        self._strict = interleave == "strict"
        if (
            len(self._parts) > 1
            and not self._strict
            and start_offset != 0
        ):
            # a scalar start offset has no meaning without the strict
            # bijection: silently accepting it would relabel records
            # (global indices shifted by start_offset) without skipping
            # anything
            raise ValueError(
                "start_offset requires interleave='strict' on a "
                "multi-partition source; auto mode resumes through "
                "restore_state (per-partition offset vector)"
            )
        self._next = start_offset  # next Kafka offset (single-partition)
        self._g = start_offset  # next global record index (multi)
        self._bufs: Dict[int, "collections.deque"] = {
            p: collections.deque() for p in self._parts
        }
        # vector mode: per-partition next-offset cursors + emission-
        # boundary snapshots (global_end, cursor vector) for checkpoint.
        # _snap_mu guards snaps/floor: the ingest thread appends while
        # the score thread's checkpoint_state prunes (block.py runs
        # poll and _ckpt_state on different threads)
        self._cursors: Dict[int, int] = {p: 0 for p in self._parts}
        self._rr = 0  # round-robin preference pointer (auto mode)
        self._snap_mu = threading.Lock()
        self._snaps: "collections.deque" = collections.deque()
        self._snap_floor = (start_offset, dict(self._cursors))
        self._max_wait_ms = max_wait_ms
        # the fetch.max.bytes analogue: bounds how much backlog ONE
        # fetch RPC can slurp — load drills cap it so broker-side lag
        # stays observable instead of teleporting into host memory
        self._max_bytes = int(max_bytes)
        # capped exponential backoff with full jitter (utils/retry.py):
        # the constructor's reconnect_backoff_s is the base delay
        # (FJT_RETRY_* env overrides); consecutive failures back off to
        # the cap so N consumers of a dead broker don't storm it in
        # lockstep the instant it heals, and the current delay rides
        # the reconnect_backoff_s gauge (fleet merge: worst-of)
        self._backoff = Backoff(
            "kafka", base_s=reconnect_backoff_s, metrics=metrics
        )
        self._eos = False

    def _reconnect(self) -> None:
        # reconnect-at-offset: exactly the consumer resume model —
        # nothing is lost or duplicated because the cursors only
        # advance on successfully decoded records
        flight.record(
            "kafka_reconnect", topic=self._topic,
            partitions=list(self._parts),
            attempt=self._backoff.attempts + 1,
        )
        self._client.close()
        self._backoff.sleep()
        try:
            self._client.connect()
        except OSError:
            pass

    def _observe_fetch(self, part: int, offset: int, hw: int,
                       t0: float) -> None:
        if self._metrics is None:
            return
        dt = time.monotonic() - t0
        self._fetch_hist.observe(dt)
        # the attribution plane's fetch column (obs/attr.py): kafka
        # fetch RPC time per fetch, merged fleet-wide like every stage
        if self._ledger is not None:
            self._ledger.observe("fetch", dt)
        g = self._lag_gauges.get(part)
        if g is None:
            g = self._metrics.gauge(f'kafka_lag{{partition="{part}"}}')
            self._lag_gauges[part] = g
        g.set(max(hw - offset, 0))
        if self._forecaster is not None:
            # produced (broker high watermark) vs consumed (our cursor):
            # the sliding-window drain-ETA/trend estimator, plus the
            # age-stamp sweep that keeps EVERY partition's lag reading
            # honest while this one fetches
            self._forecaster.observe(part, hw, offset)

    def _fetch_part(
        self, part: int, offset: int, max_wait_ms: Optional[int] = None
    ) -> List[Tuple[int, bytes]]:
        t0 = time.monotonic()
        try:
            # fault hooks INSIDE the try: an injected broker death rides
            # the same except → reconnect/backoff path a real one does,
            # and an injected slow fetch lands in the fetch histogram
            faults.fire("kafka_fetch")
            hw, record_set = self._client.fetch_raw(
                self._topic, part, offset,
                max_wait_ms=(
                    self._max_wait_ms if max_wait_ms is None else max_wait_ms
                ),
                max_bytes=self._max_bytes,
            )
        except KafkaPartitionError:
            raise  # misconfiguration: fail fast, don't reconnect-loop
        except (OSError, ConnectionError, KafkaProtocolError):
            self._reconnect()
            self._sweep_lag_age()
            return []
        self._backoff.reset()  # a successful fetch closes the streak
        self._note_event_times(part, record_set)
        self._observe_fetch(part, offset, hw, t0)
        return [
            rec
            for rec in decode_record_batches(record_set)
            if rec[0] >= offset
        ]

    def _fetch_raw_part(
        self, part: int, offset: int, max_wait_ms: Optional[int] = None
    ) -> bytes:
        t0 = time.monotonic()
        try:
            faults.fire("kafka_fetch")  # see _fetch_part
            hw, raw = self._client.fetch_raw(
                self._topic, part, offset,
                max_wait_ms=(
                    self._max_wait_ms if max_wait_ms is None else max_wait_ms
                ),
                max_bytes=self._max_bytes,
            )
        except KafkaPartitionError:
            raise  # misconfiguration: fail fast, don't reconnect-loop
        except (OSError, ConnectionError, KafkaProtocolError):
            self._reconnect()
            self._sweep_lag_age()
            return b""
        self._backoff.reset()
        self._note_event_times(part, raw)
        self._observe_fetch(part, offset, hw, t0)
        return raw

    def _note_decode_error(self, part, off: int, value: bytes, exc) -> None:
        """One undecodable record value: count it per partition, park
        the raw bytes in the DLQ (when installed), rate-limit one
        flight event — the caller skips the record and advances its
        cursor past it (never silently, never fatally)."""
        label = part if part is not None else "na"
        c = self._decode_err_counters.get(label)
        if c is None and self._metrics is not None:
            c = self._metrics.counter(f'decode_errors{{partition="{label}"}}')
            self._decode_err_counters[label] = c
        if c is not None:
            c.inc()
        # terminal journey hop + the envelope's trace context: the
        # quarantine is this record's journey exit, and the carried ids
        # are what fjt-dlq redrive stamps back into the topic header
        rctx = trace_mod.context_for(off)
        jstore = trace_mod.store_for(self._metrics)
        if jstore is not None:
            jstore.terminal(
                "decode_error", rctx, offset=int(off),
                partition=part if isinstance(part, int) else None,
            )
        now = time.monotonic()
        if now - self._last_decode_event >= 1.0:
            self._last_decode_event = now
            flight.record(
                "decode_error", topic=self._topic, partition=part,
                offset=off, size=len(value), error=repr(exc),
                trace_id=rctx.trace_id,
            )
        if self._dlq is not None:
            self._dlq.quarantine(
                value, offset=off, reason="decode",
                partition=part if isinstance(part, int) else None,
                error=exc, topic=self._topic,
                trace_id=rctx.trace_id, span_id=rctx.span_id,
            )

    def _sweep_lag_age(self) -> None:
        """A dead broker must not freeze ``kafka_lag_age_s`` at its last
        fresh-looking value: the poll loop keeps sweeping through the
        reconnect path even when every fetch fails, so the
        ``FJT_LAG_STALE_S`` crossing (and its ``kafka_lag_stale``
        flight event) still fires. Rate-limited inside sweep()."""
        if self._forecaster is not None:
            self._forecaster.sweep()

    def _note_event_times(self, part: int, record_set: bytes) -> None:
        """Advance the partition's event-time watermark from the fetched
        batches' header timestamps and remember the range for the poll
        path's ingest stamp (a header-only walk; skipped entirely when
        no registry is attached)."""
        self._note_traceparents(record_set)
        if self._freshness is None or not record_set:
            self._last_trange = None
            return
        tr = record_batch_time_range(record_set)
        self._last_trange = tr
        if tr is not None:
            self._freshness.observe_source(part, tr[0], tr[1])

    def _note_traceparents(self, record_set: bytes) -> None:
        """Stash the fetch's ``traceparent`` record headers for the
        poll path's journey ingest hop (record-journey tracing,
        obs/trace.py). Only walked when the journey plane is armed —
        the unarmed cost is the store_for gate; and only on
        single-partition sources, where record offsets ARE the global
        offset domain the journey fragments key on."""
        if self._multi or not record_set:
            return
        if trace_mod.store_for(self._metrics) is None:
            return
        tps = record_batch_traceparents(record_set)
        if tps:
            self._tps_pending.update(tps)
            while len(self._tps_pending) > 4096:
                # headers of records that were never polled out (a
                # seek away, a re-fetch overlap): oldest first
                self._tps_pending.pop(next(iter(self._tps_pending)))

    def _journey_ingest(self, first_off: int, n: int) -> None:
        """One fetched run's ingest hop (batch-keyed — per-record cost
        only for the rare header-carrying records, i.e. redrives).
        Consumes the emitted range's pending traceparents, however many
        fetches ago they arrived."""
        store = trace_mod.store_for(self._metrics)
        if store is None or n <= 0:
            return
        tps = None
        if self._tps_pending:
            hits = [
                off for off in self._tps_pending
                if first_off <= off < first_off + n
            ]
            if hits:
                tps = {off: self._tps_pending.pop(off) for off in hits}
        store.ingest(
            first_off, n,
            partition=self._partition if not self._multi else None,
            traceparents=tps,
        )

    _TRANGE_LAST = object()  # "use the last fetch's range" default

    def _stamp_ingest(
        self, first_off: int, n: int, trange=_TRANGE_LAST
    ) -> None:
        """Offset-keyed ingest stamp for the sink's staleness books
        (block sources only: record offsets there are the global domain
        the pipeline's sink commits in). ``trange`` overrides the last
        fetch's range for paths that buffer rows across fetches (the
        strict interleave merges per-slot ranges); an EXPLICIT ``None``
        means the emitted rows carried no event times at all — it must
        not fall back to another partition's fetch range, or unstamped
        rows would be booked with foreign event times."""
        if trange is self._TRANGE_LAST:
            trange = self._last_trange
        if self._freshness is not None and trange is not None:
            self._freshness.stamp_ingest(first_off, n, trange[0], trange[1])

    def _fetch(self) -> List[Tuple[int, bytes]]:
        """Single-partition fetch from the legacy Kafka-offset cursor."""
        recs = self._fetch_part(self._partition, self._next)
        if recs:
            self._next = recs[-1][0] + 1
        return recs

    def _pump(self, want: int) -> List[Tuple[int, bytes]]:
        """→ up to ``want`` (global_index, value) pairs in strict
        round-robin order across the configured partitions. Stops early
        when the next-in-turn partition has nothing fetchable yet (the
        interleave never skips ahead — that would break the bijection)."""
        P = len(self._parts)
        out: List[Tuple[int, bytes]] = []
        while len(out) < want:
            part = self._parts[self._g % P]
            po = self._g // P
            buf = self._bufs[part]
            while buf and buf[0][0] < po:
                buf.popleft()
            if not buf:
                recs = self._fetch_part(part, po)
                if not recs:
                    break
                buf.extend(recs)
                continue
            off, value = buf.popleft()
            if off != po:
                raise KafkaProtocolError(
                    f"partition {part} offset gap ({po} -> {off}) breaks "
                    "the round-robin interleave contract"
                )
            out.append((self._g, value))
            self._g += 1
        return out

    @property
    def _multi(self) -> bool:
        return len(self._parts) > 1

    @property
    def partitions(self) -> Tuple[int, ...]:
        """The partition set this source drains — the mesh ingest
        split (parallel/assignment.ChipAssignment) reads it to attach
        per-chip partition ownership."""
        return self._parts

    @property
    def _vector_mode(self) -> bool:
        return self._multi and not self._strict

    def _snap(self) -> None:
        """Record an emission-boundary cursor snapshot (vector mode)."""
        with self._snap_mu:
            self._snaps.append((self._g, dict(self._cursors)))
            # bound memory when nothing ever checkpoints by THINNING —
            # dropping intermediate boundaries only coarsens resume
            # granularity (more replay). The floor must NEVER advance
            # here: every retained-or-dropped entry has g > any
            # committed offset the score thread could have pruned to,
            # and a floor past committed would SKIP records on restore.
            if len(self._snaps) > 65536:
                self._snaps = collections.deque(
                    v for i, v in enumerate(self._snaps)
                    if i % 2 == 1
                )

    def checkpoint_state(self, committed: int) -> Optional[dict]:
        """Engine hook: JSON state for an exact multi-partition resume —
        the newest cursor-vector snapshot at or before ``committed``
        (None = the scalar offset fully encodes resume: single-partition
        or strict mode)."""
        if not self._vector_mode:
            return None
        with self._snap_mu:
            while self._snaps and self._snaps[0][0] <= committed:
                self._snap_floor = self._snaps.popleft()
            g, cursors = self._snap_floor
        return {
            "offset": g,
            "cursors": {str(p): off for p, off in cursors.items()},
        }

    def restore_state(self, state: dict) -> int:
        """Engine hook: resume from a checkpointed cursor vector →
        the effective committed offset (≤ what was requested when the
        commit landed mid-emission)."""
        if not self._vector_mode:
            # an auto-era checkpoint restored into a strict source:
            # the bijection would silently misread the arrival-order
            # global offset — refuse rather than mis-align lanes
            raise KafkaProtocolError(
                "checkpoint carries a per-partition cursor vector "
                "(written by interleave='auto') but this source is "
                "strict/single-partition; construct it with "
                "interleave='auto' to resume (migration notes: "
                "docs/migration.md, 'Kafka multi-partition interleave "
                "and checkpoint migration')"
            )
        cursors = {
            int(p): int(off) for p, off in state["cursors"].items()
        }
        if set(cursors) != set(self._parts):
            raise KafkaProtocolError(
                f"checkpoint cursors {sorted(cursors)} do not match the "
                f"configured partitions {sorted(self._parts)}"
            )
        g = int(state["offset"])
        with self._snap_mu:
            self._cursors = cursors
            self._g = g
            self._snaps.clear()
            self._snap_floor = (g, dict(cursors))
        self._clear_buffers()
        if self._freshness is not None:
            self._freshness.reset_stamps()
        if self._forecaster is not None:
            self._forecaster.reset()
        return g

    def _clear_buffers(self) -> None:
        for buf in self._bufs.values():
            buf.clear()
        # the offset domain is about to restart: pending traceparents
        # would mis-key against the new offsets (cf. reset_stamps)
        self._tps_pending.clear()

    def seek(self, offset: int) -> None:
        # engine offset k ("k records consumed") == next Kafka offset
        # (single-partition) / next global index (multi-strict): no +1
        # bridging anywhere (cf. net.py header)
        if self._vector_mode and offset != self._snap_floor[0]:
            raise KafkaProtocolError(
                f"vector-mode seek({offset}) without cursor state: "
                "multi-partition auto interleave resumes through "
                "restore_state (checkpointed per-partition offsets); "
                "arbitrary scalar seeks only exist in strict mode. "
                "Restoring a legacy scalar-only checkpoint (written by "
                "the pre-vector strict bijection)? Construct the "
                "source with interleave='strict' (migration notes: "
                "docs/migration.md, 'Kafka multi-partition interleave "
                "and checkpoint migration')."
            )
        self._next = offset
        self._g = offset
        self._clear_buffers()
        # the offset domain restarted (resume, or a cycling bench's
        # wrap-to-0): pending ingest stamps would mis-key against the
        # new offsets, and the forecaster's consume rate would read the
        # cursor jump as a giant negative delta
        if self._freshness is not None:
            self._freshness.reset_stamps()
        if self._forecaster is not None:
            self._forecaster.reset()

    def close(self) -> None:
        self._client.close()

    @property
    def exhausted(self) -> bool:
        return self._eos


class KafkaRecordSource(_KafkaSourceBase, Source):
    """Record-object source: each Kafka message value is one JSON record
    (or raw bytes via ``decoder``)."""

    # network source with real fetch latency: the pipelines wrap it in
    # a prefetch sidecar (runtime/prefetch.py) unless disabled
    prefetchable = True

    def __init__(self, *args, decoder=None, **kw):
        super().__init__(*args, **kw)
        import json

        self._decode = decoder or (lambda v: json.loads(v))
        self._pending: List[Tuple[int, bytes]] = []
        # vector mode: globally-indexed records buffered between polls
        self._pending_global: "collections.deque" = collections.deque()

    def _pump_auto(self, want: int) -> List[Tuple[int, bytes]]:
        """Vector-mode pump: runs from whichever partition has data
        (round-robin preference); cursors track observed offsets, gaps
        included; one snapshot per fetched run. Dry partitions are
        probed with ``max_wait_ms=0``; one long-poll only when the
        whole sweep is dry (cf. ``_poll_multi_auto``)."""
        out: List[Tuple[int, bytes]] = []
        P = len(self._parts)
        while len(out) < want:
            if self._pending_global:
                out.append(self._pending_global.popleft())
                continue
            fetched = False
            for attempt in (0, 1):
                for i in range(P):
                    idx = (self._rr + i) % P
                    part = self._parts[idx]
                    cur = self._cursors[part]
                    recs = [
                        (o, v)
                        for o, v in self._fetch_part(
                            part, cur,
                            max_wait_ms=0 if attempt == 0 else None,
                        )
                        if o >= cur
                    ]
                    if not recs:
                        if attempt:
                            break  # one long-poll per dry sweep
                        continue
                    g0 = self._g
                    self._pending_global.extend(
                        (g0 + j, v) for j, (_, v) in enumerate(recs)
                    )
                    self._g = g0 + len(recs)
                    self._cursors[part] = recs[-1][0] + 1
                    self._rr = (idx + 1) % P
                    self._snap()
                    fetched = True
                    break
                if fetched:
                    break
            if not fetched:
                break
        return out

    def _decode_polled(self, pairs, part) -> Polled:
        """(offset, value) pairs → (offset+1, record), quarantining +
        skipping values the decoder rejects (counted per partition,
        raw bytes to the DLQ when installed). With neither metrics nor
        a DLQ the historical raise stands — an invisible skip would be
        silent data loss."""
        out = []
        for off, value in pairs:
            try:
                rec = self._decode(value)
            except Exception as e:
                if self._dlq is None and self._metrics is None:
                    raise
                self._note_decode_error(part, off, value, e)
                continue
            out.append((off + 1, rec))
        if pairs:
            # record-path ingest hop, in the RECORD-offset domain the
            # engine's journeys key on (stamp − 1; see _record_off)
            self._journey_ingest(int(pairs[0][0]), len(pairs))
        return out

    def poll(self, max_n: int) -> Polled:
        if self._vector_mode:
            return self._decode_polled(self._pump_auto(max_n), None)
        if self._multi:
            return self._decode_polled(self._pump(max_n), None)
        # a fetch may return more than max_n records; the surplus stays
        # buffered so nothing fetched is ever dropped (the fetch cursor
        # has already moved past it)
        if len(self._pending) < max_n:
            self._pending.extend(self._fetch())
        take, self._pending = (
            self._pending[:max_n],
            self._pending[max_n:],
        )
        return self._decode_polled(take, self._partition)

    def _clear_buffers(self) -> None:
        self._pending.clear()
        self._pending_global.clear()
        super()._clear_buffers()

    def seek(self, offset: int) -> None:
        self._pending.clear()
        super().seek(offset)


class KafkaBlockSource(_KafkaSourceBase, BlockSource):
    """Block source: each Kafka message value is one packed f32-LE feature
    row; a fetch's worth of consecutive rows forms one [n, F] block.
    Single- and multi-partition polls both ride the C++ record-batch
    decoder; the multi-partition interleave is array-strided, not
    per-record.

    ``metrics`` (optional, a ``MetricsRegistry``) accounts wire-decode
    time into a ``kafka_decode_s`` counter — the consumer-thread half
    of the stream's host budget, reported next to the score loop's
    ``encode_s`` so the bench's ``kafka_mode`` can say where consumer
    CPU goes (``decode_ms``) — plus the base class's fetch-latency
    histogram and per-partition ``kafka_lag`` gauges."""

    # network source with real fetch latency: the pipelines wrap it in
    # a prefetch sidecar (runtime/prefetch.py) unless disabled
    prefetchable = True

    def __init__(self, *args, n_cols: int, metrics=None, **kw):
        super().__init__(*args, metrics=metrics, **kw)
        self._cols = n_cols
        self._decode_s = (
            metrics.counter("kafka_decode_s") if metrics is not None else None
        )
        # per-slot decoded row buffers: slot → [rows...] contiguous from
        # that slot's next needed partition offset (multi-partition only)
        self._rbufs: Dict[int, np.ndarray] = {}
        # slot → (min_ts, max_ts) of the fetches its buffered rows came
        # from — batch granularity, so the emitted interleave's ingest
        # stamp stays an upper bound on staleness
        self._rbuf_tranges: Dict[int, tuple] = {}

    def _decode_rows(self, raw: bytes, part):
        """→ (offsets int64, rows f32, bad_hi): the decoded fixed-width
        rows plus the highest offset of any record whose VALUE was the
        wrong length (None when all decoded). Bad records are counted
        (``decode_errors{partition=*}``) and routed to the DLQ when one
        is installed; the callers advance their cursors past ``bad_hi``
        so a poisoned producer message is consumed exactly once, not
        refetched forever. With neither metrics nor DLQ attached the
        historical ValueError propagates (a skip nobody can see would
        be silent data loss); the strict interleave also re-raises —
        its round-robin bijection cannot tolerate a dropped lane."""
        t0 = time.monotonic() if self._decode_s is not None else None
        try:
            try:
                offs, rows = decode_record_batches_rows(raw, self._cols)
                return offs, rows, None
            except ValueError:
                if self._strict and self._multi:
                    raise
                if self._dlq is None and self._metrics is None:
                    raise
                return self._decode_rows_lenient(raw, part)
        finally:
            if t0 is not None:
                dt = time.monotonic() - t0
                self._decode_s.inc(dt)
                if self._ledger is not None:
                    self._ledger.observe("decode", dt)

    def _decode_rows_lenient(self, raw: bytes, part):
        """Per-record decode isolating wrong-length values (CRC and
        framing errors re-raise from ``decode_record_batches`` — a
        corrupt record SET is transport damage, not a poison value)."""
        recs = decode_record_batches(raw)
        want = 4 * self._cols
        offs: List[int] = []
        rows: List[np.ndarray] = []
        bad_hi = None
        for off, value in recs:
            if len(value) == want:
                offs.append(off)
                rows.append(np.frombuffer(value, np.float32))
            else:
                self._note_decode_error(
                    part, off, value,
                    ValueError(
                        f"value length {len(value)} != {want} "
                        f"(n_cols={self._cols})"
                    ),
                )
                bad_hi = off if bad_hi is None else max(bad_hi, off)
        if not offs:
            return (
                np.empty((0,), np.int64),
                np.empty((0, self._cols), np.float32),
                bad_hi,
            )
        return np.asarray(offs, np.int64), np.vstack(rows), bad_hi

    def _poll_multi(self) -> Optional[Tuple[int, np.ndarray]]:
        """Strict round-robin interleave, vectorized: global index
        g ↦ (slot g % P, partition offset g // P). Each slot keeps a
        contiguous decoded-row buffer; emission takes min-available full
        strides and interleaves with P slice-assigns."""
        P = len(self._parts)
        g0 = self._g
        limits = []
        for s, part in enumerate(self._parts):
            off_s = (s - g0) % P  # first emission index landing on slot s
            po0 = (g0 + off_s) // P  # that record's partition offset
            buf = self._rbufs.get(s)
            if buf is None or buf.shape[0] == 0:
                raw = self._fetch_raw_part(part, po0)
                if raw:
                    offs, rows, _ = self._decode_rows(raw, part)
                    k = int(np.searchsorted(offs, po0))
                    offs, rows = offs[k:], rows[k:]
                    if offs.shape[0]:
                        if offs[0] != po0 or (np.diff(offs) != 1).any():
                            raise KafkaProtocolError(
                                f"partition {part} offset gap at {po0} "
                                "breaks the round-robin interleave contract"
                            )
                        buf = rows
                        self._rbufs[s] = buf
                        if self._last_trange is not None:
                            self._rbuf_tranges[s] = self._last_trange
                        else:
                            self._rbuf_tranges.pop(s, None)
            avail = 0 if buf is None else buf.shape[0]
            limits.append(off_s + avail * P)
        m = min(limits)
        if m <= 0:
            return None
        out = np.empty((m, self._cols), np.float32)
        trange = None
        for s in range(P):
            off_s = (s - g0) % P
            c = len(range(off_s, m, P))
            if c:
                buf = self._rbufs[s]
                out[off_s:m:P] = buf[:c]
                self._rbufs[s] = buf[c:]
                tr = self._rbuf_tranges.get(s)
                if tr is not None:
                    trange = tr if trange is None else (
                        min(trange[0], tr[0]), max(trange[1], tr[1])
                    )
        self._g = g0 + m
        # the interleaved run spans every consumed slot's fetch range
        self._stamp_ingest(g0, m, trange=trange)
        self._journey_ingest(g0, m)
        return g0, out

    def _poll_multi_auto(self) -> Optional[Tuple[int, np.ndarray]]:
        """Vector-mode poll: take the next available run from whichever
        partition has data (round-robin preference, never stalling on an
        empty one). Cursors advance to the offsets actually observed —
        offset gaps (compaction) are data, not errors — and every
        emission appends a cursor-vector snapshot for checkpointing.

        Empty partitions are probed with ``max_wait_ms=0`` — a serial
        sweep must not pay the broker's long-poll per dry partition
        (with one hot partition of P, that would cap the poll rate at
        ~1/((P-1)·max_wait) regardless of throughput); only when the
        WHOLE sweep is dry does one bounded long-poll keep the idle-
        stream blocking semantics."""
        P = len(self._parts)
        for attempt in (0, 1):
            for i in range(P):
                idx = (self._rr + i) % P
                part = self._parts[idx]
                raw = self._fetch_raw_part(
                    part,
                    self._cursors[part],
                    max_wait_ms=0 if attempt == 0 else None,
                )
                if not raw:
                    if attempt:
                        break  # one long-poll per dry sweep, not P
                    continue
                offs, rows, bad_hi = self._decode_rows(raw, part)
                k = int(np.searchsorted(offs, self._cursors[part]))
                offs, rows = offs[k:], rows[k:]
                if offs.shape[0] == 0:
                    if (
                        bad_hi is not None
                        and bad_hi >= self._cursors[part]
                    ):
                        # an all-poison fetch: advance past it, or the
                        # next poll refetches and re-quarantines forever
                        self._cursors[part] = bad_hi + 1
                        self._snap()
                    if attempt:
                        break
                    continue
                g0 = self._g
                self._g = g0 + rows.shape[0]
                self._cursors[part] = int(offs[-1]) + 1
                if bad_hi is not None:
                    # trailing poison records consumed by this fetch:
                    # the cursor moves past them exactly once
                    self._cursors[part] = max(
                        self._cursors[part], bad_hi + 1
                    )
                self._rr = (idx + 1) % P
                self._snap()
                # one fetch == one emitted run here, so the fetch's
                # event-time range stamps these global offsets exactly
                self._stamp_ingest(g0, rows.shape[0])
                self._journey_ingest(g0, rows.shape[0])
                return g0, rows
        return None

    def _clear_buffers(self) -> None:
        self._rbufs.clear()
        self._rbuf_tranges.clear()
        super()._clear_buffers()

    def seek(self, offset: int) -> None:
        self._rbufs.clear()
        self._rbuf_tranges.clear()
        super().seek(offset)

    def poll(self) -> Optional[Tuple[int, np.ndarray]]:
        if self._vector_mode:
            return self._poll_multi_auto()
        if self._multi:
            return self._poll_multi()
        raw = self._fetch_raw_part(self._partition, self._next)
        if not raw:
            return None
        offs, rows, bad_hi = self._decode_rows(raw, self._partition)
        # a fetch returns whole batches: drop records below the cursor
        k = int(np.searchsorted(offs, self._next))
        offs, rows = offs[k:], rows[k:]
        if offs.shape[0] == 0:
            if bad_hi is not None and bad_hi >= self._next:
                # an all-poison fetch: advance past it, or the next
                # poll refetches and re-quarantines forever
                self._next = bad_hi + 1
            return None
        first = int(offs[0])
        gaps = np.nonzero(np.diff(offs) != 1)[0]
        if gaps.size:
            # a gap means a compacted/partial topic (or a quarantined
            # poison value) — not the tabular stream contract; resync
            # the block at the gap
            stop = int(gaps[0]) + 1
            self._next = int(offs[stop])
            rows = rows[:stop]
        else:
            self._next = int(offs[-1]) + 1
            if bad_hi is not None:
                # trailing poison records: consumed exactly once
                self._next = max(self._next, bad_hi + 1)
        # the fetch's batch-header time range bounds these rows' event
        # times (batch granularity: the cursor filter above may narrow
        # the rows, never widen them — staleness stays an upper bound)
        self._stamp_ingest(first, rows.shape[0])
        self._journey_ingest(first, rows.shape[0])
        return first, rows


def chip_block_sources(
    assignment,
    host: str,
    port: int,
    topic: str,
    *,
    n_cols: int,
    metrics=None,
    **kw,
) -> dict:
    """One :class:`KafkaBlockSource` per mesh chip, each draining
    exactly the partitions the rendezvous assignment
    (parallel/assignment.ChipAssignment) owns it — the mesh ingest
    split: each chip's pipeline fetches only its own partitions, so
    ingest bandwidth scales with the data width instead of funneling
    every partition through one consumer. Chips owning no partition
    are omitted (fewer partitions than chips). Ownership is key-stable:
    after a degraded-mesh resize only the dead chip's partitions
    re-home (``assignment.without``), so the surviving chips' sources —
    and their per-partition checkpoint cursors — remain valid as-is.

    → ``{chip: KafkaBlockSource}``; extra kwargs pass through to the
    source (``dlq=``, ``interleave=``, ...)."""
    sources = {}
    for chip in assignment.chips:
        parts = assignment.partitions_for(chip)
        if not parts:
            continue
        sources[chip] = KafkaBlockSource(
            host, port, topic,
            partitions=list(parts),
            n_cols=n_cols, metrics=metrics, **kw,
        )
    return sources


# ---------------------------------------------------------------------------
# MiniKafkaBroker (tests / drills)
# ---------------------------------------------------------------------------


class MiniKafkaBroker:
    """In-process single-topic single-partition broker speaking the same
    wire protocol the client consumes: ApiVersions v0, Metadata v1,
    ListOffsets v1, Fetch v0–v4, Produce ignored. The FJT1-server role
    (runtime/net.py BlockFrameServer), but Kafka-framed — tests and
    kill/resume drills run against real protocol bytes."""

    def __init__(self, topic: str = "records", host: str = "127.0.0.1",
                 port: int = 0, n_partitions: int = 1):
        self.topic = topic
        self.n_partitions = n_partitions
        # per-partition parallel (offsets, values) lists — offsets are
        # explicit (not list indices) so a compacted log can hold real
        # gaps, like a real broker's; _next[p] = next offset to assign
        self._offs: List[List[int]] = [[] for _ in range(n_partitions)]
        self._vals: List[List[bytes]] = [[] for _ in range(n_partitions)]
        # per-record header lists (None = no headers): a real broker
        # stores headers with the record, so a redriven traceparent
        # must survive produce→fetch here too
        self._hdrs: List[List[Optional[list]]] = [
            [] for _ in range(n_partitions)
        ]
        self._next: List[int] = [0] * n_partitions
        # per-partition encoded segments (base_offset, end_offset, batch
        # bytes): like a real broker's log, the wire format is the
        # storage format — appends encode once, fetches serve cached
        # bytes (the round-4 rework; re-encoding per fetch made the test
        # broker the loopback bottleneck at ~45k rec/s while the
        # consumer decodes at 2.3M)
        self._segs: List[List[Tuple[int, int, bytes]]] = [
            [] for _ in range(n_partitions)
        ]
        self._mu = threading.Condition()
        self._srv = socket.create_server((host, port))
        self.host, self.port = self._srv.getsockname()[:2]
        self._closing = False
        self._threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._conns_mu = threading.Lock()
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    # -- producer side (in-process) --------------------------------------

    _SEG_RECORDS = 512  # records per stored batch segment

    def append(self, *values: bytes, partition: int = 0,
               timestamp_ms: Optional[int] = None,
               headers: Optional[Sequence] = None) -> int:
        """→ offset of the first appended value (in ``partition``).
        ``timestamp_ms`` stamps the batch headers (CreateTime) — the
        event time the freshness plane's watermarks read; the default
        0 means "no event time" (consumers skip it). ``headers`` is a
        per-value list (aligned; each entry None or
        ``[(key, value_bytes), ...]``) stored with the records like a
        real broker stores record headers."""
        ts = 0 if timestamp_ms is None else int(timestamp_ms)
        hdr_list = (
            list(headers) if headers is not None
            else [None] * len(values)
        )
        if len(hdr_list) != len(values):
            raise ValueError(
                f"{len(hdr_list)} header lists for {len(values)} values"
            )
        with self._mu:
            first = self._next[partition]
            self._offs[partition].extend(
                range(first, first + len(values))
            )
            self._vals[partition].extend(values)
            self._hdrs[partition].extend(hdr_list)
            self._next[partition] = first + len(values)
            segs = self._segs[partition]
            for i in range(0, len(values), self._SEG_RECORDS):
                chunk = values[i : i + self._SEG_RECORDS]
                segs.append((
                    first + i,
                    first + i + len(chunk),
                    encode_record_batch(
                        first + i, list(chunk), timestamp_ms=ts,
                        headers=hdr_list[i : i + len(chunk)],
                    ),
                ))
            self._mu.notify_all()
            return first

    def append_rows(self, rows: np.ndarray, partition: int = 0,
                    timestamp_ms: Optional[int] = None) -> int:
        """Fixed-width producer fast path: segments encode through the
        C++ batch encoder when available (byte-identical output), so a
        million-row log appends in tenths of a second instead of tens.
        ``timestamp_ms`` stamps the batch headers with an event time —
        the native encoder writes timestamp 0, so a stamped append
        takes the Python encoder (the load generators that stamp append
        in paced chunks, where the Python path keeps up)."""
        from flink_jpmml_tpu.runtime import native

        rows = np.ascontiguousarray(rows, np.float32)
        if rows.shape[0] == 0:  # round-robin slices can be empty
            with self._mu:
                return self._next[partition]
        raw = rows.view(np.uint8).reshape(rows.shape[0], -1)
        with self._mu:
            first = self._next[partition]
            segs = self._segs[partition]
            for i in range(0, rows.shape[0], self._SEG_RECORDS):
                chunk = raw[i : i + self._SEG_RECORDS]
                base = first + i
                blob = (
                    native.kafka_encode_fixed(chunk, base)
                    if timestamp_ms is None else None
                )
                if blob is None:
                    blob = encode_record_batch(
                        base,
                        [chunk[j].tobytes() for j in range(chunk.shape[0])],
                        timestamp_ms=int(timestamp_ms or 0),
                    )
                segs.append((base, base + chunk.shape[0], blob))
            self._offs[partition].extend(
                range(first, first + rows.shape[0])
            )
            self._vals[partition].extend(
                raw[i].tobytes() for i in range(raw.shape[0])
            )
            self._hdrs[partition].extend([None] * rows.shape[0])
            self._next[partition] = first + rows.shape[0]
            self._mu.notify_all()
            return first

    def append_rows_round_robin(
        self, rows: np.ndarray, timestamp_ms: Optional[int] = None
    ) -> None:
        """Row i → partition i % n_partitions (the producer layout the
        multi-partition sources' strict interleave consumes). Chunked
        producers must pass chunks whose length divides by n_partitions,
        or the round-robin phase restarts mid-stream."""
        rows = np.ascontiguousarray(rows, np.float32)
        for p in range(self.n_partitions):
            self.append_rows(
                rows[p :: self.n_partitions], partition=p,
                timestamp_ms=timestamp_ms,
            )

    def append_rows_keyed(self, rows: np.ndarray, keys) -> None:
        """Keyed producer: row i → partition ``hash(keys[i]) %
        n_partitions`` — the layout real keyed producers create, where
        partitions fill unevenly and NO round-robin bijection exists.
        The vector-offset consumer mode exists for exactly this."""
        import zlib

        rows = np.ascontiguousarray(rows, np.float32)
        if len(keys) != rows.shape[0]:
            raise ValueError(
                f"{len(keys)} keys for {rows.shape[0]} rows"
            )
        parts = np.asarray([
            zlib.crc32(str(k).encode()) % self.n_partitions for k in keys
        ])
        for p in range(self.n_partitions):
            self.append_rows(rows[parts == p], partition=p)

    def compact(self, partition: int, remove_offsets) -> None:
        """Log compaction: drop the given offsets from the partition,
        leaving REAL gaps (surviving records keep their original
        offsets, exactly like Kafka compaction). Segments are rebuilt
        as contiguous surviving runs — a drill operation; efficiency is
        irrelevant next to correctness here."""
        remove = set(int(o) for o in remove_offsets)
        with self._mu:
            offs = self._offs[partition]
            vals = self._vals[partition]
            hdrs = self._hdrs[partition]
            keep = [
                (o, v, h) for o, v, h in zip(offs, vals, hdrs)
                if o not in remove
            ]
            self._offs[partition] = [o for o, _, _ in keep]
            self._vals[partition] = [v for _, v, _ in keep]
            self._hdrs[partition] = [h for _, _, h in keep]
            segs: List[Tuple[int, int, bytes]] = []
            run: List[Tuple[int, bytes, Optional[list]]] = []
            for o, v, h in keep:
                if run and o != run[-1][0] + 1:
                    segs.append(self._encode_run(run))
                    run = []
                run.append((o, v, h))
                if len(run) >= self._SEG_RECORDS:
                    segs.append(self._encode_run(run))
                    run = []
            if run:
                segs.append(self._encode_run(run))
            self._segs[partition] = segs
            self._mu.notify_all()

    @staticmethod
    def _encode_run(run) -> Tuple[int, int, bytes]:
        base = run[0][0]
        return (
            base,
            run[-1][0] + 1,
            encode_record_batch(
                base,
                [v for _, v, _ in run],
                headers=[h for _, _, h in run],
            ),
        )

    @property
    def high_watermark(self) -> int:
        """Total records across ALL partitions — so produced-vs-consumed
        waits stay correct on a multi-partition broker (per-partition
        watermarks ride the Fetch/ListOffsets responses)."""
        with self._mu:
            return sum(len(v) for v in self._vals)

    def close(self) -> None:
        self._closing = True
        # unblock a parked accept() BEFORE closing the listener: on
        # Linux, close() does not interrupt a thread blocked in
        # accept(), and the in-flight syscall keeps the kernel LISTEN
        # entry alive — a same-port restart then fails EADDRINUSE until
        # some client happens to connect (the serial consumers always
        # did, by reconnecting; a prefetch sidecar sitting in backoff
        # does not). One self-connect completes the accept so the loop
        # observes _closing and releases the last reference.
        try:
            poke = socket.create_connection(
                (self.host, self.port), timeout=0.5
            )
            poke.close()
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        # close accepted connections too: a serve thread parked in recv
        # would otherwise hold the port in ESTABLISHED/CLOSE_WAIT and
        # make an immediate same-port restart fail with EADDRINUSE
        # (SO_REUSEADDR only forgives TIME_WAIT)
        with self._conns_mu:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        with self._mu:
            self._mu.notify_all()

    # -- server side ------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            # register BEFORE spawning, and re-check _closing after: a
            # close() racing this accept must still find (or beat) the
            # connection in _conns so no socket outlives the broker
            with self._conns_mu:
                self._conns.append(conn)
            if self._closing:
                try:
                    conn.close()
                except OSError:
                    pass
                return
            t = threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while not self._closing:
                hdr = self._recv_exact(conn, 4)
                if hdr is None:
                    return
                (size,) = _I32.unpack(hdr)
                payload = self._recv_exact(conn, size)
                if payload is None:
                    return
                r = _Reader(payload)
                api_key = r.i16()
                api_version = r.i16()
                corr = r.i32()
                r.string()  # client id
                body = self._dispatch(api_key, api_version, r)
                if body is None:
                    return
                msg = _I32.pack(corr) + body
                conn.sendall(_I32.pack(len(msg)) + msg)
        except (OSError, ConnectionError):
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass
            # drop the registry entry: a long-lived broker must not
            # accumulate closed sockets across normal disconnects
            with self._conns_mu:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
        from flink_jpmml_tpu.utils.netio import recv_exact

        return recv_exact(conn, n)

    def _dispatch(self, api_key: int, v: int, r: _Reader) -> Optional[bytes]:
        if api_key == API_VERSIONS:
            w = _Writer()
            w.i16(0).i32(5)
            # Advertise exactly the versions _dispatch answers in: the
            # Fetch/ListOffsets/Metadata responses below are fixed v4/v1/v1
            # shapes, so offering lower versions would let a client pick one
            # and mis-parse the reply.
            for k, lo, hi in (
                (API_FETCH, 4, 4),
                (API_LIST_OFFSETS, 1, 1),
                (API_METADATA, 1, 1),
                (API_VERSIONS, 0, 0),
                (API_PRODUCE, 3, 3),
            ):
                w.i16(k).i16(lo).i16(hi)
            return bytes(w.b)
        if api_key == API_PRODUCE and v == 3:
            # the redrive path (fjt-dlq → KafkaClient.produce): decode
            # the record batch, append its values like an in-process
            # append() — offsets are reassigned at the log head, exactly
            # like a real broker
            r.string()  # transactional id
            r.i16()  # acks
            r.i32()  # timeout
            r.i32()  # topic count (1)
            r.string()
            r.i32()  # partition count (1)
            part = r.i32()
            record_set = r.bytes_() or b""
            ok_part = 0 <= part < len(self._offs)
            base = -1
            err = 0 if ok_part else 3
            if ok_part:
                try:
                    # the header-aware decode: a redriven traceparent
                    # must survive the produce→append→fetch round trip
                    recs = decode_record_batches_h(record_set)
                    tr = record_batch_time_range(record_set)
                except ValueError:
                    recs, tr, err = [], None, 42  # INVALID_RECORD
                if recs:
                    base = self.append(
                        *[val for _, val, _ in recs], partition=part,
                        timestamp_ms=(
                            int(tr[1] * 1000) if tr is not None else None
                        ),
                        headers=[h for _, _, h in recs],
                    )
            w = _Writer()
            w.i32(1).string(self.topic)
            w.i32(1).i32(part).i16(err).i64(base).i64(-1)
            w.i32(0)  # throttle time (trails the responses in v1+)
            return bytes(w.b)
        if api_key == API_METADATA:
            for _ in range(max(r.i32(), 0)):
                r.string()
            w = _Writer()
            w.i32(1)  # brokers
            w.i32(0).string(self.host).i32(self.port).string(None)
            w.i32(0)  # controller id
            w.i32(1)  # topics
            w.i16(0).string(self.topic).i8(0)
            w.i32(self.n_partitions)
            for idx in range(self.n_partitions):
                w.i16(0).i32(idx).i32(0)  # err, index, leader
                w.i32(1).i32(0)  # replicas
                w.i32(1).i32(0)  # isr
            return bytes(w.b)
        if api_key == API_LIST_OFFSETS:
            r.i32()  # replica id
            r.i32()  # topic count (1)
            r.string()
            r.i32()  # partition count (1)
            part = r.i32()
            ts = r.i64()
            with self._mu:
                ok_part = 0 <= part < len(self._offs)
                if ts == -2:  # earliest surviving offset
                    offs = self._offs[part] if ok_part else []
                    off = offs[0] if offs else (
                        self._next[part] if ok_part else 0
                    )
                else:  # latest = next offset to be assigned
                    off = self._next[part] if ok_part else 0
            w = _Writer()
            w.i32(1).string(self.topic)
            # err 3 = UNKNOWN_TOPIC_OR_PARTITION: a misconfigured
            # consumer must fail fast, not poll an empty phantom log
            w.i32(1).i32(part).i16(0 if ok_part else 3).i64(-1).i64(off)
            return bytes(w.b)
        if api_key == API_FETCH:
            r.i32()  # replica id
            max_wait_ms = r.i32()
            r.i32()  # min bytes
            if v >= 3:
                r.i32()  # max bytes
            if v >= 4:
                r.i8()  # isolation level
            r.i32()  # topic count
            r.string()
            r.i32()  # partition count
            part = r.i32()
            fetch_offset = r.i64()
            part_max_bytes = r.i32()
            deadline = time.monotonic() + max_wait_ms / 1000.0
            with self._mu:
                ok_part = 0 <= part < len(self._offs)
                segs = self._segs[part] if ok_part else []
                while (
                    ok_part
                    and self._next[part] <= fetch_offset
                    and not self._closing
                    and time.monotonic() < deadline
                ):
                    self._mu.wait(
                        max(deadline - time.monotonic(), 0.001)
                    )
                hw = self._next[part] if ok_part else 0
                parts: List[bytes] = []
                if fetch_offset < hw:
                    # serve the cached encoded segments (a real broker's
                    # fetch is sendfile over stored batches); whole
                    # batches may start before fetch_offset — consumers
                    # filter. At least one segment always ships so the
                    # fetch makes progress; an oversized head segment
                    # falls back to a bounded re-encode.
                    import bisect

                    j = bisect.bisect_right(
                        segs, fetch_offset, key=lambda s: s[0]
                    ) - 1
                    if j < 0:
                        j = 0
                    while (
                        j < len(segs) and segs[j][1] <= fetch_offset
                    ):
                        j += 1
                    size = 0
                    while j < len(segs):
                        _, _, blob = segs[j]
                        if parts and size + len(blob) > part_max_bytes:
                            break
                        if not parts and len(blob) > part_max_bytes:
                            offs_l = self._offs[part]
                            k = bisect.bisect_left(offs_l, fetch_offset)
                            values = []
                            hdrs_l = []
                            size2 = 0
                            base = None
                            while k < len(offs_l):
                                o, val = offs_l[k], self._vals[part][k]
                                if base is None:
                                    base = o
                                elif o != base + len(values):
                                    break  # re-encode one contiguous run
                                size2 += len(val) + 32
                                if values and size2 > part_max_bytes:
                                    break
                                values.append(val)
                                hdrs_l.append(self._hdrs[part][k])
                                k += 1
                            parts = [
                                encode_record_batch(
                                    base, values, headers=hdrs_l
                                )
                            ] if values else []
                            break
                        parts.append(blob)
                        size += len(blob)
                        j += 1
            record_set = b"".join(parts)
            w = _Writer()
            w.i32(0)  # throttle
            w.i32(1).string(self.topic)
            w.i32(1)
            # err 3 = UNKNOWN_TOPIC_OR_PARTITION for an out-of-range
            # partition index (a real broker fails the fetch; an empty
            # err-0 log would mask the misconfiguration forever)
            w.i32(part).i16(0 if ok_part else 3).i64(hw)
            w.i64(hw)  # last stable offset
            w.i32(0)  # aborted txns
            w.bytes_(record_set)
            return bytes(w.b)
        # unknown api: close the connection (real brokers error; fine here)
        return None
