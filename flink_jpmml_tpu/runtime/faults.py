"""Deterministic fault injection: overload and recovery, drilled.

PRs 3-7 built the telemetry that *reports* a dying broker, a wedged
worker, or a failed checkpoint write — but every one of those paths was
only ever exercised by whatever chaos a test could improvise (monkey-
patched sockets, killed subprocesses). This harness injects the faults
into the REAL code paths, deterministically, so the overload drill and
the recovery tests run the same failure the same way every time:

=================  =========================================  ===========================
kind               fires in (site)                            effect
=================  =========================================  ===========================
``broker_death``   kafka fetch RPC (``runtime/kafka.py``)     raises ``ConnectionError`` →
                                                              the real reconnect/backoff path
``slow_fetch``     kafka fetch RPC                            sleeps ``delay_ms``
``dispatch_delay`` device dispatch                            sleeps ``delay_ms`` before the
                   (``OverlappedDispatcher.launch``)          dispatch is issued
``checkpoint_fail`` checkpoint write                          raises ``OSError`` mid-write →
                   (``CheckpointManager.save``)               the retry/backoff path
``worker_wedge``   the block score loop                       sleeps ``wedge_s`` per fire —
                                                              the heartbeat-wedge shape
=================  =========================================  ===========================

Two front doors:

- **env** — ``FJT_FAULTS`` holds comma-separated specs, each a kind
  followed by ``:key=value`` params::

      FJT_FAULTS="slow_fetch:delay_ms=40:p=0.5,broker_death:after_s=5:for_s=2"

  parsed once at import (and re-parseable via :func:`install_from_env`);
  a malformed spec is skipped loudly (stderr), never fatal.
- **programmatic** — :func:`inject`/:func:`clear` for tests and drills.

Gate params (all optional): ``after_s`` (arm delay from install),
``for_s`` (active window after arming), ``n`` (max fires), ``p``
(per-call probability from a seeded RNG — ``seed`` makes it
deterministic), ``delay_ms`` / ``wedge_s`` (the action magnitudes).

Every fire records a rate-limited ``fault_injected`` flight event (≥1 s
apart per fault — the flight ring is for rare events; exact counts live
in :func:`stats`).

**Zero-overhead contract**: with no faults configured, ``fire(site)``
is one global load and a None check — pinned by the perf-smoke
tripwire. Hook sites sit on per-fetch / per-batch paths, never
per-record.
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time
from typing import Dict, List, Optional

from flink_jpmml_tpu.obs import recorder as flight

_ENV = "FJT_FAULTS"
_EVENT_MIN_PERIOD_S = 1.0

# the sites the runtime actually hooks; a kind IS its site mapping
SITES = {
    "broker_death": "kafka_fetch",
    "slow_fetch": "kafka_fetch",
    "dispatch_delay": "dispatch",
    "checkpoint_fail": "checkpoint_write",
    "worker_wedge": "score_loop",
}


class InjectedBrokerDeath(ConnectionError):
    """Injected broker death: rides the kafka sources' real
    ``except (OSError, ConnectionError, ...)`` → reconnect path."""


class InjectedCheckpointFailure(OSError):
    """Injected checkpoint write failure: rides ``CheckpointManager
    .save``'s real ``except OSError`` → retry/backoff path."""


class _Fault:
    """One configured fault: its gates (arm delay, active window, count
    cap, probability) and its action."""

    def __init__(self, kind: str, params: Dict[str, float],
                 clock=time.monotonic):
        if kind not in SITES:
            raise ValueError(
                f"unknown fault kind {kind!r} (have {sorted(SITES)})"
            )
        self.kind = kind
        self.site = SITES[kind]
        self._clock = clock
        self._t0 = clock()
        self.after_s = float(params.get("after_s", 0.0))
        self.for_s = params.get("for_s")
        self.max_fires = (
            int(params["n"]) if params.get("n") is not None else None
        )
        self.p = params.get("p")
        self.delay_s = float(params.get("delay_ms", 50.0)) / 1000.0
        self.wedge_s = float(params.get("wedge_s", 0.5))
        # seeded by default: the SAME drill injects the SAME faults —
        # determinism is the point of a harness over improvised chaos
        self._rng = random.Random(int(params.get("seed", 0xFA17)))
        self.fires = 0
        self._last_event = 0.0
        self._mu = threading.Lock()

    def try_claim(self) -> bool:
        """Evaluate the gates; claim one fire when they all pass."""
        now = self._clock()
        armed_at = self._t0 + self.after_s
        if now < armed_at:
            return False
        if self.for_s is not None and now > armed_at + float(self.for_s):
            return False
        with self._mu:
            if self.max_fires is not None and self.fires >= self.max_fires:
                return False
            if self.p is not None and self._rng.random() >= float(self.p):
                return False
            self.fires += 1
            event_due = now - self._last_event >= _EVENT_MIN_PERIOD_S
            if event_due:
                self._last_event = now
        if event_due:
            flight.record(
                "fault_injected", fault=self.kind, site=self.site,
                fires=self.fires,
            )
        return True

    def act(self) -> None:
        if self.kind == "broker_death":
            raise InjectedBrokerDeath("injected broker death")
        if self.kind == "checkpoint_fail":
            raise InjectedCheckpointFailure(
                "injected checkpoint write failure"
            )
        if self.kind == "worker_wedge":
            time.sleep(self.wedge_s)
        else:  # slow_fetch / dispatch_delay
            time.sleep(self.delay_s)


class FaultPlan:
    def __init__(self, faults: List[_Fault]):
        self.faults = faults
        self._by_site: Dict[str, List[_Fault]] = {}
        for f in faults:
            self._by_site.setdefault(f.site, []).append(f)

    def fire(self, site: str) -> None:
        for f in self._by_site.get(site, ()):
            if f.try_claim():
                f.act()


# None = no faults configured: fire() is a global load + None check
_ACTIVE: Optional[FaultPlan] = None


def fire(site: str) -> None:
    """The hook the runtime calls at each injection site. A raised
    fault propagates to the caller's real error-handling path."""
    plan = _ACTIVE
    if plan is None:
        return
    plan.fire(site)


def active() -> bool:
    return _ACTIVE is not None


def inject(kind: str, **params) -> _Fault:
    """Programmatically add one fault (tests/drills). → the fault, so
    the caller can read ``fires``."""
    global _ACTIVE
    f = _Fault(kind, params)
    faults = list(_ACTIVE.faults) if _ACTIVE is not None else []
    faults.append(f)
    _ACTIVE = FaultPlan(faults)
    return f


def clear() -> None:
    """Drop every configured fault (idempotent)."""
    global _ACTIVE
    _ACTIVE = None


def stats() -> Dict[str, int]:
    """→ {kind: fires} for every configured fault (summed per kind)."""
    plan = _ACTIVE
    out: Dict[str, int] = {}
    if plan is not None:
        for f in plan.faults:
            out[f.kind] = out.get(f.kind, 0) + f.fires
    return out


def parse_spec(spec: str) -> List[_Fault]:
    """Parse the ``FJT_FAULTS`` grammar → faults. Raises ValueError on
    an unknown kind or an unparseable param."""
    faults: List[_Fault] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        pieces = part.split(":")
        kind = pieces[0].strip()
        params: Dict[str, float] = {}
        for kv in pieces[1:]:
            k, _, v = kv.partition("=")
            if not _ or not k.strip():
                raise ValueError(f"bad fault param {kv!r} in {part!r}")
            params[k.strip()] = float(v)
        faults.append(_Fault(kind, params))
    return faults


def install_from_env(env: Optional[str] = None) -> bool:
    """(Re)install the plan from ``FJT_FAULTS`` (or ``env``). → True
    when faults were installed. A malformed spec is skipped loudly on
    stderr — a typo in a drill config must not crash the pipeline it
    was meant to drill."""
    global _ACTIVE
    raw = os.environ.get(_ENV) if env is None else env
    if not raw:
        return False
    try:
        faults = parse_spec(raw)
    except ValueError as e:
        print(f"[fjt-faults] ignoring {_ENV}={raw!r}: {e}",
              file=sys.stderr, flush=True)
        return False
    if not faults:
        return False
    _ACTIVE = FaultPlan(faults)
    flight.record(
        "faults_installed", kinds=[f.kind for f in faults], spec=raw,
    )
    return True


# env faults arm at import so every process in a drill (workers spawned
# by the supervisor included) picks them up with no plumbing
install_from_env()
