"""Deterministic fault injection: overload and recovery, drilled.

PRs 3-7 built the telemetry that *reports* a dying broker, a wedged
worker, or a failed checkpoint write — but every one of those paths was
only ever exercised by whatever chaos a test could improvise (monkey-
patched sockets, killed subprocesses). This harness injects the faults
into the REAL code paths, deterministically, so the overload drill and
the recovery tests run the same failure the same way every time:

=================  =========================================  ===========================
kind               fires in (site)                            effect
=================  =========================================  ===========================
``broker_death``   kafka fetch RPC (``runtime/kafka.py``)     raises ``ConnectionError`` →
                                                              the real reconnect/backoff path
``slow_fetch``     kafka fetch RPC                            sleeps ``delay_ms``
``dispatch_delay`` device dispatch                            sleeps ``delay_ms`` before the
                   (``OverlappedDispatcher.launch``)          dispatch is issued
``checkpoint_fail`` checkpoint write                          raises ``OSError`` mid-write →
                   (``CheckpointManager.save``)               the retry/backoff path
``worker_wedge``   the block score loop                       sleeps ``wedge_s`` per fire —
                                                              the heartbeat-wedge shape
``poison_record``  per-batch scoring (``score_batch`` site,   raises ``InjectedPoisonRecord``
                   carries the dispatched offsets)            when ``offset=``/``every=``
                                                              matches → the record-isolation
                                                              (suspect-mode bisection) path
``worker_crash``   any site via ``site=`` (default            SIGKILLs the process — the
                   ``score_loop``); ``offset=`` targets the   kill-anywhere recovery drill's
                   batch containing that record               chaos primitive
``device_oom``     device launch/readback (``device_dispatch``raises ``InjectedDeviceOOM``
                   / ``device_readback`` via ``site=``)       → batch-size bisection
``device_error``   device launch/readback                     raises ``InjectedDeviceError``
                                                              → redispatch / circuit breaker
``chip_loss``      device launch/readback                     raises ``InjectedChipLoss``
                                                              → supervisor escalation /
                                                              degraded-mesh mode
=================  =========================================  ===========================

The device kinds ride the real launch/readback hook sites in
``runtime/pipeline.OverlappedDispatcher`` and the record engine's
submit/finish path; ``runtime/devfault.classify`` recognizes their
exceptions exactly like real XLA runtime errors, so the drills prove
the production recovery ladder, not a parallel test-only path.
``checkpoint_fail`` accepts ``errno=`` (e.g. ``errno=28`` = ENOSPC) so
a persistent-full-disk outage is drillable end to end.

Two front doors:

- **env** — ``FJT_FAULTS`` holds comma-separated specs, each a kind
  followed by ``:key=value`` params::

      FJT_FAULTS="slow_fetch:delay_ms=40:p=0.5,broker_death:after_s=5:for_s=2"

  parsed once at import (and re-parseable via :func:`install_from_env`);
  a malformed spec is skipped loudly (stderr), never fatal.
- **programmatic** — :func:`inject`/:func:`clear` for tests and drills.

Gate params (all optional): ``after_s`` (arm delay from install),
``for_s`` (active window after arming), ``n`` (max fires), ``p``
(per-call probability from a seeded RNG — ``seed`` makes it
deterministic), ``delay_ms`` / ``wedge_s`` (the action magnitudes).

Every fire records a rate-limited ``fault_injected`` flight event (≥1 s
apart per fault — the flight ring is for rare events; exact counts live
in :func:`stats`).

**Zero-overhead contract**: with no faults configured, ``fire(site)``
is one global load and a None check — pinned by the perf-smoke
tripwire. Hook sites sit on per-fetch / per-batch paths, never
per-record.
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time
from typing import Dict, List, Optional

from flink_jpmml_tpu.obs import recorder as flight

_ENV = "FJT_FAULTS"
_EVENT_MIN_PERIOD_S = 1.0

# the sites the runtime actually hooks; a kind IS its DEFAULT site
# mapping (worker_crash may override via its ``site=`` param — a kill
# must land ANYWHERE: mid-fetch, mid-dispatch, mid-checkpoint)
SITES = {
    "broker_death": "kafka_fetch",
    "slow_fetch": "kafka_fetch",
    "dispatch_delay": "dispatch",
    "checkpoint_fail": "checkpoint_write",
    "worker_wedge": "score_loop",
    # per-batch scoring hook carrying the batch's offsets as context:
    # an injected poison record raises exactly when its offset is in
    # the dispatched range, so bisection isolates it like a real one
    "poison_record": "score_batch",
    # SIGKILL self at the chosen site — the kill-anywhere recovery
    # drill's chaos primitive (no Python cleanup runs, like a real OOM
    # kill); with ``offset=`` it fires only when that offset is in the
    # batch, the shape of a record that hard-crashes the process
    "worker_crash": "score_loop",
    # device faults (runtime/devfault.py's taxonomy): default to the
    # readback site — async dispatch errors surface where the host
    # first blocks, like the real thing; ``site=device_dispatch``
    # moves them to launch time
    "device_oom": "device_readback",
    "device_error": "device_readback",
    "chip_loss": "device_readback",
}

# sites a ``site=`` param may name (worker_crash: any; device kinds:
# the two device hook sites only)
KNOWN_SITES = frozenset(
    list(SITES.values()) + ["score_batch", "dispatch", "device_dispatch"]
)
_DEVICE_KINDS = frozenset(("device_oom", "device_error", "chip_loss"))
_DEVICE_SITES = frozenset(("device_dispatch", "device_readback"))


class InjectedBrokerDeath(ConnectionError):
    """Injected broker death: rides the kafka sources' real
    ``except (OSError, ConnectionError, ...)`` → reconnect path."""


class InjectedCheckpointFailure(OSError):
    """Injected checkpoint write failure: rides ``CheckpointManager
    .save``'s real ``except OSError`` → retry/backoff path."""


class InjectedDeviceOOM(RuntimeError):
    """Injected device OOM: message mirrors XLA's RESOURCE_EXHAUSTED
    status so ``runtime/devfault.classify`` routes it exactly like a
    real allocator refusal → the batch-size bisection ladder."""

    def __init__(self):
        super().__init__(
            "RESOURCE_EXHAUSTED: Out of memory allocating device "
            "buffer (injected device OOM)"
        )


class InjectedDeviceError(RuntimeError):
    """Injected transient XLA runtime failure → the redispatch /
    circuit-breaker ladder."""

    def __init__(self):
        super().__init__(
            "INTERNAL: injected XLA runtime error (transient device "
            "failure)"
        )


class InjectedChipLoss(RuntimeError):
    """Injected unrecoverable device loss → supervisor escalation
    (and, on a mesh, degraded-mesh mode)."""

    def __init__(self):
        super().__init__(
            "UNAVAILABLE: device lost (injected chip loss)"
        )


class InjectedPoisonRecord(ValueError):
    """Injected poison record: raised from the per-batch scoring hook
    when a configured offset lands in the dispatched range — rides the
    pipelines' real record-isolation (suspect-mode bisection) path.
    ``offsets`` carries the matched offsets."""

    def __init__(self, offsets):
        super().__init__(
            f"injected poison record at offset(s) {list(offsets)}"
        )
        self.offsets = tuple(int(o) for o in offsets)


class _Fault:
    """One configured fault: its gates (arm delay, active window, count
    cap, probability) and its action."""

    def __init__(self, kind: str, params: Dict[str, float],
                 clock=time.monotonic):
        if kind not in SITES:
            raise ValueError(
                f"unknown fault kind {kind!r} (have {sorted(SITES)})"
            )
        self.kind = kind
        site = params.get("site")
        if site is not None:
            if kind == "worker_crash":
                allowed = KNOWN_SITES
            elif kind in _DEVICE_KINDS:
                # a device fault can only strike where device work is
                # launched or waited on
                allowed = _DEVICE_SITES
            else:
                raise ValueError(
                    f"site= is only meaningful on worker_crash and the "
                    f"device kinds, not {kind!r}"
                )
            if site not in allowed:
                raise ValueError(
                    f"unknown fault site {site!r} for {kind!r} "
                    f"(have {sorted(allowed)})"
                )
            self.site = str(site)
        else:
            self.site = SITES[kind]
        self._clock = clock
        self._t0 = clock()
        self.after_s = float(params.get("after_s", 0.0))
        self.for_s = params.get("for_s")
        self.max_fires = (
            int(params["n"]) if params.get("n") is not None else None
        )
        self.p = params.get("p")
        self.delay_s = float(params.get("delay_ms", 50.0)) / 1000.0
        self.wedge_s = float(params.get("wedge_s", 0.5))
        # offset targeting (poison_record / worker_crash at an
        # offset-carrying site): ``offset=K`` fires exactly when record
        # K is in the batch; ``every=N`` poisons offsets ≡ 0 (mod N) —
        # both deterministic across replays, which is what lets the
        # drill assert "these offsets land in the DLQ exactly"
        self.offset = (
            int(params["offset"]) if params.get("offset") is not None
            else None
        )
        self.every = (
            int(params["every"]) if params.get("every") is not None
            else None
        )
        # checkpoint_fail only: stamp this errno on the injected
        # OSError (errno=28 drills persistent ENOSPC → the checkpoint
        # plane's degrade-don't-die path)
        self.errno = (
            int(params["errno"]) if params.get("errno") is not None
            else None
        )
        if kind == "poison_record" and self.offset is None and self.every is None:
            raise ValueError(
                "poison_record needs offset= or every= targeting"
            )
        # seeded by default: the SAME drill injects the SAME faults —
        # determinism is the point of a harness over improvised chaos
        self._rng = random.Random(int(params.get("seed", 0xFA17)))
        self.fires = 0
        self._last_event = 0.0
        self._mu = threading.Lock()

    def _match_offsets(self, ctx: Optional[dict]):
        """Offset-targeted gate: → the matched offsets (possibly ()),
        or True when this fault has no offset constraint."""
        if self.offset is None and self.every is None:
            return True
        offsets = None if ctx is None else ctx.get("offsets")
        if offsets is None:
            return ()  # offset-targeted fault at an offset-less site
        matched = []
        for o in offsets:
            o = int(o)
            if self.offset is not None and o == self.offset:
                matched.append(o)
            elif self.every is not None and self.every > 0 and o % self.every == 0:
                matched.append(o)
        return tuple(matched)

    def try_claim(self, ctx: Optional[dict] = None):
        """Evaluate the gates; claim one fire when they all pass.
        → falsy (no fire), or a fire token: ``True`` / the non-empty
        tuple of matched offsets for offset-targeted faults."""
        token = self._match_offsets(ctx)
        if not token:
            return False
        now = self._clock()
        armed_at = self._t0 + self.after_s
        if now < armed_at:
            return False
        if self.for_s is not None and now > armed_at + float(self.for_s):
            return False
        with self._mu:
            if self.max_fires is not None and self.fires >= self.max_fires:
                return False
            if self.p is not None and self._rng.random() >= float(self.p):
                return False
            self.fires += 1
            event_due = now - self._last_event >= _EVENT_MIN_PERIOD_S
            if event_due:
                self._last_event = now
        if event_due:
            flight.record(
                "fault_injected", fault=self.kind, site=self.site,
                fires=self.fires,
            )
        return token

    def act(self, token=True) -> None:
        if self.kind == "broker_death":
            raise InjectedBrokerDeath("injected broker death")
        if self.kind == "checkpoint_fail":
            e = InjectedCheckpointFailure(
                "injected checkpoint write failure"
            )
            if self.errno is not None:
                e.errno = self.errno
            raise e
        if self.kind == "device_oom":
            raise InjectedDeviceOOM()
        if self.kind == "device_error":
            raise InjectedDeviceError()
        if self.kind == "chip_loss":
            raise InjectedChipLoss()
        if self.kind == "poison_record":
            raise InjectedPoisonRecord(
                token if token is not True else ()
            )
        if self.kind == "worker_crash":
            # SIGKILL self: no atexit, no finally, no flushes — the
            # honest shape of an OOM kill or a segfaulting record. The
            # flight event above already rode its own fsync'd dump path
            # only if a dump was triggered; a crash drill reads the
            # SUPERVISOR's events, not this process's.
            os.kill(os.getpid(), 9)
            return  # pragma: no cover - unreachable
        if self.kind == "worker_wedge":
            time.sleep(self.wedge_s)
        else:  # slow_fetch / dispatch_delay
            time.sleep(self.delay_s)


class FaultPlan:
    def __init__(self, faults: List[_Fault]):
        self.faults = faults
        self._by_site: Dict[str, List[_Fault]] = {}
        for f in faults:
            self._by_site.setdefault(f.site, []).append(f)

    def fire(self, site: str, ctx: Optional[dict] = None) -> None:
        for f in self._by_site.get(site, ()):
            token = f.try_claim(ctx)
            if token:
                f.act(token)


# None = no faults configured: fire() is a global load + None check
_ACTIVE: Optional[FaultPlan] = None


def fire(site: str, **ctx) -> None:
    """The hook the runtime calls at each injection site. A raised
    fault propagates to the caller's real error-handling path.
    ``ctx`` carries site context for targeted faults (the
    ``score_batch`` site passes ``offsets=<array>`` so poison faults
    can match the dispatched range); with no faults configured this
    stays one global load + a None check."""
    plan = _ACTIVE
    if plan is None:
        return
    plan.fire(site, ctx if ctx else None)


def active() -> bool:
    return _ACTIVE is not None


def inject(kind: str, **params) -> _Fault:
    """Programmatically add one fault (tests/drills). → the fault, so
    the caller can read ``fires``."""
    global _ACTIVE
    f = _Fault(kind, params)
    faults = list(_ACTIVE.faults) if _ACTIVE is not None else []
    faults.append(f)
    _ACTIVE = FaultPlan(faults)
    return f


def clear() -> None:
    """Drop every configured fault (idempotent)."""
    global _ACTIVE
    _ACTIVE = None


def stats() -> Dict[str, int]:
    """→ {kind: fires} for every configured fault (summed per kind)."""
    plan = _ACTIVE
    out: Dict[str, int] = {}
    if plan is not None:
        for f in plan.faults:
            out[f.kind] = out.get(f.kind, 0) + f.fires
    return out


def parse_spec(spec: str) -> List[_Fault]:
    """Parse the ``FJT_FAULTS`` grammar → faults. Raises ValueError on
    an unknown kind or an unparseable param."""
    faults: List[_Fault] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        pieces = part.split(":")
        kind = pieces[0].strip()
        params: Dict[str, float] = {}
        for kv in pieces[1:]:
            k, _, v = kv.partition("=")
            if not _ or not k.strip():
                raise ValueError(f"bad fault param {kv!r} in {part!r}")
            if k.strip() == "site":
                # the one string-valued param (worker_crash site
                # selection); everything else stays numeric
                params[k.strip()] = v.strip()
            else:
                params[k.strip()] = float(v)
        faults.append(_Fault(kind, params))
    return faults


def install_from_env(env: Optional[str] = None) -> bool:
    """(Re)install the plan from ``FJT_FAULTS`` (or ``env``). → True
    when faults were installed. A malformed spec is skipped loudly on
    stderr — a typo in a drill config must not crash the pipeline it
    was meant to drill."""
    global _ACTIVE
    raw = os.environ.get(_ENV) if env is None else env
    if not raw:
        return False
    try:
        faults = parse_spec(raw)
    except ValueError as e:
        print(f"[fjt-faults] ignoring {_ENV}={raw!r}: {e}",
              file=sys.stderr, flush=True)
        return False
    if not faults:
        return False
    _ACTIVE = FaultPlan(faults)
    flight.record(
        "faults_installed", kinds=[f.kind for f in faults], spec=raw,
    )
    return True


# env faults arm at import so every process in a drill (workers spawned
# by the supervisor included) picks them up with no plumbing
install_from_env()
