"""Device-fault classification: a sick chip is not a poison record.

PR 12 taught the hot paths to survive *record* poison — a scoring
exception bisects the batch and quarantines the offender. But the
``on_error`` hook saw every exception the same way, and a device OOM,
an XLA runtime error, or a lost chip mid-dispatch would have sent
perfectly clean records to the dead-letter queue (or killed the worker
outright and burned a restart+replay cycle for a fault that a simple
re-dispatch heals). This module is the triage step both hot paths run
FIRST on any dispatch/readback-time exception:

=================  ====================================================
kind               meaning / recovery ladder entry
=================  ====================================================
``device_oom``     the device allocator refused the batch — bisect the
                   *batch size* (never the records) and feed the
                   shrunken cap into the AdaptiveBatcher
                   (serving/overload.py)
``device_error``   a transient XLA internal/runtime failure — re-
                   dispatch the in-flight batch from its host-retained
                   staging copy under the shared full-jitter backoff;
                   persistent streaks trip the circuit breaker
                   (serving/failover.py) onto the host fallback tier
``chip_loss``      the device is gone — escalate to the supervisor
                   (restart with ``FJT_RESTART_STREAK`` context) and,
                   on a mesh, to degraded-mesh mode
                   (parallel/sharding.degraded_mesh)
``None``           not a device fault: record poison, routing bugs,
                   featurize errors — the PR 12 isolation path owns it
=================  ====================================================

Classification is type-gated: only the runtime's own injected device
faults (runtime/faults.py) and the XLA runtime error types
(``jaxlib``'s ``XlaRuntimeError`` / ``jax.errors.JaxRuntimeError``)
classify at all — an application ``ValueError`` can never be mistaken
for a sick device, and an injected poison record (a ``ValueError``
subclass) stays poison. Within the XLA types the *kind* comes from the
status-message markers XLA actually emits (``RESOURCE_EXHAUSTED`` /
"out of memory" → OOM; device-lost/halted markers → chip loss;
everything else → transient device error), so the injected faults and
the real errors exercise one classifier.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Tuple

from flink_jpmml_tpu.obs import recorder as flight

KIND_OOM = "device_oom"
KIND_ERROR = "device_error"
KIND_LOST = "chip_loss"
KINDS = (KIND_OOM, KIND_ERROR, KIND_LOST)

# status markers in XLA runtime error messages (lowercased substring
# match); LOST checks first — a dead chip's message can mention memory
_OOM_MARKERS = ("resource_exhausted", "out of memory", "oom")
_LOST_MARKERS = (
    "device lost", "device_lost", "data_loss", "halted",
    "device unavailable", "failed to connect",
)

_XLA_TYPES: Optional[Tuple[type, ...]] = None


def _xla_error_types() -> Tuple[type, ...]:
    """The XLA runtime error types this build exposes (resolved once;
    runs on the error path only — never on a hot path)."""
    global _XLA_TYPES
    if _XLA_TYPES is None:
        types = []
        try:  # the canonical type every backend raises through
            from jaxlib.xla_extension import XlaRuntimeError

            types.append(XlaRuntimeError)
        except Exception:  # pragma: no cover - jaxlib layout varies
            pass
        try:  # newer jax re-exports (may alias the above)
            from jax.errors import JaxRuntimeError

            types.append(JaxRuntimeError)
        except Exception:
            pass
        _XLA_TYPES = tuple(types)
    return _XLA_TYPES


def _kind_from_message(msg: str) -> str:
    m = msg.lower()
    for marker in _LOST_MARKERS:
        if marker in m:
            return KIND_LOST
    for marker in _OOM_MARKERS:
        if marker in m:
            return KIND_OOM
    return KIND_ERROR


def classify(exc: BaseException) -> Optional[str]:
    """→ the device-fault kind of ``exc``, or None when it is NOT a
    device fault (record poison, application errors). The one triage
    call both hot paths make before the PR 12 isolation path may run —
    clean records must never be quarantined for a sick device."""
    from flink_jpmml_tpu.runtime import faults

    if isinstance(exc, faults.InjectedChipLoss):
        return KIND_LOST
    if isinstance(exc, faults.InjectedDeviceOOM):
        return KIND_OOM
    if isinstance(exc, faults.InjectedDeviceError):
        return KIND_ERROR
    xla = _xla_error_types()
    if xla and isinstance(exc, xla):
        return _kind_from_message(str(exc))
    return None


# -- shared fault accounting -------------------------------------------------

_EVENT_MIN_PERIOD_S = 1.0
_note_mu = threading.Lock()
# rate limiter PER KIND: a chatty device_error stream must not
# suppress the first (possibly only) device_oom/chip_loss event —
# each taxonomy entry keeps its own flight-event cadence
_last_event: dict = {}


def note(metrics, kind: str, model=None, first_off=None, n=None,
         error=None) -> None:
    """Book one observed device fault: the ``device_fault_total{kind}``
    counter (fleet merge: sum — true fault volume) plus a rate-limited
    ``device_fault`` flight event carrying the active journey's trace
    id when one is set (the fjt-trace pivot). Shared by the block
    path's failover plane, the record engine, and the dynamic scorer so
    the taxonomy cannot drift between them."""
    if metrics is not None:
        metrics.counter(f'device_fault_total{{kind="{kind}"}}').inc()
    now = time.monotonic()
    due = False
    with _note_mu:
        if now - _last_event.get(kind, 0.0) >= _EVENT_MIN_PERIOD_S:
            _last_event[kind] = now
            due = True
    if due:
        from flink_jpmml_tpu.obs import trace as trace_mod

        ctx = trace_mod.current()
        flight.record(
            "device_fault", fault=kind, model=model, first=first_off,
            n=n, error=None if error is None else repr(error),
            trace_id=None if ctx is None else ctx.trace_id,
        )
