"""Bounded queue with fill-or-deadline batch draining.

The reference's backpressure came from Flink's credit-based network stack
(SURVEY.md §3 row D1, EXT-A); ours is a bounded host-side queue between
sources and the device loop: producers block when the device falls behind,
and the consumer drains *batches* — up to ``max_n`` records, waiting at most
``deadline_us`` after the first record arrives (SURVEY.md §8 step 3
"fill-or-deadline"). This is the latency/throughput control point: a full
batch ships immediately; a trickle ships after the deadline with padding.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, List, Optional


class Closed(Exception):
    """The queue was closed and fully drained."""


class BoundedQueue:
    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError(f"capacity must be > 0: {capacity}")
        self._capacity = capacity
        self._items: deque = deque()
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._not_empty = threading.Condition(self._lock)
        self._closed = False

    def put(self, item: Any, timeout: Optional[float] = None) -> bool:
        """Blocking put; returns False on timeout, raises Closed if closed."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._not_full:
            while len(self._items) >= self._capacity:
                if self._closed:
                    raise Closed()
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._not_full.wait(remaining)
            if self._closed:
                raise Closed()
            self._items.append(item)
            self._not_empty.notify()
            return True

    def drain(self, max_n: int, deadline_us: int) -> List[Any]:
        """Take up to ``max_n`` items.

        Blocks until at least one item is available (or the queue closes —
        then raises :class:`Closed` once empty). After the first item, keeps
        taking until ``max_n`` or until ``deadline_us`` microseconds have
        elapsed since the first item was taken.
        """
        out: List[Any] = []
        with self._not_empty:
            while not self._items:
                if self._closed:
                    raise Closed()
                self._not_empty.wait(0.1)
            take = min(max_n, len(self._items))
            for _ in range(take):
                out.append(self._items.popleft())
            self._not_full.notify_all()
        if len(out) >= max_n:
            return out
        deadline = time.monotonic() + deadline_us / 1e6
        while len(out) < max_n:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            with self._not_empty:
                if not self._items:
                    if self._closed:
                        break
                    self._not_empty.wait(min(remaining, 0.05))
                take = min(max_n - len(out), len(self._items))
                for _ in range(take):
                    out.append(self._items.popleft())
                if take:
                    self._not_full.notify_all()
        return out

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    @property
    def capacity(self) -> int:
        return self._capacity

    def occupancy(self) -> float:
        """Fill fraction in [0, 1] — the queue-side input to the
        composite backpressure score (obs/pressure.py): producers are
        blocked exactly when this sits at 1.0."""
        with self._lock:
            return min(len(self._items) / self._capacity, 1.0)
