"""Streaming micro-batch engine (SURVEY.md section 8 step 3)."""

from flink_jpmml_tpu.runtime.engine import Pipeline, Scorer, StaticScorer  # noqa: F401
from flink_jpmml_tpu.runtime.pipeline import OverlappedDispatcher  # noqa: F401
from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager  # noqa: F401
from flink_jpmml_tpu.runtime.queues import BoundedQueue, Closed  # noqa: F401
from flink_jpmml_tpu.runtime.net import (  # noqa: F401
    BlockFrameServer,
    TcpBlockSource,
    TcpRecordSource,
)
