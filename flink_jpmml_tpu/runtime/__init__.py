"""Streaming micro-batch engine (SURVEY.md §8 step 3): sources, batcher, sinks."""
