"""Checkpoint/resume for the streaming runtime (capability C7).

The reference inherited checkpointing from Flink barriers and contributed
only its operator state — the served-models map (SURVEY.md §6). Our runtime
owns the whole mechanism, but the state is deliberately tiny and JSON-shaped:
(source offsets, served-model registry, counters). Model *parameters* are
never checkpointed — models reload from their PMML paths on resume, exactly
like the reference's idempotent ``open()`` reload (capability C2).

Atomicity: write to a temp file in the same directory, fsync, replace,
fsync the DIRECTORY — the last step makes the rename itself durable, so
a crash at any instant leaves either the previous snapshot set or the
new one, never a truncated newest file (pinned by the kill-mid-write
drill in tests/test_checkpoint.py).
Retention: the last ``keep`` checkpoints are kept for manual rollback.
Transient write failures retry through the shared capped-jittered
backoff (utils/retry.py, the kafka reconnect schedule); only an
exhausted streak raises.
"""

from __future__ import annotations

import errno as errno_mod
import json
import os
import tempfile
import time
import warnings
from typing import Any, Dict, Optional

from flink_jpmml_tpu.obs import recorder as flight
from flink_jpmml_tpu.runtime import faults
from flink_jpmml_tpu.utils.exceptions import CheckpointException
from flink_jpmml_tpu.utils.retry import Backoff

_PREFIX = "ckpt-"


_FULL_DISK_ERRNOS = (errno_mod.ENOSPC, errno_mod.EDQUOT)
_SUSPEND_EVENT_MIN_PERIOD_S = 5.0


def _is_disk_full(exc: BaseException) -> bool:
    """Does this CheckpointException trace back to a full disk/quota?
    ENOSPC is a DEGRADE signal, not a die signal: the records are
    safe (they replay from the last committed offset), it is only the
    snapshot cadence that stalls."""
    cause = exc.__cause__
    return (
        isinstance(cause, OSError)
        and cause.errno in _FULL_DISK_ERRNOS
    )


class CheckpointPolicy:
    """Interval-gated save/restore shared by the record and block pipelines
    (one implementation of the timing + enablement logic, so the two
    engines cannot drift on checkpoint semantics).

    Persistent-ENOSPC degrade: a save streak exhausted by a FULL DISK
    does not raise out of the score loop (that crash-looped the worker
    against a disk a restart cannot empty) — checkpointing SUSPENDS
    instead: serving continues, the ``checkpoint_suspended`` gauge
    (fleet merge: worst-of) and a rate-limited flight event flag the
    widened replay window, and each subsequent interval sends ONE
    cheap write probe — the first one that lands resumes the cadence
    automatically (``checkpoint_resumed``). Any other exhausted save
    failure keeps the historical raise."""

    def __init__(self, manager: Optional["CheckpointManager"],
                 interval_s: float, metrics=None):
        self._mgr = manager
        self._interval = interval_s
        self._last = 0.0
        self._metrics = metrics
        self.suspended = False
        # gauge registered lazily at the first suspension (the
        # adaptive_batch discipline: healthy pipelines don't export a
        # permanent 0 row)
        self._suspended_gauge = None
        self._last_suspend_event = 0.0

    @property
    def enabled(self) -> bool:
        return self._mgr is not None

    def restore_latest(self) -> Optional[Dict[str, Any]]:
        if self._mgr is None:
            return None
        return self._mgr.load_latest()

    def maybe_save(self, state_fn) -> None:
        if self._mgr is None:
            return
        if time.monotonic() - self._last >= self._interval:
            self.save_now(state_fn)

    def save_now(self, state_fn) -> None:
        if self._mgr is None:
            return
        try:
            # suspended → one cheap probe per interval instead of a
            # full retry streak per attempt
            self._mgr.save(state_fn(), probe=self.suspended)
        except CheckpointException as e:
            if not _is_disk_full(e):
                raise
            self._note_suspended(e)
            # probe cadence: next attempt only after another interval
            self._last = time.monotonic()
            return
        if self.suspended:
            self.suspended = False
            if self._suspended_gauge is not None:
                self._suspended_gauge.set(0.0)
            flight.record("checkpoint_resumed")
        self._last = time.monotonic()

    def _note_suspended(self, exc: BaseException) -> None:
        first = not self.suspended
        self.suspended = True
        if self._metrics is not None:
            if self._suspended_gauge is None:
                self._suspended_gauge = self._metrics.gauge(
                    "checkpoint_suspended"
                )
            self._suspended_gauge.set(1.0)
        now = time.monotonic()
        if first or now - self._last_suspend_event >= (
            _SUSPEND_EVENT_MIN_PERIOD_S
        ):
            self._last_suspend_event = now
            flight.record(
                "checkpoint_suspended", error=str(exc), first=first,
            )


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3):
        self._dir = directory
        self._keep = keep
        os.makedirs(directory, exist_ok=True)

    @property
    def directory(self) -> str:
        """Public so siblings can colocate durable state with the
        resume state it protects: the pipelines' default DLQ and the
        crash-loop fingerprint files live under here (runtime/dlq.py)."""
        return self._dir

    def save(self, state: Dict[str, Any], probe: bool = False) -> str:
        """Write one snapshot crash-safely, retrying transient failures.

        Each attempt is temp-file → fsync → ``os.replace`` → directory
        fsync: the file's bytes are durable before the name appears,
        and the name itself is durable before save() returns — a crash
        (or SIGKILL) at ANY instant leaves every ``ckpt-*.json``
        parseable. Transient OSErrors (EMFILE, an NFS hiccup, a full
        disk that clears) retry with the shared jittered backoff; an
        exhausted streak raises so the operator sees a checkpoint plane
        that cannot make progress.

        ``probe=True`` (the suspended-checkpointing resume probe,
        :class:`CheckpointPolicy`): ONE write attempt, no backoff, no
        retry flight events — a known-full disk must not re-pay the
        whole schedule (or spam the flight ring) every interval."""
        payload = {"timestamp": time.time(), "state": state}
        retries = 0
        if probe:
            try:
                path = self._write_once(payload)
            except OSError as e:
                raise CheckpointException(
                    f"checkpoint probe failed: {e}"
                ) from e
        else:
            backoff = Backoff("checkpoint")
            while True:
                try:
                    path = self._write_once(payload)
                except OSError as e:
                    flight.record(
                        "checkpoint_save_retry",
                        error=str(e), attempt=backoff.attempts + 1,
                    )
                    if backoff.exhausted:
                        flight.record(
                            "checkpoint_save_failed", error=str(e)
                        )
                        raise CheckpointException(
                            f"cannot write checkpoint after "
                            f"{backoff.attempts} retries: {e}"
                        ) from e
                    backoff.sleep()
                    continue
                break
            retries = backoff.attempts
        flight.record(
            "checkpoint_save", path=path,
            source_offset=state.get("source_offset"),
            retries=retries,
        )
        self._gc()
        return path

    def _write_once(self, payload: Dict[str, Any]) -> str:
        """One crash-safe write attempt; raises OSError on failure
        (the temp file, if any, is removed so retries can't litter)."""
        fd, tmp = tempfile.mkstemp(prefix=".tmp-", dir=self._dir)
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(payload, f)
                f.flush()
                # mid-write fault hook (runtime/faults.py): an injected
                # OSError here leaves a partial temp file — exactly the
                # crash the atomic-replace protocol must survive
                faults.fire("checkpoint_write")
                os.fsync(f.fileno())
            path = os.path.join(
                self._dir, f"{_PREFIX}{int(time.time() * 1e6)}.json"
            )
            os.replace(tmp, path)
            tmp = None
            # durable NAME, not just durable bytes: fsync the directory
            # so the replace survives a crash (best-effort — some
            # filesystems refuse directory fds)
            try:
                dfd = os.open(self._dir, os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            except OSError:
                pass
            return path
        finally:
            if tmp is not None:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass

    def load_latest(self) -> Optional[Dict[str, Any]]:
        """Newest readable checkpoint's state (None when none exist).

        A corrupt newest file (disk damage — the atomic rename rules out
        torn writes) falls back to the next retained snapshot with a
        loud warning: that is what retention is FOR, and resuming from
        an older offset just replays more records (the at-least-once
        contract). Only when every retained checkpoint is unreadable
        does restore fail.

        Transient I/O failures (EMFILE, EACCES, an NFS hiccup) are NOT
        corruption: falling back past an intact newest snapshot would
        silently replay up to a full retention window. Such reads get
        one retry; a second failure raises so the operator sees it."""
        ckpts = self._list()
        if not ckpts:
            return None
        errors = []
        for path in reversed(ckpts):
            try:
                state = self._read_state(path)
            except (
                ValueError, KeyError, TypeError,
            ) as e:
                # ValueError covers json.JSONDecodeError (malformed
                # JSON) and UnicodeDecodeError (bit-rot turned the
                # newest snapshot into invalid UTF-8 — deterministic
                # corruption, not a transient read failure); TypeError:
                # valid JSON that isn't a dict payload
                errors.append(f"{path!r}: {e}")
                continue
            except FileNotFoundError as e:
                # a concurrent _gc may legitimately remove older files;
                # a vanished file is not an intact snapshot being skipped
                errors.append(f"{path!r}: {e}")
                continue
            except OSError as e:
                raise CheckpointException(
                    f"transient I/O failure reading {path!r} (retried "
                    f"once): {e} — not falling back past a possibly "
                    "intact snapshot"
                ) from e
            if errors:
                warnings.warn(
                    "corrupt checkpoint(s) skipped during restore "
                    f"({'; '.join(errors)}); resuming from {path!r}",
                    RuntimeWarning,
                    stacklevel=2,
                )
            flight.record(
                "checkpoint_load", path=path, skipped_corrupt=len(errors),
                source_offset=(
                    state.get("source_offset")
                    if isinstance(state, dict) else None
                ),
            )
            return state
        raise CheckpointException(
            f"no readable checkpoint: {'; '.join(errors)}"
        )

    @staticmethod
    def _read_state(path: str):
        """Read + decode one snapshot, retrying a transient OSError
        once (decode errors are deterministic — no point retrying)."""
        try:
            with open(path, "r", encoding="utf-8") as f:
                return json.load(f)["state"]
        except FileNotFoundError:
            raise
        except OSError:
            time.sleep(0.05)
            with open(path, "r", encoding="utf-8") as f:
                return json.load(f)["state"]

    def _list(self):
        try:
            names = [
                n
                for n in os.listdir(self._dir)
                if n.startswith(_PREFIX) and n.endswith(".json")
            ]
        except OSError as e:
            raise CheckpointException(f"cannot list checkpoints: {e}") from e
        return [os.path.join(self._dir, n) for n in sorted(names)]

    def _gc(self) -> None:
        ckpts = self._list()
        for p in ckpts[: -self._keep]:
            try:
                os.unlink(p)
            except OSError:
                pass
