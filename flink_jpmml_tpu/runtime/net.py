"""Network streaming: an offset-replayable framed-TCP record feed.

BASELINE config 2 places the north-star GBM on a "Kafka tabular stream";
the reference gets network ingestion from Flink's connector ecosystem
(SURVEY.md §2 EXT-A). This module is the in-tree equivalent: a
deliberately tiny Kafka-style *pull* protocol — offset-addressed fetch
over TCP with length-prefixed frames — so sources get exact resume
semantics without an external broker. The real Kafka-wire counterpart
lives in :mod:`flink_jpmml_tpu.runtime.kafka` (actual binary protocol:
Fetch v4, magic-2 record batches, CRC32C) behind the same
Source/BlockSource interfaces; this simpler protocol remains for
low-dependency drills and as the block-frame push server.

Protocol (little-endian):
  client → server on connect:  magic ``b"FJT1"`` + u64 start_offset
  server → client frames:      u32 body_len, then body:
      u8 kind
      kind 1 (f32 block):    u64 first_offset, u32 n_rows, u32 n_cols,
                             n_rows*n_cols f32
      kind 2 (end-of-stream): empty
      kind 3 (JSON records): u64 first_offset, u32 count,
                             newline-joined JSON docs

Offset domain (ONE domain end to end — frames, sources, checkpoints):
an offset k always means "k records consumed"; equivalently, the next
record to serve/score has 0-based index k. A frame's ``first_offset`` is
the consumed-count *before* its first record (= that record's index), and
the offset checkpointed after scoring a record of index i is ``i + 1``
(see :func:`consumed_offset` — the only index→offset conversion in this
module). ``seek(k)`` therefore passes a checkpointed engine offset to the
frame protocol *unchanged*: both mean "resume at record index k". A
client (re)connects at its next-needed offset and the server replays from
there — the Kafka consumer model in miniature. Client-side reconnect is
automatic: a dropped connection (server restart, network blip) retries
with backoff from the exact next offset, so no record is lost or
duplicated across the blip.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from flink_jpmml_tpu.obs import recorder as flight
from flink_jpmml_tpu.runtime.block import BlockSource
from flink_jpmml_tpu.runtime.sources import Polled, Record, Source

MAGIC = b"FJT1"
KIND_BLOCK = 1
KIND_EOS = 2
KIND_RECORDS = 3

_HDR = struct.Struct("<I")  # frame body length
_BLOCK_HDR = struct.Struct("<BQII")  # kind, first_offset, n_rows, n_cols
_REC_HDR = struct.Struct("<BQI")  # kind, first_offset, count
_REQ = struct.Struct("<4sQ")  # magic, start_offset


def consumed_offset(record_index: int) -> int:
    """Record index → checkpoint offset ("records consumed through this
    record"). The inverse direction needs no conversion: a checkpointed
    offset k IS the index of the next record, so ``seek(k)`` forwards k
    to the frame protocol verbatim. This is the single place the two
    representations of the one offset domain meet (module docstring)."""
    return record_index + 1


class BlockFrameServer:
    """Serves a replayable record log over the frame protocol.

    ``data`` is either an ``[N, F]`` float32 array (block frames) or a
    sequence of dict records (JSON frames). Any client may fetch from any
    offset — the log is fully replayable, which is what gives the sources
    their exact-resume contract. ``cycle=True`` serves an endless stream
    (offset o maps to row ``o % N``; offsets keep growing) for load tests.
    """

    def __init__(
        self,
        data,
        block_size: int = 1024,
        port: int = 0,
        cycle: bool = False,
        throttle_s: float = 0.0,
        host: str = "127.0.0.1",
    ):
        """``host`` is the bind interface — default loopback for tests;
        pass ``"0.0.0.0"`` (or a specific NIC address) to serve remote
        workers in a multi-host deployment."""
        self._throttle = throttle_s
        if isinstance(data, np.ndarray):
            self._arr: Optional[np.ndarray] = np.ascontiguousarray(
                data, np.float32
            )
            self._recs: Optional[List[Record]] = None
            self._n = self._arr.shape[0]
        else:
            self._arr = None
            self._recs = list(data)
            self._n = len(self._recs)
        self._block = block_size
        self._cycle = cycle
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(8)
        self._sock.settimeout(0.2)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fjt-net-accept", daemon=True
        )
        self._accept_thread.start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._accept_thread.join(timeout=2.0)
        for t in self._threads:
            t.join(timeout=2.0)

    # -- internals ---------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(
                target=self._serve_client, args=(conn,), daemon=True
            )
            t.start()
            # keep the handler list bounded across reconnect churn
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _serve_client(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            req = _recv_exact(conn, _REQ.size)
            if req is None:
                return
            magic, offset = _REQ.unpack(req)
            if magic != MAGIC:
                return
            while not self._stop.is_set():
                if not self._cycle and offset >= self._n:
                    conn.sendall(_HDR.pack(1) + bytes([KIND_EOS]))
                    return
                n = min(self._block, (self._n - offset) if not self._cycle
                        else self._block)
                if self._arr is not None:
                    rows = (
                        self._arr[offset % self._n : offset % self._n + n]
                        if not self._cycle
                        else np.take(
                            self._arr,
                            np.arange(offset, offset + n) % self._n,
                            axis=0,
                        )
                    )
                    body = (
                        _BLOCK_HDR.pack(
                            KIND_BLOCK, offset, rows.shape[0], rows.shape[1]
                        )
                        + rows.tobytes()
                    )
                else:
                    recs = [
                        self._recs[(offset + i) % self._n] for i in range(n)
                    ]
                    payload = "\n".join(json.dumps(r) for r in recs).encode()
                    body = _REC_HDR.pack(KIND_RECORDS, offset, n) + payload
                conn.sendall(_HDR.pack(len(body)) + body)  # TCP backpressure
                offset += n
                if self._throttle:
                    # paced mode: tests use this to pin down "server died
                    # mid-stream" states independent of socket buffering
                    time.sleep(self._throttle)
        except (OSError, BrokenPipeError, ConnectionResetError):
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass


def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


class _FrameClient:
    """Shared reconnect-at-offset frame reader for both source flavors."""

    def __init__(self, host: str, port: int, poll_timeout: float = 0.002):
        self._addr = (host, port)
        self._sock: Optional[socket.socket] = None
        self._buf = bytearray()
        self._poll_timeout = poll_timeout
        # adaptive idle backoff: each consecutive empty read doubles the
        # socket timeout (up to _IDLE_TIMEOUT_MAX); any data resets it.
        # Callers that spin on None therefore cost ~20 wakeups/s against
        # an idle or dead server instead of ~500/s at the base timeout.
        self._idle_timeout = poll_timeout
        self._last_retry = 0.0
        self.next_offset = 0
        self.eos = False

    _IDLE_TIMEOUT_MAX = 0.05

    def seek(self, offset: int) -> None:
        self.next_offset = int(offset)
        self.eos = False
        self._disconnect()

    def close(self) -> None:
        self._disconnect()

    def _disconnect(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self._buf.clear()

    def _connect(self) -> bool:
        # throttle reconnect attempts so a dead server doesn't spin-burn
        now = time.monotonic()
        if now - self._last_retry < 0.05:
            return False
        self._last_retry = now
        try:
            s = socket.create_connection(self._addr, timeout=1.0)
            s.settimeout(self._idle_timeout)
            s.sendall(_REQ.pack(MAGIC, self.next_offset))
            self._sock = s
            return True
        except OSError:
            return False

    def read_frame(self) -> Optional[bytes]:
        """One frame body, or None when none is currently available.
        Transparently reconnects (from ``next_offset``) on a dropped
        connection — exactly-once across server restarts."""
        if self.eos:
            return None
        if self._sock is None and not self._connect():
            return None
        try:
            while True:
                if len(self._buf) >= _HDR.size:
                    (body_len,) = _HDR.unpack_from(self._buf, 0)
                    if len(self._buf) >= _HDR.size + body_len:
                        body = bytes(
                            self._buf[_HDR.size : _HDR.size + body_len]
                        )
                        del self._buf[: _HDR.size + body_len]
                        if self._idle_timeout != self._poll_timeout:
                            self._idle_timeout = self._poll_timeout
                            self._sock.settimeout(self._idle_timeout)
                        return body
                chunk = self._sock.recv(1 << 20)
                if not chunk:
                    # server went away mid-stream: one event per lost
                    # connection (reconnect ATTEMPTS are throttled spin
                    # and would flood the ring)
                    flight.record(
                        "net_disconnect", peer=f"{self._addr[0]}:"
                        f"{self._addr[1]}", next_offset=self.next_offset,
                    )
                    self._disconnect()
                    return None
                self._buf.extend(chunk)
        except socket.timeout:
            self._idle_timeout = min(
                self._idle_timeout * 2, self._IDLE_TIMEOUT_MAX
            )
            try:
                self._sock.settimeout(self._idle_timeout)
            except OSError:
                pass
            return None
        except OSError:
            flight.record(
                "net_disconnect",
                peer=f"{self._addr[0]}:{self._addr[1]}",
                next_offset=self.next_offset,
            )
            self._disconnect()
            return None


class TcpBlockSource(BlockSource):
    """Network block feed for :class:`BlockPipeline` (config 2's stream).

    ``poll`` returns ``(first_offset, [n, F] f32)`` blocks; ``seek`` makes
    the next fetch start at that record offset (the checkpoint-resume
    hook). The f32 payload is decoded zero-copy via ``np.frombuffer``.
    """

    def __init__(self, host: str, port: int, arity: Optional[int] = None):
        self._client = _FrameClient(host, port)
        self._arity = arity

    def poll(self) -> Optional[Tuple[int, np.ndarray]]:
        body = self._client.read_frame()
        if body is None:
            return None
        kind = body[0]
        if kind == KIND_EOS:
            self._client.eos = True
            return None
        if kind != KIND_BLOCK:
            # a mismatched stream must fail loudly, not complete cleanly
            # with zero records scored
            raise ValueError(
                "stream carries JSON record frames — use TcpRecordSource"
                if kind == KIND_RECORDS
                else f"unknown frame kind {kind}"
            )
        _, first, rows, cols = _BLOCK_HDR.unpack_from(body, 0)
        if self._arity is not None and cols != self._arity:
            raise ValueError(
                f"stream arity {cols} != model arity {self._arity}"
            )
        blk = np.frombuffer(
            body, np.float32, count=rows * cols, offset=_BLOCK_HDR.size
        ).reshape(rows, cols)
        self._client.next_offset = first + rows
        return first, blk

    def seek(self, offset: int) -> None:
        self._client.seek(offset)

    @property
    def exhausted(self) -> bool:
        return self._client.eos

    def close(self) -> None:
        self._client.close()


class TcpRecordSource(Source):
    """Network dict-record feed for the record-object engine Pipeline."""

    def __init__(self, host: str, port: int):
        self._client = _FrameClient(host, port)

    def poll(self, max_n: int) -> Polled:
        out: Polled = []
        while len(out) < max_n:
            body = self._client.read_frame()
            if body is None:
                break
            kind = body[0]
            if kind == KIND_EOS:
                self._client.eos = True
                break
            if kind != KIND_RECORDS:
                raise ValueError(
                    "stream carries f32 block frames — use TcpBlockSource"
                    if kind == KIND_BLOCK
                    else f"unknown frame kind {kind}"
                )
            _, first, count = _REC_HDR.unpack_from(body, 0)
            lines = body[_REC_HDR.size :].decode().split("\n")
            for i, line in enumerate(lines[:count]):
                out.append((consumed_offset(first + i), json.loads(line)))
            self._client.next_offset = first + count
        return out

    def seek(self, offset: int) -> None:
        # checkpointed offset k == index of the next record: one domain,
        # forwarded verbatim (module docstring / consumed_offset)
        self._client.seek(offset)

    @property
    def exhausted(self) -> bool:
        return self._client.eos

    def close(self) -> None:
        self._client.close()
