"""ctypes binding for the C++ data plane (_native/fjt_native.cpp).

Builds the shared library on first use with the baked-in ``g++``
(pybind11 isn't in the image, hence the C-plain ABI + ctypes). The source
ships inside the package (``flink_jpmml_tpu/_native/``) so a pip install
carries it; the built ``.so`` is cached under ``$FJT_NATIVE_CACHE``
(default ``~/.cache/flink_jpmml_tpu/native``) — site-packages may be
read-only — and rebuilt whenever the source is newer. Falls back cleanly:
callers check :func:`available` and use the pure-Python
:class:`flink_jpmml_tpu.runtime.queues.BoundedQueue` otherwise — same
semantics, lower throughput.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import pathlib
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_SRC = (
    pathlib.Path(__file__).resolve().parent.parent
    / "_native"
    / "fjt_native.cpp"
)


def _lib_path() -> pathlib.Path:
    """Cache name carries the source content hash: the shared ~/.cache
    survives package upgrades/downgrades across venvs, and mtimes are
    unreliable for wheels (often pinned to a fixed epoch) — a stale
    ABI loaded through ctypes would corrupt memory, not error."""
    d = os.environ.get("FJT_NATIVE_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "flink_jpmml_tpu", "native"
    )
    try:
        digest = hashlib.sha256(_SRC.read_bytes()).hexdigest()[:12]
    except OSError:
        digest = "nosrc"
    return pathlib.Path(d) / f"libfjt_native-{digest}.so"


_LIB = _lib_path()

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _build() -> Optional[str]:
    """Compile the shared library; returns an error string or None."""
    _LIB.parent.mkdir(parents=True, exist_ok=True)
    # build to a per-process temp name then atomically replace, so
    # concurrent workers racing the first build never load a half-written
    # library
    tmp = _LIB.with_suffix(f".tmp-{os.getpid()}.so")
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        "-o", str(tmp), str(_SRC), "-lpthread",
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"g++ invocation failed: {e}"
    if proc.returncode != 0:
        return f"g++ failed:\n{proc.stderr[-2000:]}"
    try:
        os.replace(tmp, _LIB)
    except OSError as e:
        return f"cache install failed: {e}"
    return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        if not _SRC.exists():
            _build_error = f"source missing: {_SRC}"
            return None
        # hash-keyed cache name: existence IS validity (see _lib_path)
        if not _LIB.exists():
            err = _build()
            if err is not None:
                _build_error = err
                return None
        try:
            lib = ctypes.CDLL(str(_LIB))
        except OSError as e:
            _build_error = str(e)
            return None
        lib.fjt_ring_create.restype = ctypes.c_void_p
        lib.fjt_ring_create.argtypes = [ctypes.c_uint32, ctypes.c_uint32]
        lib.fjt_ring_destroy.argtypes = [ctypes.c_void_p]
        lib.fjt_ring_close.argtypes = [ctypes.c_void_p]
        lib.fjt_ring_size.restype = ctypes.c_uint32
        lib.fjt_ring_size.argtypes = [ctypes.c_void_p]
        lib.fjt_ring_closed.restype = ctypes.c_int
        lib.fjt_ring_closed.argtypes = [ctypes.c_void_p]
        lib.fjt_ring_push_block.restype = ctypes.c_uint32
        lib.fjt_ring_push_block.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_uint64,
            ctypes.c_uint32,
            ctypes.c_int64,
        ]
        lib.fjt_ring_drain.restype = ctypes.c_uint32
        lib.fjt_ring_drain.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint32,
            ctypes.c_int64,
            ctypes.c_int64,  # idle_timeout_us (-1 = wait indefinitely)
        ]
        for name, code_t in (
            ("fjt_bucketize_u8", ctypes.c_uint8),
            ("fjt_bucketize_u16", ctypes.c_uint16),
        ):
            fn = getattr(lib, name)
            fn.restype = None
            fn.argtypes = [
                ctypes.POINTER(ctypes.c_float),   # X
                ctypes.c_uint64,                  # n
                ctypes.c_uint32,                  # f
                ctypes.POINTER(ctypes.c_float),   # cuts (ragged, concat)
                ctypes.POINTER(ctypes.c_int32),   # offs [f+1]
                ctypes.POINTER(ctypes.c_float),   # repl
                ctypes.POINTER(ctypes.c_uint8),   # has_repl
                ctypes.POINTER(ctypes.c_uint8),   # mask (nullable)
                ctypes.POINTER(code_t),           # out
                ctypes.c_uint32,                  # n_threads
            ]
        for name, code_t in (
            ("fjt_bucketize_pow2_u8", ctypes.c_uint8),
            ("fjt_bucketize_pow2_u16", ctypes.c_uint16),
        ):
            fn = getattr(lib, name)
            fn.restype = None
            fn.argtypes = [
                ctypes.POINTER(ctypes.c_float),   # X
                ctypes.c_uint64,                  # n
                ctypes.c_uint32,                  # f
                ctypes.POINTER(ctypes.c_float),   # cuts [f*L], +inf padded
                ctypes.c_uint32,                  # L (power of two)
                ctypes.POINTER(ctypes.c_float),   # repl
                ctypes.POINTER(ctypes.c_uint8),   # has_repl
                ctypes.POINTER(ctypes.c_uint8),   # mask (nullable)
                ctypes.POINTER(code_t),           # out
                ctypes.c_uint32,                  # n_threads
            ]
        lib.fjt_kafka_encode_fixed.restype = ctypes.c_int64
        lib.fjt_kafka_encode_fixed.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),   # values [n, value_len]
            ctypes.c_int64,                   # n
            ctypes.c_int64,                   # value_len
            ctypes.c_int64,                   # base_offset
            ctypes.POINTER(ctypes.c_uint8),   # out buffer
            ctypes.c_int64,                   # out capacity (bytes)
        ]
        lib.fjt_kafka_decode_fixed.restype = ctypes.c_int64
        lib.fjt_kafka_decode_fixed.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),   # record-set bytes
            ctypes.c_int64,                   # len
            ctypes.c_int64,                   # value_len
            ctypes.POINTER(ctypes.c_uint8),   # out values [cap, value_len]
            ctypes.c_int64,                   # out capacity (records)
            ctypes.POINTER(ctypes.c_int64),   # out offsets [cap]
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def build_error() -> Optional[str]:
    _load()
    return _build_error


class NativeRing:
    """Bounded MPSC ring of fixed-arity float32 records (the C++ batcher).

    ``push_block`` takes a contiguous ``[n, arity]`` float32 array with
    consecutive source offsets; ``drain`` fills a preallocated batch buffer
    fill-or-deadline and returns (records_view, offsets_view) — zero-copy
    numpy views over reused buffers, valid until the next drain.
    """

    def __init__(self, capacity: int, arity: int, batch_size: int):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native data plane unavailable: {_build_error}")
        self._lib = lib
        self._arity = arity
        self._handle = lib.fjt_ring_create(capacity, arity)
        if not self._handle:
            raise MemoryError("fjt_ring_create failed")
        self._batch = np.zeros((batch_size, arity), np.float32)
        self._offsets = np.zeros((batch_size,), np.uint64)

    def push_block(
        self, block: np.ndarray, first_offset: int, timeout_us: int = -1
    ) -> int:
        block = np.ascontiguousarray(block, np.float32)
        if block.ndim != 2 or block.shape[1] != self._arity:
            raise ValueError(
                f"block shape {block.shape} != [n, {self._arity}]"
            )
        return self._lib.fjt_ring_push_block(
            self._handle,
            block.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            first_offset,
            block.shape[0],
            timeout_us,
        )

    def drain(
        self, deadline_us: int, idle_timeout_us: int = -1
    ) -> Tuple[np.ndarray, np.ndarray]:
        """``idle_timeout_us >= 0`` bounds the wait for the *first*
        record — an empty return on an open ring then means "idle", so
        the consumer can run control-plane work (dynamic serving's
        Add/Del polling) instead of parking forever."""
        n = self._lib.fjt_ring_drain(
            self._handle,
            self._batch.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            self._offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            self._batch.shape[0],
            deadline_us,
            idle_timeout_us,
        )
        return self._batch[:n], self._offsets[:n]

    def close(self) -> None:
        self._lib.fjt_ring_close(self._handle)

    @property
    def closed(self) -> bool:
        return bool(self._lib.fjt_ring_closed(self._handle))

    def __len__(self) -> int:
        return self._lib.fjt_ring_size(self._handle)

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.fjt_ring_destroy(handle)
            self._handle = None


def kafka_encode_fixed(
    values: np.ndarray, base_offset: int
) -> Optional[bytes]:
    """Encode a contiguous ``[n, value_len]`` uint8 array as one
    magic-v2 record batch — byte-identical to the Python
    ``encode_record_batch`` (null keys, no headers, timestamp 0).
    → batch bytes, or ``None`` when the native library is unavailable.
    """
    lib = _load()
    if lib is None:
        return None
    values = np.ascontiguousarray(values, np.uint8)
    n, value_len = values.shape
    cap = 61 + n * (value_len + 26)  # generous per-record framing bound
    out = np.empty((cap,), np.uint8)
    rc = lib.fjt_kafka_encode_fixed(
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n,
        value_len,
        base_offset,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        cap,
    )
    if rc < 0:
        return None
    return out[: int(rc)].tobytes()


def kafka_decode_fixed(
    buf: bytes, value_len: int
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Decode magic-v2 record batches whose values are all ``value_len``
    bytes (the tabular-stream contract) at C speed.

    → ``(offsets int64 [n], values uint8 [n, value_len])``, or ``None``
    when the native library is unavailable OR the record set is not
    fixed-length (caller falls back to the Python decoder). Raises
    ``ValueError`` on CRC mismatch / bad magic / malformed framing with
    the same messages as ``decode_record_batches``.
    """
    lib = _load()
    if lib is None:
        return None
    # a record costs at least 6 framing bytes + the value, so this bounds
    # the record count from the buffer size alone
    cap = len(buf) // (value_len + 6) + 1
    out = np.empty((cap, value_len), np.uint8)
    offs = np.empty((cap,), np.int64)
    src = np.frombuffer(buf, np.uint8)  # zero-copy, read-only view
    rc = lib.fjt_kafka_decode_fixed(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        len(buf),
        value_len,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        cap,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if rc == -3:
        return None  # not fixed-length: the general Python path decides
    if rc == -1:
        raise ValueError("record batch CRC32C mismatch")
    if rc == -2:
        raise ValueError("unsupported record-batch magic")
    if rc < 0:
        raise ValueError(f"malformed record batch (native rc={rc})")
    n = int(rc)
    return offs[:n].copy(), out[:n].copy()


def bucketize(
    X: np.ndarray,
    cuts_flat: np.ndarray,
    offs: np.ndarray,
    repl: np.ndarray,
    has_repl: np.ndarray,
    out_dtype,
    mask: Optional[np.ndarray] = None,
    n_threads: int = 0,
) -> Optional[np.ndarray]:
    """Ragged-table rank-wire featurization (branchless per-feature
    lower_bound). The skew-robust fallback: memory and per-feature
    search depth follow each feature's OWN cut count, so one long table
    doesn't tax the others (cf. :func:`bucketize_pow2`). Returns the
    [n, f] code array, or None when the native library is unavailable
    (caller falls back to numpy searchsorted — identical semantics).
    """
    lib = _load()
    if lib is None:
        return None
    X = np.ascontiguousarray(X, np.float32)
    n, f = X.shape
    out = np.empty((n, f), out_dtype)
    fn = lib.fjt_bucketize_u8 if out.itemsize == 1 else lib.fjt_bucketize_u16
    code_t = ctypes.c_uint8 if out.itemsize == 1 else ctypes.c_uint16
    if mask is not None:
        mask = np.ascontiguousarray(mask, np.uint8)
        mask_ptr = mask.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    else:
        mask_ptr = ctypes.POINTER(ctypes.c_uint8)()
    fn(
        X.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n,
        f,
        cuts_flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        repl.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        has_repl.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        mask_ptr,
        out.ctypes.data_as(ctypes.POINTER(code_t)),
        n_threads,
    )
    return out


def bucketize_pow2(
    X: np.ndarray,
    cuts_padded: np.ndarray,
    L: int,
    repl: np.ndarray,
    has_repl: np.ndarray,
    out_dtype,
    mask: Optional[np.ndarray] = None,
    n_threads: int = 0,
) -> Optional[np.ndarray]:
    """Lockstep rank-wire featurization over +inf-padded [f, L] tables
    (L a power of two) — ~1.3-2x the ragged path on one core when cut
    counts are balanced, because the per-feature binary-search loads
    pipeline instead of serializing. Every feature pays L-depth rounds
    and L-width memory, so heavily skewed tables belong on
    :func:`bucketize` instead (QuantizedWire.encode picks). Same results
    as :func:`bucketize`; None when the library is missing.
    """
    lib = _load()
    if lib is None:
        return None
    X = np.ascontiguousarray(X, np.float32)
    n, f = X.shape
    out = np.empty((n, f), out_dtype)
    fn = (
        lib.fjt_bucketize_pow2_u8
        if out.itemsize == 1
        else lib.fjt_bucketize_pow2_u16
    )
    code_t = ctypes.c_uint8 if out.itemsize == 1 else ctypes.c_uint16
    if mask is not None:
        mask = np.ascontiguousarray(mask, np.uint8)
        mask_ptr = mask.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    else:
        mask_ptr = ctypes.POINTER(ctypes.c_uint8)()
    fn(
        X.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n,
        f,
        cuts_padded.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        L,
        repl.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        has_repl.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        mask_ptr,
        out.ctypes.data_as(ctypes.POINTER(code_t)),
        n_threads,
    )
    return out
