"""The streaming micro-batch engine: source → batcher → device → sink.

This replaces the reference's Flink operator runtime (SURVEY.md §4.1): where
Flink called ``flatMap(event)`` per record on a CPU, we run a two-stage host
pipeline per worker process:

- **ingest thread**: polls the source, stamps each record with (offset,
  enqueue-time), and puts it on a bounded queue (backpressure point).
- **scoring loop**: drains fill-or-deadline micro-batches, converts them to
  ``(X, M)`` tensors, dispatches the jitted scorer **asynchronously** (JAX
  dispatch returns before the TPU finishes), and keeps a small in-flight
  window so host prep of batch N+1 overlaps device execution of batch N.
  Results are decoded and sunk in order; the source offset is committed only
  after the batch is sunk (at-least-once on restart, like the reference's
  Flink checkpoint semantics).

Metrics (BASELINE §metrics): records/sec, p50/p99 per-record latency
(enqueue→sink), batch fill ratio.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from flink_jpmml_tpu.compile import prepare
from flink_jpmml_tpu.compile.compiler import CompiledModel
from flink_jpmml_tpu.models.prediction import Prediction
from flink_jpmml_tpu.obs import freshness as fresh_mod
from flink_jpmml_tpu.obs import pressure as pressure_mod
from flink_jpmml_tpu.obs import recorder as flight
from flink_jpmml_tpu.obs import trace as trace_mod
from flink_jpmml_tpu.runtime import devfault
from flink_jpmml_tpu.runtime import faults
from flink_jpmml_tpu.runtime import prefetch as prefetch_mod
from flink_jpmml_tpu.runtime.checkpoint import (
    CheckpointManager,
    CheckpointPolicy,
)
from flink_jpmml_tpu.runtime.dlq import (
    REASON_CRASH_LOOP,
    REASON_SCORE,
    CrashFingerprint,
    PoisonIsolationOverflow,
    dlq_for_checkpoint,
    env_count,
    serialize_record,
)
from flink_jpmml_tpu.runtime.queues import BoundedQueue, Closed
from flink_jpmml_tpu.runtime import state as state_mod
from flink_jpmml_tpu.runtime.sinks import Sink
from flink_jpmml_tpu.runtime.sources import Source, batch_event_range
from flink_jpmml_tpu.utils.config import RuntimeConfig
from flink_jpmml_tpu.utils.exceptions import InputValidationException
from flink_jpmml_tpu.utils.metrics import MetricsRegistry
from flink_jpmml_tpu.utils.profiling import StageTimer


@dataclass
class _Stamped:
    offset: int
    record: Any
    t_enq: float


class Scorer:
    """Strategy interface: turn a micro-batch of records into outputs.

    ``submit`` must dispatch device work without blocking on it; ``finish``
    blocks (device→host transfer + decode). Splitting the two lets the engine
    overlap host batch-prep with device execution.
    """

    def submit(self, records: Sequence[Any]) -> Any:
        raise NotImplementedError

    def finish(self, ticket: Any) -> List[Any]:
        raise NotImplementedError

    def state(self) -> dict:
        return {}

    def restore(self, state: dict) -> None:
        pass


ExtractFn = Callable[[Sequence[Any]], Tuple[np.ndarray, np.ndarray]]
EmitFn = Callable[[Sequence[Any], List[Prediction]], List[Any]]


class StaticScorer(Scorer):
    """Single fixed model (the reference's static ``evaluate``, C3).

    ``extract`` turns raw records into an ``(X, M)`` pair — defaults to
    dict-records via the model's field space; pass a custom one for event
    objects or pre-stacked vectors. ``emit`` shapes the sink items — defaults
    to bare ``Prediction``s; the quick-evaluate API uses
    ``(prediction, record)`` pairs like the reference.
    """

    def __init__(
        self,
        model: CompiledModel,
        extract: Optional[ExtractFn] = None,
        emit: Optional[EmitFn] = None,
        replace_nan: Optional[float] = None,
        use_quantized: bool = True,
        state=None,
    ):
        self._model = model
        self._replace_nan = replace_nan
        self._extract = extract or self._extract_records
        self._emit = emit or (lambda recs, preds: list(preds))
        # rank-wire fast path (qtrees.py): ships uint8 threshold ranks
        # instead of f32+mask when the model is an eligible tree ensemble.
        # ShardedModel (parallel/sharding.py) has no quantized path; it
        # scores through the same f32 predict contract.
        probe = getattr(model, "quantized_scorer", None)
        self._q = probe() if (use_quantized and probe is not None) else None
        # keyed session state (runtime/state.py): a StateSpec builds a
        # private table (pass a KeyedStateTable constructed with the
        # pipeline's MetricsRegistry to surface its state_* family);
        # state rides the rank-wire dispatch, so the f32 fallback
        # contract cannot carry it
        if isinstance(state, state_mod.StateSpec):
            state = state_mod.KeyedStateTable(state)
        if state is not None and self._q is None:
            raise InputValidationException(
                "keyed state needs the rank-wire scorer (this model "
                "has no quantized path)"
            )
        self.state_table = state
        # the engine passes per-record source offsets (the state decay
        # clock + exactly-once replay guard) only to scorers that ask
        self.accepts_offsets = state is not None
        # which scoring backend this scorer engages (surfaced in the
        # pipeline's metrics as scorer_backend_*)
        self.backend = (
            f"rank_wire_{self._q.backend}" if self._q is not None else "f32"
        )

    def _extract_records(self, records: Sequence[Any]):
        first = records[0]
        if isinstance(first, dict):
            return prepare.from_records(self._model.field_space, records)
        arr = np.asarray(records, np.float32)
        return prepare.from_dense(
            self._model.field_space, arr, self._replace_nan
        )

    def submit(self, records: Sequence[Any], offsets=None):
        from flink_jpmml_tpu.runtime.block import _prefetch_host

        X, M = self._extract(records)
        n = X.shape[0]
        if self._q is not None:
            table = self.state_table
            if table is not None and not table.bypassed:
                return self._submit_state(table, records, X, M, offsets)
            Xq = self._q.wire.encode(X, M)
            # predict_wire owns batch-size alignment (padding / chunking)
            out = self._q.predict_wire(Xq)  # async dispatch
            _prefetch_host(out)  # D2H queued now; finish() finds it local
            return ("q", out, records, n)
        if self._model.batch_size is not None:
            X, M, _ = prepare.pad_batch(X, M, self._model.batch_size)
        out = self._model.predict(X, M)  # async dispatch
        _prefetch_host(out)
        return ("f", out, records, n)

    def _submit_state(self, table, records, X, M, offsets):
        """State-armed dispatch: host slot routing + ONE fused
        lookup→score→update launch (cf. pipeline.dispatch_quantized's
        block-path twin). The updated state buffer commits immediately
        — the next dispatch chains on it device-side."""
        from flink_jpmml_tpu.runtime.block import _prefetch_host

        n = X.shape[0]
        khash = table.hash_records(records)
        offs = (
            np.asarray(offsets, np.int64) if offsets is not None
            else None
        )
        first = (
            int(offs[0]) if offs is not None and offs.size
            else table.applied_hi
        )
        table.maybe_renorm(first)
        slots, reset, rel, w = table.assign_slots(khash, offs)
        Xq = self._q.wire.encode(X, M)
        Xq, K = self._q.pad_wire(Xq)
        pad = Xq.shape[0] - n
        if pad > 0:
            # alignment rows ride the scratch slot with zero weight
            slots = np.concatenate(
                [slots, np.full(pad, table.scratch, np.int32)]
            )
            reset = np.concatenate([reset, np.zeros(pad, bool)])
            rel = np.concatenate([rel, np.zeros(pad, np.float32)])
            w = np.concatenate([w, np.zeros(pad, np.float32)])
        out, derived, S2 = self._q.predict_padded_state(
            Xq, K, table, slots, rel, w, reset
        )
        table.commit(S2)
        _prefetch_host((out, derived))
        return ("q", (out, derived), records, n)

    def finish(self, ticket) -> List[Any]:
        kind, out, records, n = ticket
        out, _derived = state_mod.split_output(out)
        if kind == "q":
            preds = self._q.decode(out, n)  # blocks on device
        else:
            preds = self._model.decode(out, n)  # blocks on device
        return self._emit(records, preds)

    def state(self) -> dict:
        if self.state_table is None:
            return {}
        try:
            # inline payload (small tables); beyond the inline cap the
            # record path degrades to stateless restore (documented)
            return {"keyed_state": self.state_table.to_payload()}
        except InputValidationException:
            return {}

    def restore(self, state: dict) -> None:
        payload = state.get("keyed_state")
        if payload and self.state_table is not None:
            self.state_table.from_payload(payload)


class Pipeline:
    """One worker's streaming loop. Thread-safe start/stop; join() drains."""

    def __init__(
        self,
        source: Source,
        scorer: Scorer,
        sink: Sink,
        config: Optional[RuntimeConfig] = None,
        metrics: Optional[MetricsRegistry] = None,
        checkpoint: Optional[CheckpointManager] = None,
        in_flight: int = 2,
        dlq=None,
        prefetch: Optional[bool] = None,
        tenant: Optional[str] = None,
    ):
        # ``tenant`` labels this pipeline's delivered records for the
        # multi-tenant zoo plane (serving/zoo.py): records_out stays
        # the unlabelled total, tenant_records{model=...} adds the
        # per-tenant axis the fjt-top --zoo panel ranks by
        self._tenant = tenant
        self._source = source
        self._scorer = scorer
        self._sink = sink
        self._config = config or RuntimeConfig()
        self.metrics = metrics or MetricsRegistry()
        # pipelined ingest (runtime/prefetch.py): prefetchable sources
        # (Kafka — network fetch + decode) poll on a sidecar thread and
        # hand decoded records across a bounded queue; cf. block.py
        self._source = prefetch_mod.maybe_wrap_records(
            self._source, metrics=self.metrics, enable=prefetch
        )
        backend = getattr(scorer, "backend", None)
        if backend:
            self.metrics.counter(f"scorer_backend_{backend}").inc()
        self._ckpt = CheckpointPolicy(
            checkpoint, self._config.checkpoint_interval_s,
            metrics=self.metrics,
        )
        # delivery-correctness plane (runtime/dlq.py): record-level
        # error isolation — a scoring exception bisects the micro-batch
        # and quarantines the offending record(s) instead of killing
        # the worker. Defaults to a DLQ beside the checkpoints; without
        # durable state the historical fail-fast behavior is unchanged.
        self._dlq = dlq if dlq is not None else dlq_for_checkpoint(
            checkpoint, metrics=self.metrics
        )
        ckpt_dir = getattr(checkpoint, "directory", None)
        self._fingerprint = (
            CrashFingerprint(ckpt_dir)
            if (ckpt_dir is not None and self._dlq is not None) else None
        )
        # device-fault recovery (runtime/devfault.py) arms on the same
        # terms as the block path: durable state wired (DLQ) or the
        # explicit FJT_FAILOVER opt-in — a bare pipeline keeps the
        # historical fail-fast (die, let the supervisor restart onto a
        # healthy device)
        self._devfault_armed = (
            self._dlq is not None or bool(os.environ.get("FJT_FAILOVER"))
        )
        self._dispatched_hi = 0
        self._replay_until = 0
        self._suspect_until: Optional[int] = None
        self._death_marker: Optional[dict] = None
        self._suspect_gauge = self.metrics.gauge("poison_suspect_mode")
        self._in_flight_max = max(1, in_flight)
        self._queue = BoundedQueue(self._config.batch.queue_capacity)
        self._stop = threading.Event()
        # run_until_exhausted sets this: the score loop then consumes the
        # whole queued backlog after close. A plain stop() leaves it False
        # — queued-but-uncommitted records are discarded (they replay from
        # the committed offset on restore), so stop() returns promptly
        # even under a flooding source instead of draining for minutes
        # and leaving a busy daemon thread behind at interpreter exit.
        self._drain_all = False
        self._ingest_thread: Optional[threading.Thread] = None
        self._score_thread: Optional[threading.Thread] = None
        self._committed_offset = 0
        self._error: Optional[BaseException] = None

    def _ckpt_state(self) -> dict:
        state = {
            "source_offset": self._committed_offset,
            # the at-least-once replay region's upper bound (offsets of
            # records handed to submit but not yet committed): restore
            # reads it for replay accounting + crash-loop suspect mode
            "inflight_hi": max(self._dispatched_hi, self._committed_offset),
            "scorer": self._scorer.state(),
        }
        # cf. BlockPipelineBase._ckpt_state: vector-resume sources embed
        # their per-partition cursor snapshot alongside the scalar
        snap = getattr(self._source, "checkpoint_state", None)
        if snap is not None:
            extra = snap(self._committed_offset)
            if extra is not None:
                state["source_state"] = extra
        return state

    # -- lifecycle ---------------------------------------------------------

    def restore(self) -> bool:
        """Resume from the latest checkpoint, if any (capability C7)."""
        state = self._ckpt.restore_latest()
        if state is None:
            # no snapshot yet: still count the restore — a poison
            # record in the first uncommitted window crash-loops at
            # offset 0 before any checkpoint lands (cf. block.py)
            self._init_poison_state({})
            return False
        off = int(state.get("source_offset", 0))
        sstate = state.get("source_state")
        rst = getattr(self._source, "restore_state", None)
        if sstate is not None and rst is not None:
            off = int(rst(sstate))
        else:
            self._source.seek(off)
        self._committed_offset = off
        self._scorer.restore(state.get("scorer", {}))
        self._init_poison_state(state)
        return True

    def _init_poison_state(self, state: dict) -> None:
        """Crash-loop fingerprinting (the block pipeline's protocol,
        record-path flavor): either the worker-local restore counter
        (crashes.json) or the supervisor's ``FJT_RESTART_STREAK``
        crossing ``FJT_POISON_RESTARTS`` resumes the checkpoint's
        in-flight range in suspect mode — one record per dispatch under
        persisted markers, so a process-killing record converges to a
        DLQ entry instead of an on_give_up outage."""
        self._replay_until = max(
            int(state.get("inflight_hi", 0)), self._committed_offset
        )
        if self._fingerprint is None:
            return
        committed = self._committed_offset
        count = self._fingerprint.note_restore(committed)
        streak = env_count("FJT_RESTART_STREAK", 0)
        # markers live in the RECORD-offset domain (stamp − 1): record
        # r is committed once committed ≥ r+1, so a marker is stale
        # exactly when hi ≤ committed — the first uncommitted record's
        # marker (hi == committed+1) must survive, it IS the suspect
        self._death_marker = self._fingerprint.read_marker()
        if (
            self._death_marker is not None
            and self._death_marker["hi"] <= committed
        ):
            self._death_marker = None
            self._fingerprint.clear_marker()
        jstore = trace_mod.store_for(self.metrics)
        if jstore is not None:
            jstore.hop(
                "restore", trace_mod.context_for(committed),
                first_off=committed, durable=True,
                restarts=max(count - 1, streak),
            )
        threshold = env_count("FJT_POISON_RESTARTS", 3)
        if max(count - 1, streak) >= threshold:
            hi = self._replay_until
            if hi <= committed:
                hi = committed + self._config.batch.size
            self._suspect_until = hi
            self._suspect_gauge.set(1.0)
            if jstore is not None:
                # see block.py: suspect mode → write-through journeys
                jstore.write_through = True
                jstore.hop(
                    "suspect_mode", trace_mod.context_for(committed),
                    first_off=committed, n=hi - committed, durable=True,
                    restarts=max(count - 1, streak),
                )
            flight.record(
                "poison_suspect_mode", lo=committed, hi=hi,
                restarts=max(count - 1, streak),
                marker=self._death_marker,
            )

    def start(self) -> "Pipeline":
        self._ingest_thread = threading.Thread(
            target=self._ingest_loop, name="fjt-ingest", daemon=True
        )
        self._score_thread = threading.Thread(
            target=self._score_loop, name="fjt-score", daemon=True
        )
        self._ingest_thread.start()
        self._score_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        stop_sidecar = getattr(self._source, "stop_prefetch", None)
        if stop_sidecar is not None:
            stop_sidecar()  # park the prefetch sidecar (cf. block.py)
        self._queue.close()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._ingest_thread:
            self._ingest_thread.join(timeout)
        if self._score_thread:
            self._score_thread.join(timeout)
        if self._error is not None:
            raise self._error

    def run_until_exhausted(self, timeout: float = 60.0) -> None:
        """Test/batch helper: process the whole (finite) source, then stop.

        Deterministic drain (no sleep windows): the ingest thread exits on
        its own once the source is exhausted and every record is enqueued;
        only then is the queue closed. ``BoundedQueue.drain`` keeps serving
        remaining items after close, so the score loop consumes everything
        in the queue, then its in-flight window, then exits — zero records
        can be lost regardless of how slow the scorer is.
        """
        self.start()
        deadline = time.monotonic() + timeout
        assert self._ingest_thread is not None
        while self._ingest_thread.is_alive() and self._error is None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            self._ingest_thread.join(timeout=min(remaining, 0.05))
        self._drain_all = True
        self._stop.set()
        self._queue.close()
        self.join(timeout=max(10.0, deadline - time.monotonic()))

    @property
    def committed_offset(self) -> int:
        return self._committed_offset

    # -- poison isolation (runtime/dlq.py) ---------------------------------

    @staticmethod
    def _record_off(s: "_Stamped") -> int:
        """A stamp's offset is the RESUME point — one past the record
        (sources emit ``(consumed_count, rec)``). Fault targeting and
        DLQ envelopes use the record's own offset, so ``offset=K``
        means the same record on this path as on the block path, and
        a score-quarantined record files under the same offset its
        decode-quarantined twin would."""
        return s.offset - 1

    def _score_seq(self, seq: List["_Stamped"]) -> List[Any]:
        """Synchronous submit+finish of a sub-batch (the isolation and
        device-recovery paths' dispatch primitive), with the fault
        hooks carrying the sub-range's record offsets — the device
        sites fire here too, so a persistent injected device fault
        keeps failing redispatches exactly like a real one."""
        faults.fire(
            "score_batch", offsets=[self._record_off(s) for s in seq]
        )
        # isolation/recovery replays score STATELESSLY: these records
        # may already be folded into the keyed state table (the failed
        # dispatch committed before the error surfaced) — a bypassed
        # redispatch cannot double-apply them (runtime/state.py)
        table = getattr(self._scorer, "state_table", None)
        ctx = table.bypass() if table is not None else (
            contextlib.nullcontext()
        )
        with ctx:
            faults.fire("device_dispatch")
            ticket = self._scorer.submit([s.record for s in seq])
            faults.fire("device_readback")
            return self._scorer.finish(ticket)

    def _roll_back_state(self) -> None:
        """A dispatch error with a donated/chained keyed-state buffer
        may have poisoned it (or committed a partial update): restore
        the last snapshot before recovery/isolation replays the range —
        bounded, counted loss (``state_rollbacks``); the replayed
        records then score statelessly via ``_score_seq``'s bypass."""
        table = getattr(self._scorer, "state_table", None)
        if table is not None and not table.bypassed:
            table.rollback()

    def _book_tenant(self, n: int) -> None:
        if self._tenant is not None:
            self.metrics.counter(
                f'tenant_records{{model="{self._tenant}"}}'
            ).inc(n)

    def _deliver_seq(self, seq, outputs) -> None:
        self._sink.emit(outputs)
        self.metrics.counter("records_out").inc(len(seq))
        self._book_tenant(len(seq))
        event_time_fn = getattr(self._source, "event_time_fn", None)
        if event_time_fn is not None:
            freshness = fresh_mod.freshness_for(self.metrics)
            tr = batch_event_range(
                [s.record for s in seq], event_time_fn
            )
            if tr is not None:
                # only DELIVERED records advance the watermark/staleness
                # books — quarantined ones never reach this path
                freshness.observe_batch(tr[0], tr[1])

    def _quarantine_stamped(
        self, s: "_Stamped", exc, state: dict,
        reason: str = REASON_SCORE, attempts: int = 1,
        original=None, parent_ctx=None,
    ) -> None:
        cap = env_count("FJT_DLQ_MAX_PER_BATCH", 32)
        if state["q"] >= cap:
            raise PoisonIsolationOverflow(
                state["q"], exc if exc is not None else original
            )
        state["q"] += 1
        off = self._record_off(s)
        # terminal journey hop + the envelope's trace context (the ids
        # fjt-dlq redrive stamps into the traceparent header)
        rctx = trace_mod.TraceContext(
            trace_mod.trace_id_for(off),
            parent_id=(
                None if parent_ctx is None else parent_ctx.span_id
            ),
        )
        jstore = trace_mod.store_for(self.metrics)
        if jstore is not None:
            jstore.terminal(
                "dlq", rctx, offset=off, reason=reason,
                attempts=attempts,
            )
        self._dlq.quarantine(
            serialize_record(s.record), offset=off,
            reason=reason, error=exc, attempts=attempts,
            trace_id=rctx.trace_id, span_id=rctx.span_id,
        )

    def _isolate(self, stamped: List["_Stamped"], error, ctx=None) -> None:
        """Bisection over one failed micro-batch: clean runs reach the
        sink in order, single failing records go to the DLQ, the whole
        range commits (a parked poison record never replays)."""
        jstore = trace_mod.store_for(self.metrics)
        if ctx is None and jstore is not None:
            ctx = trace_mod.context_for(self._record_off(stamped[0]))
        if jstore is not None:
            jstore.hop(
                "suspect_scan", ctx, self._record_off(stamped[0]),
                len(stamped), durable=True, persist=False,
                error=repr(error),
            )
        flight.record(
            "poison_isolation", first=stamped[0].offset,
            n=len(stamped), error=repr(error), persist=False,
            trace_id=None if ctx is None else ctx.trace_id,
        )
        self._suspect_gauge.set(1.0)
        state = {"q": 0}

        def scan(seq: List["_Stamped"]):
            if not seq:
                return
            try:
                outputs = self._score_seq(seq)
            except PoisonIsolationOverflow:
                raise
            except Exception as e:
                if devfault.classify(e) is not None:
                    # a sick device mid-bisection is not record
                    # poison: never quarantine clean records for it —
                    # escalate (cf. block.py's suspect scan)
                    raise
                if len(seq) == 1:
                    self._quarantine_stamped(
                        seq[0], e, state, original=error,
                        parent_ctx=ctx,
                    )
                    return
                mid = len(seq) // 2
                scan(seq[:mid])
                scan(seq[mid:])
                return
            self._deliver_seq(seq, outputs)
            if jstore is not None:
                # surviving runs get durable sink hops, like the block
                # path's emit_run — both hot paths render the same
                # documented isolation timeline
                jstore.hop(
                    "sink", ctx.child(), self._record_off(seq[0]),
                    len(seq), durable=True, isolated=True,
                )

        try:
            scan(stamped)
        finally:
            self._suspect_gauge.set(
                1.0 if self._suspect_until is not None else 0.0
            )
        self._committed_offset = stamped[-1].offset
        if state["q"]:
            flight.record(
                "poison_isolated", quarantined=state["q"],
                first=stamped[0].offset, n=len(stamped),
            )
        self._ckpt.maybe_save(self._ckpt_state)

    def _isolate_suspect(self, stamped: List["_Stamped"]) -> None:
        """Fingerprint-triggered suspect mode: one record per dispatch,
        marker written BEFORE each — a record that kills the process is
        pre-quarantined by the next incarnation without ever being
        dispatched again."""
        state = {"q": 0}
        jstore = trace_mod.store_for(self.metrics)
        for s in stamped:
            r = self._record_off(s)
            rctx = (
                trace_mod.context_for(r) if jstore is not None else None
            )
            dm = self._death_marker
            if (
                dm is not None
                and dm["lo"] == r and dm["hi"] == r + 1
            ):
                # the previous incarnation died dispatching exactly
                # this record: quarantine it unscored
                self._quarantine_stamped(
                    s, None, state, reason=REASON_CRASH_LOOP,
                    attempts=dm.get("attempts", 1), parent_ctx=rctx,
                )
                self._death_marker = None
                self._fingerprint.clear_marker()
                continue
            if self._fingerprint is not None:
                self._fingerprint.write_marker(r, r + 1, attempts=1)
                if jstore is not None:
                    # the marker's journey twin (see block.py): written
                    # BEFORE the dispatch so a kill leaves it behind
                    jstore.hop(
                        "suspect_dispatch", rctx, r, 1, durable=True,
                    )
            try:
                outputs = self._score_seq([s])
            except PoisonIsolationOverflow:
                raise
            except Exception as e:
                if devfault.classify(e) is not None:
                    raise  # device fault ≠ poison: never quarantine
                self._quarantine_stamped(s, e, state, parent_ctx=rctx)
                continue
            self._deliver_seq([s], outputs)
            if jstore is not None:
                jstore.hop(
                    "sink", rctx.child(), r, 1, durable=True,
                    isolated=True,
                )
        if self._fingerprint is not None:
            self._fingerprint.clear_marker()
        self._committed_offset = stamped[-1].offset
        self._ckpt.maybe_save(self._ckpt_state)

    def _recover_device(self, stamped: List["_Stamped"], error,
                        kind: str, ctx=None) -> None:
        """Record-path device-fault ladder (runtime/devfault.py):
        transient errors re-dispatch the micro-batch through the real
        submit/finish path under the shared full-jitter backoff; OOM
        drains in halves (batch-size bisection, never record
        quarantine); chip loss or an exhausted streak escalates to
        the supervisor. The record path has no fallback tier — its
        dynamic scorer already absorbs per-model failures — so
        persistence means restart, with every delivered run committed
        first (zero loss, bounded replay)."""
        from flink_jpmml_tpu.utils.retry import Backoff

        devfault.note(
            self.metrics, kind, first_off=self._record_off(stamped[0]),
            n=len(stamped), error=error,
        )
        if kind == devfault.KIND_LOST:
            flight.record(
                "device_lost_escalate",
                first=self._record_off(stamped[0]), n=len(stamped),
                error=repr(error),
            )
            raise error
        redispatched = self.metrics.counter("redispatch_records")
        retries = env_count("FJT_DEVICE_RETRIES", 2)
        bo = Backoff(
            "device", base_s=0.02, cap_s=0.5, max_attempts=retries
        )
        pending = list(stamped)
        # OOM dispatch-size cap: HALVES on every OOM failure (true
        # bisection — a device that only fits a quarter of the batch
        # must converge, not retry the same half forever); a proven
        # size sticks for the remainder
        size = len(pending)
        while pending and not bo.exhausted:
            bo.sleep()
            if kind == devfault.KIND_OOM and size > 1:
                size = max(1, size // 2)
                kind = devfault.KIND_ERROR  # halve once per OOM seen
                # a halving IS progress: the bisection must converge to
                # size 1 (≤ log2(batch) halvings) independent of the
                # transient-retry budget — only repeated failures at
                # the SAME size spend the streak
                bo.reset()
            seq = pending[:min(size, len(pending))]
            try:
                outputs = self._score_seq(seq)
            except Exception as e2:
                k2 = devfault.classify(e2)
                if k2 is None:
                    # the device fault cleared and record poison
                    # surfaced underneath: isolation's jurisdiction
                    if self._dlq is None:
                        raise
                    self._isolate(pending, e2, ctx=ctx)
                    return
                devfault.note(
                    self.metrics, k2,
                    first_off=self._record_off(seq[0]), n=len(seq),
                    error=e2,
                )
                if k2 == devfault.KIND_LOST:
                    flight.record(
                        "device_lost_escalate",
                        first=self._record_off(seq[0]), n=len(seq),
                        error=repr(e2),
                    )
                    raise e2
                kind = k2
                error = e2
                continue
            self._deliver_seq(seq, outputs)
            redispatched.inc(len(seq))
            self._committed_offset = seq[-1].offset
            self._ckpt.maybe_save(self._ckpt_state)
            pending = pending[size:]
            bo.reset()  # progress re-arms the schedule
        if pending:
            raise error  # exhausted: supervisor restart (streak ctx)
        flight.record(
            "device_redispatch",
            first=self._record_off(stamped[0]), n=len(stamped),
        )

    def _exit_suspect_mode(self) -> None:
        flight.record(
            "poison_suspect_exit", committed=self._committed_offset
        )
        self._suspect_until = None
        self._death_marker = None
        if self._fingerprint is not None:
            self._fingerprint.clear_marker()
        self._suspect_gauge.set(0.0)
        jstore = trace_mod.store_for(self.metrics)
        if jstore is not None:
            jstore.hop(
                "suspect_exit",
                trace_mod.context_for(self._committed_offset),
                first_off=self._committed_offset, durable=True,
            )
            jstore.write_through = bool(
                faults.active() or os.environ.get("FJT_JOURNEY_SYNC")
            )

    # -- internals ---------------------------------------------------------

    def _ingest_loop(self) -> None:
        records_in = self.metrics.counter("records_in")
        try:
            while not self._stop.is_set():
                polled = self._source.poll(1024)
                if not polled:
                    if self._source.exhausted:
                        return
                    time.sleep(0.001)
                    continue
                now = time.monotonic()
                for offset, rec in polled:
                    while not self._stop.is_set():
                        if self._queue.put(
                            _Stamped(offset, rec, now), timeout=0.1
                        ):
                            break
                records_in.inc(len(polled))
        except Closed:
            pass
        except BaseException as e:  # surface ingestion failures to join()
            self._error = e
            self._stop.set()

    def _score_loop(self) -> None:
        batch_cfg = self._config.batch
        records_out = self.metrics.counter("records_out")
        batches = self.metrics.counter("batches")
        fill = self.metrics.counter("batch_fill_records")
        # mergeable histogram (not a reservoir): fleet aggregation adds
        # bucket counts, so multi-worker p50/p99/p999 stay correct
        lat = self.metrics.histogram("record_latency_s")
        in_flight: List[Tuple[Any, List[_Stamped], Any]] = []
        # record-journey tracing (obs/trace.py): None unless armed
        jstore = trace_mod.store_for(self.metrics)

        stages = StageTimer(self.metrics)
        # event-time freshness + backpressure (obs/freshness.py,
        # obs/pressure.py): the tracker exists only when the source
        # opts in with an event_time_fn — eagerly creating it would
        # export a permanently-empty record_staleness_s family on
        # every pipeline (DynamicScorer gates the same way); the
        # pressure score always runs (the queue occupancy gauge is
        # this path's ring input)
        event_time_fn = getattr(self._source, "event_time_fn", None)
        freshness = (
            fresh_mod.freshness_for(self.metrics)
            if event_time_fn is not None else None
        )
        monitor = pressure_mod.pressure_for(self.metrics)
        queue_occ = self.metrics.gauge("ring_occupancy")

        replayed = self.metrics.counter("records_replayed")

        def _finish_one():
            ticket, stamped, jctx = in_flight.pop(0)
            try:
                # the finishing batch's context wraps readback + sink:
                # DynamicScorer.finish's span (and any exemplar those
                # stages capture) carries THIS journey's ids
                with trace_mod.use(jctx):
                    with stages.stage("readback"):
                        # readback-time device-fault hook: one global
                        # load + None check unarmed (cf. the block
                        # dispatcher's finish_oldest site)
                        faults.fire("device_readback")
                        outputs = self._scorer.finish(ticket)
            except PoisonIsolationOverflow:
                raise
            except Exception as e:
                # device-fault triage FIRST (runtime/devfault.py): a
                # sick device re-dispatches, record poison bisects —
                # entries ahead of this one already completed (FIFO),
                # so either path's commits stay monotone
                kind = devfault.classify(e)
                if kind is not None:
                    if not self._devfault_armed:
                        raise  # historical fail-fast: restart instead
                    self._roll_back_state()
                    self._recover_device(stamped, e, kind, ctx=jctx)
                    return
                if self._dlq is None:
                    raise
                self._roll_back_state()
                self._isolate(stamped, e, ctx=jctx)
                return
            with trace_mod.use(jctx):
                with stages.stage("sink"):
                    self._sink.emit(outputs)
            now = time.monotonic()
            if jstore is not None and jctx is not None:
                jstore.finish(
                    jctx, self._record_off(stamped[0]), len(stamped),
                    latency_s=now - stamped[0].t_enq,
                )
            # sample a handful of lanes, not all (host-side cost control)
            for s in stamped[:: max(1, len(stamped) // 8)]:
                lat.observe(now - s.t_enq)
            records_out.inc(len(stamped))
            self._book_tenant(len(stamped))
            if stamped[0].offset <= self._replay_until:
                replayed.inc(sum(
                    1 for s in stamped if s.offset <= self._replay_until
                ))
            self._committed_offset = stamped[-1].offset
            if freshness is not None and event_time_fn is not None:
                tr = batch_event_range(
                    [s.record for s in stamped], event_time_fn
                )
                if tr is not None:
                    freshness.observe_batch(tr[0], tr[1])
            self._ckpt.maybe_save(self._ckpt_state)
            if monitor is not None:
                monitor.maybe_tick()

        try:
            while True:
                if self._stop.is_set() and not self._drain_all:
                    break  # stop(): skip the uncommitted backlog
                try:
                    stamped = self._queue.drain(
                        batch_cfg.size, batch_cfg.deadline_us
                    )
                except Closed:
                    break
                if not stamped:
                    continue
                queue_occ.set(self._queue.occupancy())
                self._dispatched_hi = max(
                    self._dispatched_hi, stamped[-1].offset
                )
                if (
                    self._suspect_until is not None
                    and stamped[0].offset <= self._suspect_until
                ):
                    # crash-loop fingerprint: the replay region is
                    # scored one record per dispatch under persisted
                    # markers (drain the window first — suspect commits
                    # must not leapfrog in-flight batches)
                    while in_flight:
                        _finish_one()
                    self._isolate_suspect(stamped)
                    if self._committed_offset >= self._suspect_until:
                        self._exit_suspect_mode()
                    batches.inc()
                    fill.inc(len(stamped))
                    continue
                jctx = None
                if jstore is not None:
                    # one dispatch hop per micro-batch, keyed
                    # (first record offset, n) — the record-path twin
                    # of the block pipeline's batch journey
                    jctx = trace_mod.context_for(
                        self._record_off(stamped[0])
                    )
                    jstore.hop(
                        "dispatch", jctx,
                        self._record_off(stamped[0]), len(stamped),
                    )
                try:
                    with trace_mod.use(jctx):
                        with stages.stage("featurize_dispatch"):
                            faults.fire(
                                "score_batch",
                                offsets=[
                                    self._record_off(s) for s in stamped
                                ],
                            )
                            faults.fire("device_dispatch")
                            if getattr(
                                self._scorer, "accepts_offsets", False
                            ):
                                # keyed-state scorers get the record
                                # offsets: the state decay clock + the
                                # exactly-once replay guard
                                ticket = self._scorer.submit(
                                    [s.record for s in stamped],
                                    offsets=[
                                        self._record_off(s)
                                        for s in stamped
                                    ],
                                )
                            else:
                                ticket = self._scorer.submit(
                                    [s.record for s in stamped]
                                )
                except PoisonIsolationOverflow:
                    raise
                except Exception as e:
                    # the submit itself raised (featurize, routing, an
                    # injected poison, a launch-time device fault):
                    # older in-flight batches commit first, then this
                    # one recovers or isolates in place
                    kind = devfault.classify(e)
                    if kind is not None and not self._devfault_armed:
                        raise  # historical fail-fast: restart instead
                    if kind is None and self._dlq is None:
                        raise
                    while in_flight:
                        _finish_one()
                    self._roll_back_state()
                    if kind is not None:
                        self._recover_device(stamped, e, kind, ctx=jctx)
                    else:
                        self._isolate(stamped, e, ctx=jctx)
                    batches.inc()
                    fill.inc(len(stamped))
                    continue
                in_flight.append((ticket, stamped, jctx))
                batches.inc()
                fill.inc(len(stamped))
                if len(in_flight) >= self._in_flight_max:
                    _finish_one()
            while in_flight:
                _finish_one()
            self._ckpt.save_now(self._ckpt_state)
        except BaseException as e:
            self._error = e
            self._stop.set()
