"""Overlapped host→device dispatch: the depth-K in-flight window.

The round-5 bench showed the chip idle most of the time (5.8% MFU,
near-zero HBM utilization) because the streaming hot path staged,
dispatched, and blocked on every batch serially.  This module is the
fix's shared core: a bounded **in-flight window** through which every
scoring path (the block pipelines, the dynamic scorer, the bench) runs
its async device dispatches, so that while batch N executes on the
device, batch N+1 is drained from the ring, wire-encoded on the host,
and `jax.device_put` to a fresh staging buffer.  Results are fetched
only when the window is full (or on flush) — never per-batch on the
critical path.

Semantics:

- **FIFO.**  Completions happen strictly in launch order; the pipelines
  rely on this for in-order sink delivery and contiguous offset commits.
- **Bounded.**  At most ``depth`` dispatches are in flight after
  ``launch`` returns; launching into a full window blocks on the oldest
  dispatch (that wait is the *stall* — time the host spent gated on
  device completion — accounted in ``h2d_stall_s``).  With ``depth=2``
  (the default everywhere) staging is double-buffered: the entry being
  executed and the entry being staged each pin one device input buffer,
  and buffer donation (see :meth:`QuantizedScorer.predict_padded`)
  releases the executed entry's staging buffer to the device allocator
  at dispatch — steady-state input allocations stay bounded at the
  window depth instead of accumulating to fetch time.
- **Composable with ring deadlines.**  The dispatcher itself never
  waits for *work to arrive* — only for work it already launched — so
  the fill-or-deadline semantics of ``_PyRing``/``NativeRing``/
  ``BoundedQueue`` drains are untouched: an idle stream still hits its
  idle bound upstream, and the caller flushes the window explicitly.
- **Errors surface where the host blocks.**  An exception raised while
  dispatching propagates out of ``launch``; one raised by the device
  (or the fetch) propagates out of whichever call first waits on that
  entry (``launch`` on a full window, ``finish_oldest``, ``wait``,
  ``flush``, ``close``).  After an error the window keeps its remaining
  entries so a supervisor can still drain or abandon them.
- **Clean shutdown.**  ``close()`` flushes by default; ``abandon()``
  drops un-fetched work (the block pipelines' give-up path — records
  replay from the committed offset on restore, C7 at-least-once).

Metrics (into the shared :class:`MetricsRegistry`):

- ``h2d_stall_s``   — total host time blocked waiting on device work;
- ``dispatches``    — launches through the window;
- ``donation_hits`` — steady-state dispatches whose staged input buffer
  was donated to (consumed by) the jitted call, incremented by the
  callers that stage (see ``BlockPipelineBase._dispatch_bound``);
- ``inflight_depth`` gauge — current and high-water in-flight depth.

``profiling.overlap_stats`` turns these into the bench's
``overlap_efficiency`` / ``h2d_stall_ms`` artifact fields.
"""

from __future__ import annotations

import time
import weakref
from collections import deque
from typing import Any, Callable, Optional

import numpy as np

from flink_jpmml_tpu.obs import attr as attr_mod
from flink_jpmml_tpu.obs import drift as drift_mod
from flink_jpmml_tpu.obs import profiler as prof_mod
from flink_jpmml_tpu.obs import recorder as flight
from flink_jpmml_tpu.obs import spans
from flink_jpmml_tpu.runtime import faults
from flink_jpmml_tpu.utils.exceptions import FlinkJpmmlTpuError
from flink_jpmml_tpu.utils.metrics import MetricsRegistry


def _tree_leaves(out) -> list:
    """Pytree leaves of a dispatch result; [out] when jax is absent."""
    try:
        import jax

        return jax.tree_util.tree_leaves(out)
    except ImportError:  # pragma: no cover - jax is a hard dep in practice
        return [out]


def _prefetch_host(out) -> None:
    """Queue the D2H copies for a dispatched batch NOW, so the sink's
    later ``np.asarray`` finds the data already on the host.  Without
    this the copy is first issued inside the sink's blocking fetch, and
    on a high-RTT link (the tunneled chip: ~66 ms round trip) every
    batch pays the full round trip serially — measured 243k rec/s
    through the block loop vs ~1M with the prefetch."""
    for leaf in _tree_leaves(out):
        fn = getattr(leaf, "copy_to_host_async", None)
        if fn is not None:  # numpy fallback leaves are host-resident
            fn()


def _block_ready(out) -> None:
    """Wait for every device leaf of ``out`` (host leaves pass through).

    Uses the leaves' own ``block_until_ready`` so test doubles and
    numpy fallbacks compose; device-side errors raise here."""
    for leaf in _tree_leaves(out):
        fn = getattr(leaf, "block_until_ready", None)
        if fn is not None:
            fn()


def _is_ready(out) -> bool:
    """Non-blocking probe: would :func:`_block_ready` return instantly?

    Leaves without an ``is_ready`` (numpy, test doubles) count as
    ready — only a device leaf that reports itself in flight makes the
    whole value not-ready."""
    for leaf in _tree_leaves(out):
        fn = getattr(leaf, "is_ready", None)
        if fn is not None and not fn():
            return False
    return True


class DispatcherClosed(FlinkJpmmlTpuError):
    """launch() after close(): the window is shut down."""


# shape regexes whose inert donation warning is already silenced (see
# filter_donate_warning)
_DONATE_WARN_FILTERED: set = set()


def filter_donate_warning(shape_re: str) -> None:
    """One-shot, NARROW silencing of XLA's "donated buffers were not
    usable" warning for a wire batch shape that can never output-alias
    its scores (the uint8/uint16 rank wire, or the fused path's raw
    f32 [B, F] batch): the donation still frees the staging buffer to
    the device allocator at dispatch, so the warning is inert — but
    only for these shapes; an application's own actionable donation
    warnings stay visible. Shared by the block pipelines' uint-wire
    filter and the fused dispatch path (one mechanism, one message
    shape to keep in sync with XLA)."""
    if shape_re in _DONATE_WARN_FILTERED:
        return
    import warnings

    warnings.filterwarnings(
        "ignore",
        message=(
            r"Some donated buffers were not usable: ShapedArray\("
            + shape_re
        ),
    )
    _DONATE_WARN_FILTERED.add(shape_re)
    # once per shape, so a postmortem can see which donation warnings
    # this process decided were inert (and when)
    flight.record("donation_warning_filtered", shape_re=shape_re)


# per-registry (encode_s, h2d_bytes) counter pairs: resolving through
# the registry lock on every per-batch dispatch is avoidable hot-path
# work; weak keys let ephemeral bench registries die normally
_WIRE_COUNTERS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _wire_counters(metrics: MetricsRegistry):
    pair = _WIRE_COUNTERS.get(metrics)
    if pair is None:
        pair = (metrics.counter("encode_s"), metrics.counter("h2d_bytes"))
        _WIRE_COUNTERS[metrics] = pair
    return pair


def dispatch_quantized(
    q,
    X,
    M=None,
    *,
    donate: bool = False,
    metrics: Optional[MetricsRegistry] = None,
    donation_hits=None,
    state=None,
    state_keys=None,
    offsets=None,
):
    """Featurize + stage + async-dispatch one raw f32 batch through a
    :class:`~flink_jpmml_tpu.compile.qtrees.QuantizedScorer` — the ONE
    place the autotuned encode-placement decision (``q.encode_mode``)
    is enacted, shared by the block pipelines, the dynamic scorer, and
    every bench mode:

    - ``"host"`` (default, the byte-parity oracle): the C++ bucketizer
      rank-encodes on the host and the uint8/uint16 codes ship;
    - ``"fused"``: the raw f32 batch ships and the threshold-rank
      bucketize runs on-device as an XLA pre-stage traced into the
      scoring jit — one dispatch covers encode+pad+score.

    ``M`` is an optional explicit missing mask (the dynamic scorer's
    record path); the fused stage understands only the NaN convention,
    so the mask folds in as NaN before staging.

    Two counters land in ``metrics`` (→ the bench's ``encode_ms`` /
    ``h2d_bytes_per_record`` fields via ``profiling.wire_stats``):
    ``encode_s`` — host featurize+align time on the dispatch path (≈0
    when fused); ``h2d_bytes`` — bytes staged per dispatch (F uint
    codes per record on the host path, 4·F f32 on the fused path).

    ``donate=True`` stages via an explicit ``jax.device_put`` and
    donates the staging buffer to the jitted call (released to the
    device allocator at dispatch, not pinned until fetch);
    ``donation_hits`` counts dispatches whose buffer was actually
    consumed.

    ``state`` arms the keyed state stage (runtime/state.py): the batch
    additionally gathers/updates the table's device buffer inside the
    SAME dispatch and the return becomes ``(out, derived[B, 8])``
    (callers unwrap via ``state.is_state_output``). ``state_keys`` are
    precomputed uint32 key hashes (default: hash the table's key
    column of ``X``); ``offsets`` are the records' ring offsets —
    the decay clock and the exactly-once replay guard. Unarmed cost is
    one ``is None`` check."""
    enc, h2d = (
        _wire_counters(metrics) if metrics is not None else (None, None)
    )
    # data-drift profiling (obs/drift.py) on the RAW batch, before any
    # encode touches it: None + one env lookup when FJT_DRIFT_SAMPLE is
    # unset (the pinned zero-records contract); rate-limited + overhead-
    # budgeted when armed. Outside the encode timing window below so
    # encode_s / the encode stage ledger stay honest.
    dplane = drift_mod.plane_for(metrics)
    if dplane is not None:
        dplane.record_features(q, X, M)
    t0 = time.monotonic()
    fused = getattr(q, "encode_mode", "host") == "fused" and q.supports_fused
    if fused:
        owned = False  # does X already sit in a buffer only we hold?
        if M is not None and np.asarray(M).any():
            X = np.where(M, np.nan, np.asarray(X, np.float32))
            owned = True
        payload, K = q.pad_f32(X)
        if payload is X and not owned:
            # an unpadded f32-contiguous batch passes through pad_f32
            # unchanged, and the caller's array may alias a REUSED ring
            # drain buffer — which jax's CPU backend can zero-copy
            # alias straight into the async dispatch, letting the next
            # drain overwrite an in-flight batch. The host path never
            # hits this (wire.encode always allocates); the fused path
            # must ship a private copy. (One memcpy per batch — the
            # same cost the ring drain itself pays.)
            payload = np.array(payload, copy=True)
        predict = q.predict_fused_padded
    else:
        # layout-aware staging: pad_wire routes the codes through the
        # scorer's adopted wire packing (compile/layouts.py WirePack)
        # when the kernel search chose one, so the staged payload,
        # h2d_bytes, and the donation accounting all see the packed
        # wire without any per-call-site knowledge
        payload, K = q.pad_wire(q.wire.encode(X, M))
        predict = q.predict_padded
    t1 = time.monotonic()
    spans.emit(
        "featurize", t0, t1 - t0, fused=fused,
        layout=getattr(q, "layout", "ref"),
    )
    # per-batch stage attribution (obs/attr.py): the same registry's
    # stage_seconds{stage=...} histograms merge fleet-wide like every
    # other metric; encode covers featurize+align, h2d the host-side
    # staging + async dispatch issue
    ledger = attr_mod.ledger_for(metrics)
    if enc is not None:
        enc.inc(t1 - t0)
    if ledger is not None:
        ledger.observe("encode", t1 - t0)
    if h2d is not None:
        h2d.inc(payload.nbytes)
    st_args = None
    if state is not None:
        # keyed state routing (host-side slot assignment; the state
        # gather/update itself is traced into the dispatch below) —
        # one vectorized pass per batch, zero per-record host work
        n_rec = np.asarray(X).shape[0]
        khash = (
            np.asarray(state_keys, np.uint32)
            if state_keys is not None
            else state.hash_keys(state.extract_keys(X))
        )
        offs = (
            np.asarray(offsets, np.int64) if offsets is not None
            else None
        )
        first = (
            int(offs[0]) if offs is not None and offs.size
            else state.applied_hi
        )
        state.maybe_renorm(first)
        slots, reset, rel, w = state.assign_slots(khash, offs)
        pad = payload.shape[0] - n_rec
        if pad > 0:
            # alignment rows ride the scratch slot with zero weight —
            # by construction they cannot touch any key's state
            slots = np.concatenate(
                [slots, np.full(pad, state.scratch, np.int32)]
            )
            reset = np.concatenate([reset, np.zeros(pad, bool)])
            rel = np.concatenate([rel, np.zeros(pad, np.float32)])
            w = np.concatenate([w, np.zeros(pad, np.float32)])
        st_args = (slots, rel, w, reset)
        predict_state = (
            q.predict_fused_padded_state if fused
            else q.predict_padded_state
        )
    if not donate:
        if st_args is None:
            out = predict(payload, K)  # async dispatch
        else:
            out, derived, S2 = predict_state(payload, K, state,
                                             *st_args)
            state.commit(S2)
            out = (out, derived)
        t2 = time.monotonic()
        spans.emit("h2d_dispatch", t1, t2 - t1, bytes=payload.nbytes)
        if ledger is not None:
            ledger.observe("h2d", t2 - t1)
        return out
    import jax

    if fused:
        filter_donate_warning(rf"float32\[\d+,{payload.shape[1]}\]")
    staged = jax.device_put(payload)  # async H2D staging copy
    if st_args is None:
        out = predict(staged, K, donate=True)
    else:
        # the state buffer donates alongside the batch: its update is
        # in-place on device (one [rows, 8] buffer in steady state)
        filter_donate_warning(r"float32\[\d+,8\]")
        if not fused:
            # the uint wire payload rides the same donated call and can
            # never output-alias its scores — the same inert warning
            # the block pipelines' uint-wire filter suppresses
            filter_donate_warning(
                rf"uint(?:8|16)\[\d+,{payload.shape[1]}\]"
            )
        out, derived, S2 = predict_state(staged, K, state, *st_args,
                                         donate=True)
        state.commit(S2)
        out = (out, derived)
    t2 = time.monotonic()
    spans.emit("h2d_dispatch", t1, t2 - t1, bytes=payload.nbytes)
    if ledger is not None:
        ledger.observe("h2d", t2 - t1)
    deleted = getattr(staged, "is_deleted", None)
    if deleted is not None and deleted() and donation_hits is not None:
        donation_hits.inc()
    return out


class _InFlight:
    """One launched dispatch: its (lazy) result + caller metadata.

    ``done`` means the entry left the window; ``error`` carries the
    fetch failure when it left poisoned — a later ``wait`` re-raises it
    instead of handing back a never-synchronized result."""

    __slots__ = ("out", "meta", "t_launch", "done", "error", "accounted")

    def __init__(self, out: Any, meta: Any, t_launch: float,
                 accounted: bool = True):
        self.out = out
        self.meta = meta
        self.t_launch = t_launch
        self.done = False
        self.error: Optional[BaseException] = None
        # False for shed no-op entries (no device work was launched):
        # the device-readback fault hook must not fire for them
        self.accounted = accounted


class OverlappedDispatcher:
    """Bounded FIFO window of in-flight async device dispatches.

    ``complete(out, meta)`` (optional) runs on the launching thread for
    every finished entry, in launch order — the block pipelines hang
    sink delivery + offset commit on it.  ``finish_oldest``/``wait``
    also *return* the finished entries for callers (the dynamic scorer)
    that prefer pull-style completion.
    """

    def __init__(
        self,
        depth: Optional[int] = 2,
        metrics: Optional[MetricsRegistry] = None,
        complete: Optional[Callable[[Any, Any], None]] = None,
        profiler: Optional["prof_mod.DeviceProfiler"] = None,
        on_error: Optional[Callable[[Any, Any, Exception], bool]] = None,
    ):
        # depth = dispatches allowed to REMAIN in flight after launch
        # returns; 0 = synchronous (each launch finishes its own batch —
        # the latency operating point, no completion window to hide in);
        # None = unbounded (launch NEVER blocks — for callers whose own
        # contract forbids blocking in submit, e.g. the dynamic scorer:
        # they still get prefetch, FIFO completion, and stall metrics,
        # and bound the window themselves via finish/wait)
        self._depth = None if depth is None else max(0, int(depth))
        self._window: "deque[_InFlight]" = deque()
        self._complete = complete
        # on_error(out, meta, exc) -> bool: called on the launching
        # thread when fetching an entry raised an *Exception* (never a
        # KeyboardInterrupt/SystemExit). True = handled — the error is
        # swallowed, the complete-callback is skipped, and the caller's
        # loop continues; False/None = re-raise as before. The block
        # pipelines hang record-level poison isolation (suspect-mode
        # bisection → DLQ) on this hook, so one bad record stops
        # killing the worker.
        self._on_error = on_error
        self._closed = False
        self.metrics = metrics or MetricsRegistry()
        self._stall = self.metrics.counter("h2d_stall_s")
        self._dispatches = self.metrics.counter("dispatches")
        # launches that found the window FULL and blocked (depth > 0
        # only: a depth-0 synchronous window finishes every batch by
        # design, which is the latency operating point, not saturation).
        # window_full_launches / dispatches over a tick interval is the
        # "window-full fraction" input to the composite backpressure
        # score (obs/pressure.py).
        self._window_full = self.metrics.counter("window_full_launches")
        self._gauge = self.metrics.gauge("inflight_depth")
        # attribution + sampled device profiling (obs/attr.py,
        # obs/profiler.py): the per-registry singletons, so every path
        # sharing this registry lands in one stage ledger / one set of
        # live roofline gauges
        self._ledger = attr_mod.ledger_for(self.metrics)
        self._profiler = (
            profiler if profiler is not None
            else prof_mod.profiler_for(self.metrics)
        )

    # -- introspection -----------------------------------------------------

    @property
    def profiling(self) -> bool:
        """True when launches should build a dispatch profile — a
        sampled device profiler is attached and not disabled, so call
        sites can skip the per-launch profile build entirely when
        FJT_PROF_SAMPLE is off."""
        p = self._profiler
        return p is not None and p.enabled

    def __len__(self) -> int:
        return len(self._window)

    @property
    def depth(self) -> Optional[int]:
        return self._depth

    @property
    def closed(self) -> bool:
        return self._closed

    # -- core --------------------------------------------------------------

    def launch(
        self,
        dispatch_fn: Callable[[], Any],
        meta: Any = None,
        profile: Optional[dict] = None,
        accounted: bool = True,
    ) -> _InFlight:
        """Dispatch asynchronously and admit the result to the window.

        ``dispatch_fn()`` must *dispatch* device work and return without
        blocking on it (the JAX async-dispatch contract).  If admitting
        the new entry overflows ``depth``, the oldest entry is finished
        first — the only place a healthy steady state ever blocks; the
        ledger books that wait as ``queue_wait`` (a ready batch waiting
        for a window slot) rather than ``readback``.

        ``profile`` (see :func:`obs.attr.dispatch_profile`) opts this
        launch into the sampled device-timing pool: when the profiler's
        rate limiter fires, the window is drained and the *post-dispatch*
        wait is bracketed with ``block_until_ready`` — dispatch_fn's own
        host time (featurize/staging) is excluded, so the delta is pure
        device execution, feeding the live
        ``device_mfu``/``device_membw_util`` gauges and the kernel cost
        ledger. Unsampled launches pay one predicate check.

        ``accounted=False`` keeps this entry out of the ``dispatches``
        and window-full counters: the admission controller's SHED
        no-ops ride the window only for FIFO offset commits — counting
        them as dispatches would dilute the pressure monitor's
        window-full fraction (real-dispatch denominator) exactly while
        the shed rate is highest, flapping the gate open mid-overload.
        """
        if self._closed:
            raise DispatcherClosed("launch() on a closed dispatcher")
        # device-dispatch delay + launch-time device-fault injection
        # (runtime/faults.py): each a global load + None check when no
        # faults are configured. A device fault raised HERE propagates
        # out of launch to the caller's direct-dispatch handler —
        # classified by runtime/devfault.py, never quarantined as
        # record poison
        faults.fire("dispatch")
        faults.fire("device_dispatch")
        prof = self._profiler
        sampling = (
            prof is not None
            and profile is not None
            and prof.should_sample()
        )
        if sampling:
            t_pre = time.monotonic()
            # drain so the bracket times THIS dispatch, not the tail of
            # whatever the device was already running (entries stay in
            # the window: FIFO completion/callbacks are untouched)
            try:
                for h in self._window:
                    _block_ready(h.out)
            except Exception:
                # a poisoned in-flight batch: its error belongs to
                # finish_oldest (right meta, right caller) — this
                # launch just forfeits its sample
                sampling = False
        if sampling:
            t_drained = time.monotonic()
            out = dispatch_fn()
            # bracket only the post-dispatch wait: dispatch_fn's host
            # work (featurize/staging on the host-encode path) happens
            # BEFORE the device kernel is queued, so folding it in
            # would book host time as device time — inflating
            # device_ns_per_record, poisoning the kernel cost ledger,
            # and double-booking the interval dispatch_quantized
            # already attributed to encode/h2d
            t_disp = time.monotonic()
            try:
                _block_ready(out)
            except Exception:
                pass  # the finish path re-raises with attribution
            else:
                t1 = time.monotonic()
                # overhead = drain + bracket wait; dispatch_fn's own
                # host time is work the caller pays regardless, so it
                # must not eat the sampling budget
                prof.record_sample(
                    t1 - t_disp,
                    profile,
                    overhead_s=(t_drained - t_pre) + (t1 - t_disp),
                )
        else:
            out = dispatch_fn()
        _prefetch_host(out)
        handle = _InFlight(out, meta, time.monotonic(), accounted=accounted)
        self._window.append(handle)
        if accounted:
            self._dispatches.inc()
        if (
            accounted
            and self._depth is not None
            and self._depth > 0
            and len(self._window) > self._depth
            # a healthy overlapped pipeline's steady state is a window
            # trimmed to exactly depth, so overshoot alone is not
            # saturation — count only launches whose oldest entry is
            # still in flight, i.e. the trim below will actually block
            and not _is_ready(self._window[0].out)
        ):
            self._window_full.inc()
        while self._depth is not None and len(self._window) > self._depth:
            # depth 0 (the latency operating point) has no window for a
            # ready batch to wait in: this wait is the host blocking on
            # its OWN just-dispatched batch, i.e. readback — booking it
            # as queue_wait would tell the operator "window too shallow"
            # (and fire stage_stall events) on every batch of a normal
            # synchronous pipeline
            self.finish_oldest(
                _stage="queue_wait" if self._depth > 0 else "readback"
            )
        # gauge records post-enforcement depth: the window's steady
        # occupancy, not the transient overshoot inside this call
        self._gauge.set(len(self._window))
        return handle

    def finish_oldest(self, _stage: str = "readback"):
        """Finish (wait + complete-callback) the oldest in-flight entry.

        → ``(out, meta)`` or None when the window is empty.  Safe to
        call from pipeline hooks while a batch is held. ``_stage`` is
        the attribution bucket for the blocking wait — ``launch`` books
        its overflow waits as ``queue_wait`` so one wall-clock interval
        is never attributed to two stages."""
        if not self._window:
            return None
        handle = self._window[0]
        depth = len(self._window)
        t0 = time.monotonic()
        error: Optional[BaseException] = None
        try:
            # readback-time device-fault injection: raises inside the
            # same try as the real fetch, so an injected device error
            # takes exactly the real error path (handle.error +
            # on_error classification); shed no-ops (accounted=False)
            # launched no device work and are skipped
            if handle.accounted:
                faults.fire("device_readback")
            _block_ready(handle.out)
        except BaseException as e:
            handle.error = e  # wait() on this handle re-raises, never
            # returns the unsynchronized result as if it completed
            error = e
        finally:
            # stall time counts even when the wait raised: the host WAS
            # gated on the device for that long either way
            dt = time.monotonic() - t0
            self._stall.inc(dt)
            # the in-flight window on the trace: how long the host sat
            # on the oldest dispatch, and how deep the window was
            spans.emit("readback", t0, dt, inflight=depth)
            if self._ledger is not None:
                # ONLY the blocking wait is booked, and under the
                # caller's stage — launch's overflow loop passes
                # queue_wait, every other caller is a readback; the
                # complete-callback below books its own time (sink),
                # so one wall-clock interval never lands in two stages
                self._ledger.observe(_stage, dt)
            # the entry leaves the window regardless — a poisoned batch
            # must not wedge every later flush
            self._window.popleft()
            handle.done = True
            self._gauge.set(len(self._window))
        if error is not None:
            if (
                self._on_error is not None
                and isinstance(error, Exception)
                and self._on_error(handle.out, handle.meta, error)
            ):
                # handled (e.g. isolated to the DLQ): no complete
                # callback — the handler owns delivery + commit
                return None
            raise error
        if self._complete is not None:
            self._complete(handle.out, handle.meta)
        return handle.out, handle.meta

    def wait(self, handle: _InFlight) -> Any:
        """Finish entries in FIFO order until ``handle`` is done; → its
        (fetched) result.  A handle already finished returns at once; a
        handle whose fetch FAILED re-raises its error on every wait.
        The synchronized-or-raise guarantee holds even for a handle the
        window no longer tracks (e.g. dropped by :meth:`abandon`): it is
        fetched directly rather than handed back unsynchronized."""
        while not handle.done and self._window:
            self.finish_oldest()
        if not handle.done:
            t0 = time.monotonic()
            try:
                if handle.accounted:
                    faults.fire("device_readback")
                _block_ready(handle.out)
            except BaseException as e:
                handle.error = e
                raise
            finally:
                dt = time.monotonic() - t0
                self._stall.inc(dt)
                if self._ledger is not None:
                    self._ledger.observe("readback", dt)
                handle.done = True
        if handle.error is not None:
            raise handle.error
        return handle.out

    def flush(self) -> None:
        """Finish everything in flight (the drain-on-close protocol)."""
        while self._window:
            self.finish_oldest()

    def abandon(self) -> int:
        """Drop all in-flight entries without fetching; → count dropped.

        The block pipelines' bounded give-up: abandoned batches simply
        replay from the committed offset on restore (at-least-once)."""
        n = len(self._window)
        self._window.clear()
        self._gauge.set(0)
        if n:  # a give-up is exactly what a postmortem wants to see
            flight.record("dispatch_abandon", dropped=n)
        return n

    def close(self, drain: bool = True) -> None:
        """Shut the window down: flush (default) or abandon, then
        refuse further launches.  Idempotent."""
        if drain:
            self.flush()
        else:
            self.abandon()
        self._closed = True
