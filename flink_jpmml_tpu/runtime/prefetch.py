"""Pipelined ingest: the prefetch/decode sidecar feeding the ring.

BENCH_r05's production-shaped path (``kafka_mode``) ran at half the
hand loop — 545k rec/s vs 1.09M — because the pipelines' ingest thread
runs fetch RPC and wire decode *serially*: while a fetch long-polls the
broker nothing decodes, and while a batch decodes no fetch is in
flight. The PR 6 stage ledger names those as the stolen milliseconds
(``stage_seconds{stage="fetch"/"decode"}``), so the fix is the classic
input-pipeline discipline from the TPU compilation literature: overlap
host ingest with everything downstream so the accelerator never waits
on the network.

This module adds exactly one pipeline stage: a **sidecar thread** per
source that runs the source's own ``poll()`` loop — fetch, decode,
freshness stamps, DLQ routing, journey ingest hops, all of it, on the
source's existing code paths — and hands finished batches to the
consumer through a **bounded handoff queue**. The pipelines' ingest
thread then only pops a decoded block and memcpys it into the ring,
so fetch N+1 overlaps decode N overlaps ring-push/score N−1. The
sidecar is a PERFORMANCE change, not a semantics change:

- **ordering** — one sidecar per source, a FIFO queue: records emerge
  in exactly the order the source produced them;
- **seek / restore** — pauses the sidecar at a poll boundary, seeks
  the inner source, discards queued batches, resumes (the engine's
  checkpoint hooks proxy through untouched);
- **reconnect** — lives where it always did, inside the source's
  fetch path (backoff, ``kafka_reconnect`` flight events); the
  sidecar just sees an empty poll;
- **errors** — a sidecar exception (e.g. the fail-fast
  ``KafkaPartitionError``) is stashed and re-raised from the
  consumer's ``poll()``, so the pipeline dies exactly as it would
  have single-threaded;
- **shutdown** — ``stop_prefetch()`` parks and joins the sidecar;
  the pipelines call it from ``stop()``.

Telemetry (all on the shared registry, catalogued in
docs/operations.md): ``prefetch_depth`` / ``prefetch_occupancy``
gauges (queue fill; high-water in the gauge's ``_max``),
``prefetch_batches`` / ``prefetch_records`` counters,
``prefetch_stall_s`` (consumer waited on an EMPTY queue — ingest is
the bottleneck; also observed as the ``prefetch_wait`` stage so
``fjt-top`` ranks it against fetch/decode) and ``prefetch_block_s``
(sidecar blocked on a FULL queue — downstream is the bottleneck,
i.e. backpressure, which also feeds the PR 7 ``PressureMonitor``'s
``pressure_prefetch`` component through the occupancy peak-hold).

Knobs: ``FJT_PREFETCH_DEPTH`` (handoff queue depth in batches,
default 4), ``FJT_PREFETCH_DISABLE`` (operational kill switch — wins
over any explicit enable).
"""

from __future__ import annotations

import collections
import os
import threading
import time
from contextlib import contextmanager
from typing import Optional

from flink_jpmml_tpu.obs import attr as attr_mod
from flink_jpmml_tpu.obs import pressure as pressure_mod

ENV_DEPTH = "FJT_PREFETCH_DEPTH"
ENV_DISABLE = "FJT_PREFETCH_DISABLE"
DEFAULT_DEPTH = 4

# consumer-side bounded wait for a first batch: long enough to skip
# the caller's sleep-and-retry loop in the common case, short enough
# that control-plane work (stop flags, checkpoint ticks) stays live
_POLL_WAIT_S = 0.005


def env_depth() -> int:
    try:
        d = int(os.environ.get(ENV_DEPTH) or DEFAULT_DEPTH)
    except ValueError:
        return DEFAULT_DEPTH
    return max(1, d)


def env_disabled() -> bool:
    return bool(os.environ.get(ENV_DISABLE))


class _PrefetchedSourceBase:
    """Shared sidecar machinery; subclasses say what one inner poll
    yields (a block tuple / a record batch list) and how many records
    it carried. All queue state is guarded by one condition — the
    depths are single digits, contention is not a concern."""

    _THREAD_NAME = "fjt-prefetch"

    def __init__(self, inner, depth: Optional[int] = None, metrics=None):
        self._inner = inner
        self._depth = max(1, int(depth)) if depth else env_depth()
        self._q: "collections.deque" = collections.deque()
        self._cv = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        self._paused = False
        self._busy = False  # sidecar inside inner.poll right now
        self._eos = False
        self._exc: Optional[BaseException] = None
        self._metrics = metrics
        if metrics is not None:
            self._g_depth = metrics.gauge("prefetch_depth")
            self._g_occ = metrics.gauge("prefetch_occupancy")
            self._c_batches = metrics.counter("prefetch_batches")
            self._c_records = metrics.counter("prefetch_records")
            self._c_stall = metrics.counter("prefetch_stall_s")
            self._c_block = metrics.counter("prefetch_block_s")
            self._ledger = attr_mod.ledger_for(metrics)
            self._monitor = pressure_mod.pressure_for(metrics)
        else:
            self._g_depth = self._g_occ = None
            self._c_batches = self._c_records = None
            self._c_stall = self._c_block = None
            self._ledger = self._monitor = None

    # marks the wrapper so maybe_wrap_* never double-wraps
    prefetch_wrapped = True

    # -- subclass hooks ----------------------------------------------------

    def _poll_inner(self):
        """→ one handoff item or None (nothing available)."""
        raise NotImplementedError

    def _item_records(self, item) -> int:
        raise NotImplementedError

    # -- sidecar -----------------------------------------------------------

    def _ensure_started(self) -> None:
        t = self._thread
        if t is not None and t.is_alive():
            return
        with self._cv:
            t = self._thread
            if (
                self._stopped
                or self._exc is not None  # sticky until a seek resets
                or (t is not None and t.is_alive())
            ):
                return
            # t is None (first poll) or a dead sidecar whose error a
            # seek/restore cleared: spawn fresh against the re-seeked
            # inner source
            nt = threading.Thread(
                target=self._loop, name=self._THREAD_NAME, daemon=True
            )
            self._thread = nt
            nt.start()

    def _loop(self) -> None:
        while True:
            napping = False
            with self._cv:
                while not self._stopped and (
                    self._paused
                    or self._eos
                    or len(self._q) >= self._depth
                ):
                    was_full = len(self._q) >= self._depth
                    t0 = time.monotonic()
                    self._cv.wait(0.05)
                    if was_full and self._c_block is not None:
                        # backpressure: downstream (ring/score) is the
                        # bottleneck while this accrues
                        self._c_block.inc(time.monotonic() - t0)
                if self._stopped:
                    self._busy = False
                    self._cv.notify_all()
                    return
                self._busy = True
            try:
                # the inner source's OWN poll: fetch + decode +
                # freshness stamps + DLQ routing + journey hops all run
                # here, off the consumer thread, on unchanged code paths
                item = self._poll_inner()
            except BaseException as e:
                with self._cv:
                    self._exc = e  # sticky: re-raised from every poll()
                    self._busy = False
                    self._cv.notify_all()
                return
            with self._cv:
                self._busy = False
                if item is not None:
                    self._q.append(item)
                    self._note_queue(pushed=item)
                elif self._inner.exhausted:
                    self._eos = True  # parked; a seek() un-parks
                else:
                    napping = True
                self._cv.notify_all()
            if napping:
                time.sleep(0.0005)  # starved source (cf. _ingest loops)

    def _note_queue(self, pushed=None) -> None:
        """Gauge/counter updates; callers hold the condition lock."""
        if self._g_depth is None:
            return
        n = len(self._q)
        occ = min(n / self._depth, 1.0)
        self._g_depth.set(float(n))
        self._g_occ.set(occ)
        if pushed is not None:
            self._c_batches.inc()
            self._c_records.inc(self._item_records(pushed))
            if self._monitor is not None:
                # peak-hold, like the ring's pre-drain note: the tick
                # must see the worst fill between scrapes, not whatever
                # instant the gauge happens to read
                self._monitor.note_prefetch(occ)

    def _take(self):
        """→ (item | None, waited_s). Bounded wait on an empty queue;
        sticky sidecar errors re-raise here."""
        self._ensure_started()
        t0 = None
        while True:
            with self._cv:
                if self._q:
                    item = self._q.popleft()
                    self._note_queue()
                    self._cv.notify_all()
                    break
                if self._exc is not None:
                    raise self._exc
                if self._eos or self._stopped:
                    return None, 0.0
                now = time.monotonic()
                if t0 is None:
                    t0 = now
                remaining = t0 + _POLL_WAIT_S - now
                if remaining <= 0:
                    return None, now - t0
                self._cv.wait(remaining)
        waited = 0.0 if t0 is None else time.monotonic() - t0
        return item, waited

    def _account_wait(self, waited: float) -> None:
        if waited <= 0.0:
            return
        if self._c_stall is not None:
            self._c_stall.inc(waited)
        if self._ledger is not None:
            # the hot path's residual ingest cost once fetch/decode
            # moved off-thread — ranked by fjt-top next to them
            self._ledger.observe("prefetch_wait", waited)

    # -- lifecycle / source protocol --------------------------------------

    @contextmanager
    def _pause(self):
        """Park the sidecar at a poll boundary; the body may then
        mutate the inner source and the queue safely. The epilogue
        ALWAYS runs — stale pre-seek batches are discarded even when
        the sidecar already died (review finding, pinned: a dead
        sidecar's queue used to survive a seek), and a deliberate
        seek/restore is a retry: it drops a dead sidecar's sticky
        error so the next poll spawns a fresh one against the
        re-seeked inner source."""
        t = self._thread
        if t is not None and t.is_alive():
            with self._cv:
                self._paused = True
                self._cv.notify_all()
                while self._busy:
                    self._cv.wait(0.05)
        try:
            yield
        finally:
            with self._cv:
                self._q.clear()
                self._eos = False
                if not self._stopped and self._exc is not None:
                    self._exc = None
                    if (
                        self._thread is not None
                        and not self._thread.is_alive()
                    ):
                        self._thread = None
                self._note_queue()
                self._paused = False
                self._cv.notify_all()

    def seek(self, offset: int) -> None:
        # in-flight prefetched batches are PRE-seek data: discard them
        # with the pause epilogue, never hand them across the seek
        with self._pause():
            self._inner.seek(offset)

    def stop_prefetch(self, join_timeout: float = 2.0) -> None:
        t = self._thread
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        if t is not None and t.is_alive():
            t.join(join_timeout)

    def close(self) -> None:
        # join BEFORE closing the socket: a sidecar mid-fetch on a
        # closed client would ride the reconnect path for nothing
        self.stop_prefetch()
        self._inner.close()

    @property
    def exhausted(self) -> bool:
        if self._thread is None:
            return self._inner.exhausted
        with self._cv:
            return self._eos and not self._q and self._exc is None

    def __getattr__(self, name):
        # checkpoint hooks (checkpoint_state/restore_state), event_time
        # extractors, test probes: resolve against the inner source so
        # optional-protocol getattr() probes see exactly what the inner
        # source offers
        inner_attr = getattr(self._inner, name)
        if name == "restore_state":
            def _restore(state, _inner_restore=inner_attr):
                with self._pause():
                    return _inner_restore(state)

            return _restore
        return inner_attr


class PrefetchedBlockSource(_PrefetchedSourceBase):
    """BlockSource wrapper: the sidecar runs ``inner.poll()`` →
    ``(first_offset, rows)`` blocks through the handoff queue."""

    _THREAD_NAME = "fjt-prefetch-blk"

    def _poll_inner(self):
        return self._inner.poll()

    def _item_records(self, item) -> int:
        return int(item[1].shape[0])

    def poll(self):
        item, waited = self._take()
        self._account_wait(waited)
        return item


class PrefetchedRecordSource(_PrefetchedSourceBase):
    """Record ``Source`` wrapper (engine.Pipeline's shape): the sidecar
    polls fixed-size chunks; the consumer re-chunks to its ``max_n``
    through a consumer-thread-only pending deque."""

    _THREAD_NAME = "fjt-prefetch-rec"

    def __init__(self, inner, depth=None, metrics=None, chunk: int = 1024):
        super().__init__(inner, depth=depth, metrics=metrics)
        self._chunk = max(1, int(chunk))
        self._pending: "collections.deque" = collections.deque()

    @property
    def event_time_fn(self):
        return getattr(self._inner, "event_time_fn", None)

    def _poll_inner(self):
        polled = self._inner.poll(self._chunk)
        return polled if polled else None

    def _item_records(self, item) -> int:
        return len(item)

    def poll(self, max_n: int):
        out = list(self._pending)
        if out:
            self._pending.clear()
        waited = 0.0
        while len(out) < max_n:
            item, w = self._take()
            waited += w
            if item is None:
                break
            out.extend(item)
        self._account_wait(waited)
        if len(out) > max_n:
            self._pending.extend(out[max_n:])
            del out[max_n:]
        return out

    def seek(self, offset: int) -> None:
        self._pending.clear()
        super().seek(offset)

    @property
    def exhausted(self) -> bool:
        if self._pending:
            return False
        return super().exhausted


def _resolve(source, enable: Optional[bool]) -> bool:
    if env_disabled():
        return False  # the operational kill switch wins over everything
    if enable is None:
        return bool(getattr(source, "prefetchable", False))
    return bool(enable)


def maybe_wrap_block(
    source, metrics=None, enable: Optional[bool] = None,
    depth: Optional[int] = None,
):
    """→ ``source`` wrapped in a :class:`PrefetchedBlockSource` when
    pipelined ingest applies (``enable`` True, or None = auto: the
    source marked itself ``prefetchable``), else ``source`` unchanged.
    ``FJT_PREFETCH_DISABLE`` force-disables either way."""
    if getattr(source, "prefetch_wrapped", False) or not _resolve(
        source, enable
    ):
        return source
    return PrefetchedBlockSource(source, depth=depth, metrics=metrics)


def maybe_wrap_chips(
    sources: dict, metrics=None, enable: Optional[bool] = None,
    depth: Optional[int] = None,
) -> dict:
    """Per-chip prefetch wrap for the mesh ingest split (one kafka
    source per chip — runtime/kafka.chip_block_sources): each chip's
    source gets its OWN sidecar, chip-tagged in the thread name, so a
    stalled partition set shows up in thread dumps as the chip it
    starves and never blocks another chip's fetch loop. Same
    auto/enable/kill-switch rules as :func:`maybe_wrap_block`."""
    out = {}
    for chip, src in sources.items():
        w = maybe_wrap_block(src, metrics=metrics, enable=enable, depth=depth)
        if w is not src:
            w._THREAD_NAME = f"fjt-prefetch-blk-c{chip}"
        out[chip] = w
    return out


def maybe_wrap_records(
    source, metrics=None, enable: Optional[bool] = None,
    depth: Optional[int] = None,
):
    """Record-source twin of :func:`maybe_wrap_block` (engine.Pipeline's
    consumption site)."""
    if getattr(source, "prefetch_wrapped", False) or not _resolve(
        source, enable
    ):
        return source
    return PrefetchedRecordSource(source, depth=depth, metrics=metrics)
